"""SegmentEngine: durable graph engine over the native C++ segment store.

Behavioral reference: the reference's BadgerEngine
(/root/reference/pkg/storage/badger.go:67 — LSM KV with single-byte key
prefixes 0x01-0x08 for nodes/edges/indexes incl. prefixPendingEmbed 0x07).
Here the KV is native/segstore.cc (append-only segments, CRC records,
tombstones, compaction — payload bytes stay in C++ during recovery scans
and compaction). Key prefixes mirror the reference:

    n:<id>  node JSON          e:<id>  edge JSON          p:<id>  pending-embed

Secondary indexes (labels, types, adjacency) are rebuilt in memory on open
by a single native key scan + value reads, like Badger's prefix iterations.
Compaction triggers at tombstone_ratio like the HNSW/corpus rebuild policy.

At-rest encryption (ref: db.go:781-809 — the reference hands a PBKDF2-derived
key to Badger's built-in encryption): values are AES-256-GCM sealed with the
key id as AAD before they reach the native store; keys stay plaintext so the
native prefix scans keep working. Salt lives in seg.salt; a sentinel record
(m:chk) rejects wrong passphrases at open."""

from __future__ import annotations

import ctypes
import json
import logging
import os
import threading
from typing import Iterable, Iterator, Optional

from nornicdb_tpu.errors import AlreadyExistsError, NornicError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Engine, Node

log = logging.getLogger(__name__)

# NORNICDB_NATIVE_DIR overrides for installed deployments (Docker image
# places prebuilt .so files outside the source tree)
_NATIVE_DIR = os.environ.get("NORNICDB_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsegstore.so")

_lib = None
_lib_lock = threading.Lock()


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_NATIVE_DIR, "segstore.cc")
        stale = not os.path.exists(_LIB_PATH) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
        )
        if stale:
            import subprocess

            try:
                # deliberate subprocess under the module load lock: the
                # build-once gate runs a single time per process at first
                # open(); engine locks are never held around _load_lib()
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,  # nornlint: disable=NL-LK02
                               capture_output=True, timeout=120)
            except (subprocess.SubprocessError, OSError) as e:
                if not os.path.exists(_LIB_PATH):
                    log.warning("segstore native build failed: %s", e)
                    return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.seg_open.restype = ctypes.c_void_p
        lib.seg_open.argtypes = [ctypes.c_char_p]
        lib.seg_close.argtypes = [ctypes.c_void_p]
        lib.seg_put.restype = ctypes.c_int32
        lib.seg_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32]
        lib.seg_get.restype = ctypes.c_int64
        lib.seg_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
                                ctypes.c_uint64]
        lib.seg_delete.restype = ctypes.c_int32
        lib.seg_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint32]
        lib.seg_count.restype = ctypes.c_uint64
        lib.seg_count.argtypes = [ctypes.c_void_p]
        lib.seg_tombstones.restype = ctypes.c_uint64
        lib.seg_tombstones.argtypes = [ctypes.c_void_p]
        lib.seg_keys.restype = ctypes.c_int64
        lib.seg_keys.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint8),
                                 ctypes.c_uint64]
        lib.seg_compact.restype = ctypes.c_int32
        lib.seg_compact.argtypes = [ctypes.c_void_p]
        lib.seg_set_sync.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        _lib = lib
        return _lib


def segment_store_available() -> bool:
    return _load_lib() is not None


class _SegKV:
    """Thin ctypes wrapper over one segment store handle."""

    def __init__(self, path: str, sync: bool = False):
        lib = _load_lib()
        if lib is None:
            raise NornicError("native segment store unavailable (g++ missing?)")
        self._lib = lib
        self._h = lib.seg_open(path.encode())
        if not self._h:
            raise NornicError(f"failed to open segment store at {path}")
        if sync:
            lib.seg_set_sync(self._h, 1)

    def put(self, key: bytes, value: bytes) -> None:
        if self._lib.seg_put(self._h, key, len(key), value, len(value)) != 0:
            raise NornicError("segment store write failed")

    _GET_CAP = 4096

    def get(self, key: bytes) -> Optional[bytes]:
        cap = self._GET_CAP
        while True:
            buf = (ctypes.c_uint8 * cap)()
            n = self._lib.seg_get(self._h, key, len(key), buf, cap)
            if n == -1:
                return None
            if n == -2:
                raise NornicError("segment store read failed")
            if n < -2:  # -(len)-2: grow and retry (atomic per attempt)
                cap = -int(n) - 2
                continue
            return bytes(buf[: int(n)])

    def delete(self, key: bytes) -> bool:
        return self._lib.seg_delete(self._h, key, len(key)) == 0

    def count(self) -> int:
        return int(self._lib.seg_count(self._h))

    def tombstones(self) -> int:
        return int(self._lib.seg_tombstones(self._h))

    def keys(self, prefix: bytes = b"") -> list[bytes]:
        import struct as _struct

        cap = 1 << 16
        while True:
            buf = (ctypes.c_uint8 * cap)()
            n = self._lib.seg_keys(self._h, prefix, len(prefix), buf, cap)
            if n < 0:
                cap = -int(n)
                continue
            raw = bytes(buf[: int(n)])
            out = []
            off = 0
            while off + 4 <= len(raw):  # [u32 klen][key] — any byte is legal
                (klen,) = _struct.unpack_from("<I", raw, off)
                off += 4
                out.append(raw[off : off + klen])
                off += klen
            return out

    def compact(self) -> None:
        rc = self._lib.seg_compact(self._h)
        if rc == -3:
            return  # another thread's compaction is already running
        if rc != 0:
            raise NornicError("segment store compaction failed")

    def close(self) -> None:
        if self._h:
            self._lib.seg_close(self._h)
            self._h = None


class _EncKV:
    """Value-encrypting view over _SegKV: AES-256-GCM with the record key as
    AAD (so a ciphertext can't be replayed under a different key). Keys are
    left plaintext — native prefix scans and compaction never see plaintext
    values (ref: Badger's value-only encryption, db.go:781-809)."""

    def __init__(self, kv: "_SegKV", enc) -> None:
        self._kv = kv
        self._enc = enc

    def put(self, key: bytes, value: bytes) -> None:
        self._kv.put(key, self._enc.encrypt(value, aad=key))

    def get(self, key: bytes) -> Optional[bytes]:
        raw = self._kv.get(key)
        if raw is None:
            return None
        try:
            return self._enc.decrypt(raw, aad=key)
        except Exception as e:
            raise NornicError(
                f"segment store decrypt failed for {key!r} (wrong passphrase "
                f"or corrupted data): {e}"
            ) from e

    def __getattr__(self, name: str):
        return getattr(self._kv, name)


class SegmentEngine(Engine):
    """(ref: BadgerEngine badger.go:67 — the durable engine role)"""

    COMPACT_RATIO = 0.5

    _CHK_KEY = b"m:chk"
    _CHK_PLAINTEXT = b"nornicdb-segment"

    def __init__(self, data_dir: str, sync: bool = False,
                 passphrase: Optional[str] = None,
                 auto_compact_interval: float = 30.0):
        super().__init__()
        os.makedirs(data_dir, exist_ok=True)
        self._kv = _SegKV(os.path.join(data_dir, "graph.seg"), sync=sync)
        salt_path = os.path.join(data_dir, "seg.salt")
        try:
            if passphrase:
                from nornicdb_tpu.encryption import (
                    Encryptor,
                    load_or_create_salt,
                )

                chk = self._kv.get(self._CHK_KEY)
                if chk is None and self._kv.count() > 0:
                    # existing plaintext store: refuse BEFORE persisting a
                    # salt/sentinel, or the intact data becomes unreachable
                    # under both open modes
                    raise NornicError(
                        "segment store at %r holds unencrypted data; "
                        "encrypting an existing store in place is not "
                        "supported (export, then re-import into a store "
                        "created with the passphrase)" % data_dir
                    )
                salt = load_or_create_salt(salt_path)
                enc = Encryptor.from_passphrase(passphrase, salt)
                if chk is None:
                    self._kv.put(
                        self._CHK_KEY,
                        enc.encrypt(self._CHK_PLAINTEXT, aad=self._CHK_KEY))
                else:
                    try:
                        ok = (enc.decrypt(chk, aad=self._CHK_KEY)
                              == self._CHK_PLAINTEXT)
                    except Exception:
                        # expected on a wrong passphrase (AEAD tag mismatch)
                        # but the trace distinguishes that from a corrupt
                        # check blob when operators debug an unopenable store
                        log.debug("passphrase check decrypt failed",
                                  exc_info=True)
                        ok = False
                    if not ok:
                        raise NornicError(
                            "segment store: wrong encryption passphrase"
                        )
                self._kv = _EncKV(self._kv, enc)
            elif os.path.exists(salt_path):
                raise NornicError(
                    "segment store at %r is encrypted; an "
                    "encryption_passphrase is required to open it" % data_dir
                )
        except BaseException:
            self._kv.close()
            raise
        self._lock = threading.RLock()
        # in-memory secondary indexes (ref: Badger prefix scans)
        self._by_label: dict[str, set[str]] = {}
        self._by_type: dict[str, set[str]] = {}
        self._out: dict[str, set[str]] = {}
        self._in: dict[str, set[str]] = {}
        self._node_count = 0
        self._edge_count = 0
        try:
            self._rebuild_indexes()
        except BaseException:
            # a corrupted record surfacing here must not leak the native
            # handle/fd (callers may retry open in a loop)
            self._kv.close()
            raise
        # GC: every mutation path ratio-checks inline (_maybe_compact at
        # the create/update/delete sites), which covers steady state; a
        # post-recovery pass collects garbage a previous run left behind;
        # and a background thread (the role of Badger's value-log GC
        # ticker, pkg/storage/badger.go:67) sweeps periodically. The
        # native compaction is two-phase/online, so the sweep blocks
        # readers only for the write-delta replay.
        self._maybe_compact()
        self._compact_stop = threading.Event()
        self._compact_thread: Optional[threading.Thread] = None
        if auto_compact_interval > 0:
            self._compact_thread = threading.Thread(
                target=self._compact_loop, args=(auto_compact_interval,),
                daemon=True, name="seg-compact",
            )
            self._compact_thread.start()

    def _compact_loop(self, interval: float) -> None:
        while not self._compact_stop.wait(interval):
            try:
                # ratio check without the engine lock; the native two-phase
                # compaction serializes against writers itself
                if (self._kv.tombstones() / max(self._kv.count(), 1)
                        > self.COMPACT_RATIO):
                    self._kv.compact()
            except Exception:
                # storage may be mid-close; the next tick retries
                log.warning("background segment compaction failed",
                            exc_info=True)

    # -- recovery ------------------------------------------------------------
    def _rebuild_indexes(self) -> None:
        for key in self._kv.keys(b"n:"):
            raw = self._kv.get(key)
            if raw is None:
                continue
            node = Node.from_dict(json.loads(raw))
            for lbl in node.labels:
                self._by_label.setdefault(lbl, set()).add(node.id)
            self._node_count += 1
        for key in self._kv.keys(b"e:"):
            raw = self._kv.get(key)
            if raw is None:
                continue
            edge = Edge.from_dict(json.loads(raw))
            self._by_type.setdefault(edge.type, set()).add(edge.id)
            self._out.setdefault(edge.start_node, set()).add(edge.id)
            self._in.setdefault(edge.end_node, set()).add(edge.id)
            self._edge_count += 1

    def _maybe_compact(self) -> None:
        # no `live and` guard: a store whose every record was deleted
        # (live == 0, tombstones > 0) is exactly the one most worth
        # compacting — the old guard let that garbage grow unbounded
        if self._kv.tombstones() / max(self._kv.count(), 1) > self.COMPACT_RATIO:
            self._kv.compact()

    # -- nodes ----------------------------------------------------------------
    @staticmethod
    def _nk(node_id: str) -> bytes:
        return b"n:" + node_id.encode()

    @staticmethod
    def _ek(edge_id: str) -> bytes:
        return b"e:" + edge_id.encode()

    def create_node(self, node: Node) -> Node:
        with self._lock:
            key = self._nk(node.id)
            if self._kv.get(key) is not None:
                raise AlreadyExistsError(f"node {node.id} already exists")
            stored = node.copy()
            self._kv.put(key, json.dumps(stored.to_dict()).encode())
            for lbl in stored.labels:
                self._by_label.setdefault(lbl, set()).add(stored.id)
            self._node_count += 1
        self._emit("node_created", stored.copy())
        return stored.copy()

    def get_node(self, node_id: str) -> Node:
        raw = self._kv.get(self._nk(node_id))
        if raw is None:
            raise NotFoundError(f"node {node_id} not found")
        return Node.from_dict(json.loads(raw))

    def update_node(self, node: Node) -> Node:
        with self._lock:
            old = self.get_node(node.id)  # raises if absent
            import time as _time

            stored = node.copy()
            stored.created_at = old.created_at
            stored.updated_at = _time.time()
            for lbl in old.labels:
                self._by_label.get(lbl, set()).discard(old.id)
            for lbl in stored.labels:
                self._by_label.setdefault(lbl, set()).add(stored.id)
            self._kv.put(self._nk(node.id), json.dumps(stored.to_dict()).encode())
            self._maybe_compact()  # overwrites count as garbage too
        self._emit("node_updated", stored.copy())
        return stored.copy()

    def delete_node(self, node_id: str) -> None:
        with self._lock:
            node = self.get_node(node_id)
            attached = list(
                self._out.get(node_id, set()) | self._in.get(node_id, set())
            )
            removed_edges = []
            for eid in attached:
                raw = self._kv.get(self._ek(eid))
                if raw is None:
                    continue
                edge = Edge.from_dict(json.loads(raw))
                self._kv.delete(self._ek(eid))
                self._by_type.get(edge.type, set()).discard(eid)
                self._out.get(edge.start_node, set()).discard(eid)
                self._in.get(edge.end_node, set()).discard(eid)
                self._edge_count -= 1
                removed_edges.append(edge)
            self._kv.delete(self._nk(node_id))
            self._kv.delete(b"p:" + node_id.encode())
            for lbl in node.labels:
                self._by_label.get(lbl, set()).discard(node_id)
            self._node_count -= 1
            self._maybe_compact()
        for e in removed_edges:
            self._emit("edge_deleted", e)
        self._emit("node_deleted", node)

    def get_nodes_by_label(self, label: str) -> list[Node]:
        with self._lock:
            ids = sorted(self._by_label.get(label, set()))
        out = []
        for i in ids:
            try:
                out.append(self.get_node(i))
            except NotFoundError:
                pass
        return out

    def all_nodes(self) -> Iterator[Node]:
        # snapshot at call time (the badger engine iterates inside one txn
        # view, ref: badger_test.go TestGetAllNodes): a concurrent delete
        # must not change what an already-started iteration yields
        with self._lock:
            snapshot = [raw for raw in
                        (self._kv.get(k) for k in self._kv.keys(b"n:"))
                        if raw is not None]
        # decode lazily: consumers that stop early (LIMIT 1 scans) must not
        # pay a full-store JSON parse; the raw snapshot above already gives
        # the call-time view
        return (Node.from_dict(json.loads(r)) for r in snapshot)

    # -- edges -----------------------------------------------------------------
    def create_edge(self, edge: Edge) -> Edge:
        with self._lock:
            if self._kv.get(self._ek(edge.id)) is not None:
                raise AlreadyExistsError(f"edge {edge.id} already exists")
            if self._kv.get(self._nk(edge.start_node)) is None:
                raise NotFoundError(f"start node {edge.start_node} not found")
            if self._kv.get(self._nk(edge.end_node)) is None:
                raise NotFoundError(f"end node {edge.end_node} not found")
            stored = edge.copy()
            self._kv.put(self._ek(edge.id), json.dumps(stored.to_dict()).encode())
            self._by_type.setdefault(stored.type, set()).add(stored.id)
            self._out.setdefault(stored.start_node, set()).add(stored.id)
            self._in.setdefault(stored.end_node, set()).add(stored.id)
            self._edge_count += 1
        self._emit("edge_created", stored.copy())
        return stored.copy()

    def get_edge(self, edge_id: str) -> Edge:
        raw = self._kv.get(self._ek(edge_id))
        if raw is None:
            raise NotFoundError(f"edge {edge_id} not found")
        return Edge.from_dict(json.loads(raw))

    def update_edge(self, edge: Edge) -> Edge:
        with self._lock:
            old = self.get_edge(edge.id)
            import time as _time

            stored = edge.copy()
            stored.created_at = old.created_at
            stored.updated_at = _time.time()
            if old.type != stored.type:
                self._by_type.get(old.type, set()).discard(old.id)
                self._by_type.setdefault(stored.type, set()).add(stored.id)
            self._kv.put(self._ek(edge.id), json.dumps(stored.to_dict()).encode())
            self._maybe_compact()
        self._emit("edge_updated", stored.copy())
        return stored.copy()

    def delete_edge(self, edge_id: str) -> None:
        with self._lock:
            edge = self.get_edge(edge_id)
            self._kv.delete(self._ek(edge_id))
            self._by_type.get(edge.type, set()).discard(edge_id)
            self._out.get(edge.start_node, set()).discard(edge_id)
            self._in.get(edge.end_node, set()).discard(edge_id)
            self._edge_count -= 1
            self._maybe_compact()
        self._emit("edge_deleted", edge)

    def get_edges_by_type(self, edge_type: str) -> list[Edge]:
        with self._lock:
            ids = sorted(self._by_type.get(edge_type, set()))
        out = []
        for i in ids:
            try:
                out.append(self.get_edge(i))
            except NotFoundError:
                pass
        return out

    def get_outgoing_edges(self, node_id: str) -> list[Edge]:
        with self._lock:
            ids = sorted(self._out.get(node_id, set()))
        return [e for e in (self._safe_edge(i) for i in ids) if e]

    def get_incoming_edges(self, node_id: str) -> list[Edge]:
        with self._lock:
            ids = sorted(self._in.get(node_id, set()))
        return [e for e in (self._safe_edge(i) for i in ids) if e]

    def _safe_edge(self, edge_id: str) -> Optional[Edge]:
        try:
            return self.get_edge(edge_id)
        except NotFoundError:
            return None

    def all_edges(self) -> Iterator[Edge]:
        # snapshot at call time, same contract as all_nodes (badger
        # iterates inside one txn view): concurrent deletes must not
        # change what an already-started iteration yields
        with self._lock:
            snapshot = [raw for raw in
                        (self._kv.get(k) for k in self._kv.keys(b"e:"))
                        if raw is not None]
        return (Edge.from_dict(json.loads(r)) for r in snapshot)

    # -- counts / pending ---------------------------------------------------------
    def node_count(self) -> int:
        with self._lock:
            return self._node_count

    def count_nodes_by_label(self, label: str) -> int:
        with self._lock:
            return len(self._by_label.get(label, ()))

    def count_edges_by_type(self, edge_type: str) -> int:
        with self._lock:
            return len(self._by_type.get(edge_type, ()))

    def edge_count(self) -> int:
        with self._lock:
            return self._edge_count

    def mark_pending_embed(self, node_id: str) -> None:
        if self._kv.get(self._nk(node_id)) is not None:
            import time

            self._kv.put(b"p:" + node_id.encode(), str(time.time()).encode())

    def unmark_pending_embed(self, node_id: str) -> None:
        self._kv.delete(b"p:" + node_id.encode())

    def pending_embed_ids(self, limit: int = 0) -> list[str]:
        entries = []
        for key in self._kv.keys(b"p:"):
            raw = self._kv.get(key)
            ts = float(raw) if raw else 0.0
            entries.append((ts, key[2:].decode()))
        entries.sort()
        ids = [i for _, i in entries]
        return ids[:limit] if limit > 0 else ids

    def compact(self) -> None:
        with self._lock:
            self._kv.compact()

    def close(self) -> None:
        self._compact_stop.set()
        if self._compact_thread is not None:
            self._compact_thread.join(timeout=5.0)
        with self._lock:
            self._kv.close()
