"""Shared CSR adjacency snapshot: one event-maintained topology index.

Traversal hot paths used to pay Python-per-edge costs on every query:
GDS procedures rebuilt (src, dst) arrays from a full `all_edges()` scan per
call, variable-length MATCH / shortestPath BFS expanded one node at a time
through engine calls, and link prediction ran yet another full scan. This
module keeps the graph's topology resident as CSR arrays — int32
`offsets` / `neighbors` / `edge_rows` per direction plus per-edge
src/dst/type columns and the id<->index vocab — maintained incrementally
from the engine event bus (EDGE_CREATED/UPDATED/DELETED, NODE_CREATED/
DELETED), the same mechanism the columnar label index (cypher/colindex.py)
and NamespacedEngine counts use. After the first build there is never a
full engine rescan on the query path: mutations land in a delta buffer
(O(1) per event) that merges into the CSR arrays only when it exceeds a
threshold, and consumers cache derived views keyed on the snapshot
generation.

Concurrency contract (mirrors colindex.py, verified by nornsan):
the snapshot lock is never held across engine calls — the event handler
touches only snapshot state, and builds fetch from the engine *before*
taking the lock. A build is epoch-validated: if any topology event lands
during the snapshot scan, the build is discarded and retried; on repeated
interference the caller falls back to the engine-scan path for that query.

Index stability: node indices are append-only for the lifetime of the
snapshot (deleted nodes keep a dead vocab slot), so a traversal may hold
node indices across delta merges. Edge rows are renumbered by merges, so
expansion APIs hand back edge *ids*, resolved under the lock.

Known limitation (shared with every consumer of this event bus —
colindex, NamespacedEngine counts): engines emit events after releasing
their lock, so two threads racing create/delete of the SAME edge id can
deliver the events inverted relative to the engine mutations. Healing
this per-query is not an option — engine calls under the snapshot lock
are forbidden (AsyncEngine.edge_count takes its flush lock, whose holder
emits events into this handler: a guaranteed AB/BA deadlock) — and the
window requires a second thread to learn an edge id between another
writer's insert and its emit, which Cypher surfaces don't do.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Iterable, Optional

import numpy as np

from nornicdb_tpu.storage.types import (
    EDGE_CREATED,
    EDGE_DELETED,
    EDGE_UPDATED,
    NODE_CREATED,
    NODE_DELETED,
    Edge,
    Node,
)
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

_ADJ_HIST = _REGISTRY.histogram(
    "nornicdb_adjacency_maintenance_seconds",
    "CSR snapshot maintenance duration by phase (build / delta merge)",
    labels=("phase",),
)
_ADJ_BUILD_CELL = _ADJ_HIST.labels("build")
_ADJ_MERGE_CELL = _ADJ_HIST.labels("merge")

_EDGE_EVENTS = (EDGE_CREATED, EDGE_UPDATED, EDGE_DELETED)
_NODE_EVENTS = (NODE_CREATED, NODE_DELETED)

# delta events buffered before they are folded into the CSR arrays
# (docs/operations.md "Adjacency snapshot tuning")
DEFAULT_MERGE_THRESHOLD = 4096

# csr_view() fold economics: graphs at or under the eager floor always
# fold pending delta adds before serving (the rebuild is cheap); larger
# graphs wait for VIEW_FOLD_MIN_PENDING buffered events so a trickle of
# single writes can't force an O(m log m) rank rebuild per read — interim
# reads serve generically through the matcher's delta overlay instead
VIEW_FOLD_EAGER_EDGES = 32_768
VIEW_FOLD_MIN_PENDING = 512

_attach_lock = threading.Lock()


def _gather_csr(off, nbr, rows, row_alive, row_type, n_csr, arr, codes):
    """One batched gather over frontier `arr` for one CSR direction:
    (heads, rows, neighbor_idx) with tombstoned rows and non-matching type
    codes filtered out. Pure array math over a consistent set of refs —
    callers either hold the snapshot lock or captured the refs under it
    (merges replace these arrays, never resize them in place)."""
    arr = arr[arr < n_csr]
    empty = np.zeros(0, np.int64)
    if not arr.size:
        return empty, empty, empty
    starts = off[arr].astype(np.int64)
    cnts = (off[arr + 1] - off[arr]).astype(np.int64)
    total = int(cnts.sum())
    if not total:
        return empty, empty, empty
    shift = np.repeat(np.cumsum(cnts) - cnts, cnts)
    g = np.repeat(starts, cnts) + np.arange(total) - shift
    heads = np.repeat(arr, cnts)
    r = rows[g]
    keep = row_alive[r]
    if codes is not None:
        keep = keep & np.isin(row_type[r], codes)
    sel = np.nonzero(keep)[0]
    return heads[sel], r[sel].astype(np.int64), nbr[g[sel]].astype(np.int64)


def attach_snapshot(storage, merge_threshold: Optional[int] = None):
    """The engine's shared snapshot, created on first request.

    One snapshot per engine object: matcher, GDS procedures, and link
    prediction all subscribe through the same instance, so one build and
    one event-maintained index serve every consumer. An explicit
    merge_threshold re-tunes an already-attached snapshot (consumers
    auto-attach with the default, so the operator's later setting must
    not be silently dropped)."""
    snap = getattr(storage, "_adjacency_snapshot", None)
    if snap is None:
        with _attach_lock:
            snap = getattr(storage, "_adjacency_snapshot", None)
            if snap is None:
                snap = AdjacencySnapshot(
                    storage,
                    merge_threshold=merge_threshold
                    if merge_threshold is not None
                    else DEFAULT_MERGE_THRESHOLD)
                storage._adjacency_snapshot = snap
                return snap
    if merge_threshold is not None:
        snap.merge_threshold = max(int(merge_threshold), 1)
    return snap


class CSRView:
    """Generation-pinned capture of the merged CSR arrays for the columnar
    Cypher pipeline (cypher/columnar.py).  Built under the snapshot lock
    with the delta buffer folded first, so a view needs no overlay logic:
    the CSR arrays alone answer every expansion.  Arrays are replaced —
    never resized — by later merges, so holding a view across a query is
    safe; ``row_alive``/``node_alive`` are copies pinned at capture (a
    concurrent delete must not tear a half-executed operator pipeline).

    ``erow_rank[r]`` is the dense rank of edge row ``r`` in edge-ID-sorted
    order — expansions order each frontier node's edges by this integer
    instead of sorting edge-id strings per query (the generic matcher's
    per-edge ``sort()`` contract at array speed)."""

    __slots__ = ("generation", "n_csr", "ids", "node_alive", "row_alive",
                 "erow_type", "erow_rank", "row_ids", "type_code", "eprops",
                 "_csr")

    def __init__(self, generation, n_csr, ids, node_alive, row_alive,
                 erow_type, erow_rank, row_ids, type_code, eprops, csr):
        self.generation = generation
        self.n_csr = n_csr
        self.ids = ids              # vocab list ref (append-only)
        self.node_alive = node_alive
        self.row_alive = row_alive
        self.erow_type = erow_type
        self.erow_rank = erow_rank
        self.row_ids = row_ids      # row -> edge id (list ref; replaced by merges)
        self.type_code = type_code  # name -> code (copy)
        self.eprops = eprops        # key -> row-aligned column (list refs)
        self._csr = csr             # {"out": (off, nbr, rows), "in": ...}

    def edge_prop_column(self, key: str):
        """Row-aligned edge property column, or None when the key was never
        present on any edge at capture (callers synthesize all-null).  The
        list is shared with the snapshot: in-place property updates are
        visible until the next merge replaces it — the same mid-query
        read-latest semantics the node colindex columns have."""
        return self.eprops.get(key)

    def codes_for(self, types) -> Optional[list[int]]:
        """Codes for a rel-type filter; None = no filter. An empty list
        means the types were never seen on any edge (matches nothing)."""
        if not types:
            return None
        return [c for t in types if (c := self.type_code.get(t)) is not None]

    def expand_unique(
        self, uniq: np.ndarray, direction: str,
        codes: Optional[list[int]],
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Batched expansion of a SORTED array of unique node indices.

        Returns ``(counts, rows, nbrs)``: ``counts[i]`` edges for
        ``uniq[i]``, with the flat ``rows``/``nbrs`` arrays segmented per
        unique node and each segment ordered by edge id (via erow_rank) —
        the order the generic matcher's per-node expansion produces.
        Edges to dead neighbor nodes are dropped (the generic walk skips
        them at get_node time)."""
        dirs = (("out",) if direction == "out"
                else ("in",) if direction == "in" else ("out", "in"))
        h_parts, r_parts, n_parts = [], [], []
        for d in dirs:
            off, nbr, rows = self._csr[d]
            h, r, nb = _gather_csr(off, nbr, rows, self.row_alive,
                                   self.erow_type, self.n_csr, uniq, codes)
            if h.size:
                h_parts.append(h)
                r_parts.append(r)
                n_parts.append(nb)
        zero = np.zeros(len(uniq), np.int64)
        if not h_parts:
            empty = np.zeros(0, np.int64)
            return zero, empty, empty
        heads = np.concatenate(h_parts)
        rows = np.concatenate(r_parts)
        nbrs = np.concatenate(n_parts)
        keep = self.node_alive[nbrs]
        if not keep.all():
            sel = np.nonzero(keep)[0]
            heads, rows, nbrs = heads[sel], rows[sel], nbrs[sel]
        if not heads.size:
            empty = np.zeros(0, np.int64)
            return zero, empty, empty
        pos = np.searchsorted(uniq, heads)
        order = np.lexsort((self.erow_rank[rows], pos))
        pos = pos[order]
        counts = np.bincount(pos, minlength=len(uniq)).astype(np.int64)
        return counts, rows[order], nbrs[order]


class EdgeArraysView:
    """Sorted-id projection of the snapshot for array-native consumers
    (GDS procedures, link prediction). Arrays are immutable by contract —
    consumers may hold them across queries; the snapshot replaces (never
    mutates) the cached view when the generation moves."""

    __slots__ = ("ids", "index", "src", "dst", "type_codes", "type_names",
                 "generation")

    def __init__(self, ids, index, src, dst, type_codes, type_names,
                 generation):
        self.ids = ids
        self.index = index
        self.src = src
        self.dst = dst
        self.type_codes = type_codes
        self.type_names = type_names
        self.generation = generation


class SnapshotStats:
    """Counters in the corpus SyncStats style (ops/similarity.py)."""

    __slots__ = ("builds", "epoch_retries", "delta_merges", "merged_edges",
                 "delta_events", "expansions")

    def __init__(self) -> None:
        self.builds = 0
        self.epoch_retries = 0
        self.delta_merges = 0
        self.merged_edges = 0
        self.delta_events = 0
        self.expansions = 0


class AdjacencySnapshot:
    def __init__(self, storage,
                 merge_threshold: int = DEFAULT_MERGE_THRESHOLD):
        self.storage = storage
        self.merge_threshold = max(int(merge_threshold), 1)
        self._lock = threading.RLock()
        self.stats = SnapshotStats()
        self._built = False
        self._epoch = 0       # bumped per topology event; validates builds
        self._generation = 0  # bumped per applied topology change
        # -- node vocab (append-only indices; dead slots are kept) ---------
        self._ids: list[str] = []
        self._idx: dict[str, int] = {}
        self._alive: list[bool] = []
        self._alive_count = 0
        # -- edge-type vocab (append-only codes) ---------------------------
        self._type_names: list[str] = []
        self._type_code: dict[str, int] = {}
        # -- canonical CSR state (rebuilt by _merge_locked) ----------------
        self._n_csr = 0  # vocab size the CSR offsets were built for
        self._m = 0      # canonical edge rows
        self._erow_src = np.zeros(0, np.int32)
        self._erow_dst = np.zeros(0, np.int32)
        self._erow_type = np.zeros(0, np.int32)
        self._row_ids: list[str] = []
        self._row_of: dict[str, int] = {}
        self._row_alive = np.zeros(0, bool)
        # row-aligned edge property columns (key -> list, length _m); the
        # columnar pipeline's edge-prop filters/aggregates read these via
        # CSRView.edge_prop_column instead of per-row get_edge fetches
        self._eprops: dict[str, list] = {}
        self._tombstones = 0
        self._out_off = np.zeros(1, np.int32)
        self._out_nbr = np.zeros(0, np.int32)
        self._out_rows = np.zeros(0, np.int32)
        self._in_off = np.zeros(1, np.int32)
        self._in_nbr = np.zeros(0, np.int32)
        self._in_rows = np.zeros(0, np.int32)
        # -- delta buffer (edges since last merge; rows >= _m) -------------
        self._d_ids: list[str] = []
        self._d_src: list[int] = []
        self._d_dst: list[int] = []
        self._d_type: list[int] = []
        self._d_alive: list[bool] = []
        self._d_props: list[Optional[dict]] = []
        self._d_out: dict[int, list[int]] = {}
        self._d_in: dict[int, list[int]] = {}
        self._pending = 0  # delta events since last merge (adds + removes)
        # -- generation-tagged derived views -------------------------------
        self._view_cache: Optional[EdgeArraysView] = None
        self._graph_cache: dict[Any, tuple[int, Any]] = {}
        # columnar-pipeline view cache: the CSRView itself is keyed on
        # generation; the edge-id rank array on the _row_ids list identity
        # (merges replace the list, everything else leaves it alone)
        self._csr_view: Optional[CSRView] = None
        self._rank_src: Optional[list] = None
        self._rank_arr: Optional[np.ndarray] = None
        storage.on_event(self._on_event)

    # -- event handler (writer threads; touches ONLY snapshot state) -------
    def _on_event(self, kind: str, entity: Any) -> None:
        if kind in _EDGE_EVENTS:
            if not isinstance(entity, Edge):
                return
            with self._lock:
                self._epoch += 1
                if not self._built:
                    return
                if kind == EDGE_CREATED:
                    self._add_edge_locked(entity.id, entity.start_node,
                                          entity.end_node, entity.type,
                                          entity.properties)
                elif kind == EDGE_DELETED:
                    self._remove_edge_locked(entity.id)
                else:  # EDGE_UPDATED: re-link only if topology changed
                    self._update_edge_locked(entity)
        elif kind in _NODE_EVENTS:
            if not isinstance(entity, Node):
                return
            with self._lock:
                self._epoch += 1
                if not self._built:
                    return
                if kind == NODE_CREATED:
                    self._intern_node_locked(entity.id, resurrect=True)
                    self._generation += 1
                    self._view_cache = None
                else:
                    i = self._idx.get(entity.id)
                    if i is not None and self._alive[i]:
                        self._alive[i] = False
                        self._alive_count -= 1
                        self._generation += 1
                        self._view_cache = None

    # -- locked mutators ----------------------------------------------------
    def _intern_node_locked(self, node_id: str, resurrect: bool = False) -> int:
        i = self._idx.get(node_id)
        if i is None:
            i = len(self._ids)
            self._ids.append(node_id)
            self._idx[node_id] = i
            self._alive.append(True)
            self._alive_count += 1
        elif resurrect and not self._alive[i]:
            self._alive[i] = True
            self._alive_count += 1
        return i

    def _type_code_locked(self, name: str) -> int:
        c = self._type_code.get(name)
        if c is None:
            c = len(self._type_names)
            self._type_names.append(name)
            self._type_code[name] = c
        return c

    def _add_edge_locked(self, eid: str, src_id: str, dst_id: str,
                         type_name: str,
                         props: Optional[dict] = None) -> None:
        row = self._row_of.get(eid)
        if row is not None and self._edge_alive_locked(row):
            return  # duplicate create event
        s = self._intern_node_locked(src_id)
        d = self._intern_node_locked(dst_id)
        t = self._type_code_locked(type_name)
        j = len(self._d_ids)
        self._d_ids.append(eid)
        self._d_src.append(s)
        self._d_dst.append(d)
        self._d_type.append(t)
        self._d_alive.append(True)
        self._d_props.append(dict(props) if props else None)
        self._d_out.setdefault(s, []).append(j)
        self._d_in.setdefault(d, []).append(j)
        self._row_of[eid] = self._m + j
        self._pending += 1
        self.stats.delta_events += 1
        self._generation += 1
        self._view_cache = None

    def _remove_edge_locked(self, eid: str) -> None:
        row = self._row_of.get(eid)
        if row is None:
            return
        if row < self._m:
            if self._row_alive[row]:
                self._row_alive[row] = False
                self._tombstones += 1
                self._pending += 1
                self.stats.delta_events += 1
                self._generation += 1
                self._view_cache = None
            self._row_of.pop(eid, None)
        else:
            j = row - self._m
            if self._d_alive[j]:
                self._d_alive[j] = False
                self._pending += 1
                self.stats.delta_events += 1
                self._generation += 1
                self._view_cache = None
            self._row_of.pop(eid, None)

    def _edge_alive_locked(self, row: int) -> bool:
        if row < self._m:
            return bool(self._row_alive[row])
        return self._d_alive[row - self._m]

    def _edge_record_locked(self, row: int) -> tuple[int, int, int]:
        if row < self._m:
            return (int(self._erow_src[row]), int(self._erow_dst[row]),
                    int(self._erow_type[row]))
        j = row - self._m
        return (self._d_src[j], self._d_dst[j], self._d_type[j])

    def _update_edge_locked(self, edge: Edge) -> None:
        row = self._row_of.get(edge.id)
        if row is None or not self._edge_alive_locked(row):
            # update for an edge we never saw created: treat as add
            self._add_edge_locked(edge.id, edge.start_node, edge.end_node,
                                  edge.type, edge.properties)
            return
        s, d, t = self._edge_record_locked(row)
        ns = self._idx.get(edge.start_node)
        nd = self._idx.get(edge.end_node)
        nt = self._type_code.get(edge.type)
        if (ns, nd, nt) == (s, d, t):
            # property-only update: topology unchanged, refresh columns
            self._set_edge_props_locked(row, edge.properties)
            return
        self._remove_edge_locked(edge.id)
        self._add_edge_locked(edge.id, edge.start_node, edge.end_node,
                              edge.type, edge.properties)

    def _set_edge_props_locked(self, row: int, props: dict) -> None:
        """Overwrite an alive edge row's property columns in place (keys
        absent from ``props`` are cleared — update replaces the map)."""
        if row >= self._m:
            self._d_props[row - self._m] = dict(props) if props else None
            return
        for k, col in self._eprops.items():
            col[row] = props.get(k) if props else None
        if props:
            for k, v in props.items():
                if k not in self._eprops:
                    col = [None] * self._m
                    col[row] = v
                    self._eprops[k] = col
                    # a brand-new key isn't in the cached view's shallow
                    # column dict: drop the view so the next capture sees it
                    self._csr_view = None

    # -- build / merge ------------------------------------------------------
    def ready(self) -> bool:
        """Built and usable, without triggering a build."""
        with self._lock:
            return self._built

    def ensure(self) -> bool:
        """Build on first use (epoch-validated), fold the delta buffer into
        the CSR arrays when it exceeds the threshold. Returns False only
        when racing writers defeated every build attempt — callers fall
        back to the engine-scan path for that query."""
        with self._lock:
            if self._built:
                if self._pending > self.merge_threshold:
                    self._merge_locked()
                return True
        for _ in range(3):
            with self._lock:
                epoch0 = self._epoch
            node_ids = self._scan_node_ids()
            edges = [(e.id, e.start_node, e.end_node, e.type,
                      e.properties or None)
                     for e in self.storage.all_edges()]
            with self._lock:
                if self._built:
                    return True
                if self._epoch != epoch0:
                    self.stats.epoch_retries += 1
                    continue
                self._install_locked(node_ids, edges)
                return True
        return False

    def _scan_node_ids(self) -> list[str]:
        ids_fn = getattr(self.storage, "all_node_ids", None)
        if ids_fn is not None:
            try:
                return list(ids_fn())
            except AttributeError:
                # decorator engine whose base lacks the id-only scan
                pass
        return [n.id for n in self.storage.all_nodes()]

    def _install_locked(self, node_ids: list[str],
                        edges: list[tuple]) -> None:
        t0 = time.perf_counter()
        with _tracer.span("adjacency.build",
                          {"nodes": len(node_ids), "edges": len(edges)}):
            self._install_locked_inner(node_ids, edges)
        _ADJ_BUILD_CELL.observe(time.perf_counter() - t0)

    def _install_locked_inner(self, node_ids: list[str],
                              edges: list[tuple]) -> None:
        self._ids = list(node_ids)
        self._idx = {id_: i for i, id_ in enumerate(self._ids)}
        self._alive = [True] * len(self._ids)
        self._alive_count = len(self._ids)
        m = len(edges)
        src = np.zeros(m, np.int32)
        dst = np.zeros(m, np.int32)
        typ = np.zeros(m, np.int32)
        self._row_ids = [""] * m
        self._row_of = {}
        eprops: dict[str, list] = {}
        for r, (eid, s_id, d_id, t_name, props) in enumerate(edges):
            src[r] = self._intern_node_locked(s_id)
            dst[r] = self._intern_node_locked(d_id)
            typ[r] = self._type_code_locked(t_name)
            self._row_ids[r] = eid
            self._row_of[eid] = r
            if props:
                for k, v in props.items():
                    col = eprops.get(k)
                    if col is None:
                        col = eprops[k] = [None] * m
                    col[r] = v
        self._erow_src, self._erow_dst, self._erow_type = src, dst, typ
        self._eprops = eprops
        self._m = m
        self._row_alive = np.ones(m, bool)
        self._tombstones = 0
        self._clear_delta_locked()
        self._rebuild_csr_locked()
        self._built = True
        self.stats.builds += 1
        self._generation += 1
        self._view_cache = None

    def _clear_delta_locked(self) -> None:
        self._d_ids = []
        self._d_src = []
        self._d_dst = []
        self._d_type = []
        self._d_alive = []
        self._d_props = []
        self._d_out = {}
        self._d_in = {}
        self._pending = 0

    def _rebuild_csr_locked(self) -> None:
        n = len(self._ids)
        self._n_csr = n
        rows = np.arange(self._m, dtype=np.int32)
        for direction in ("out", "in"):
            key = self._erow_src if direction == "out" else self._erow_dst
            nbr = self._erow_dst if direction == "out" else self._erow_src
            order = np.argsort(key, kind="stable")
            counts = np.bincount(key, minlength=n) if self._m else \
                np.zeros(n, np.int64)
            off = np.zeros(n + 1, np.int32)
            off[1:] = np.cumsum(counts).astype(np.int32)
            if direction == "out":
                self._out_off = off
                self._out_nbr = nbr[order]
                self._out_rows = rows[order]
            else:
                self._in_off = off
                self._in_nbr = nbr[order]
                self._in_rows = rows[order]

    def _merge_locked(self) -> None:
        """Fold tombstones + delta adds into fresh canonical arrays. Node
        indices are preserved (vocab is append-only); edge rows renumber."""
        t0 = time.perf_counter()
        with _tracer.span("adjacency.merge", {"pending": self._pending}):
            self._merge_locked_inner()
        _ADJ_MERGE_CELL.observe(time.perf_counter() - t0)

    def _merge_locked_inner(self) -> None:
        keep = np.nonzero(self._row_alive)[0]
        d_keep = [j for j, a in enumerate(self._d_alive) if a]
        merged = len(d_keep) + self._tombstones
        src = np.concatenate([
            self._erow_src[keep],
            np.asarray([self._d_src[j] for j in d_keep], np.int32),
        ]).astype(np.int32)
        dst = np.concatenate([
            self._erow_dst[keep],
            np.asarray([self._d_dst[j] for j in d_keep], np.int32),
        ]).astype(np.int32)
        typ = np.concatenate([
            self._erow_type[keep],
            np.asarray([self._d_type[j] for j in d_keep], np.int32),
        ]).astype(np.int32)
        keep_l = keep.tolist()
        row_ids = [self._row_ids[r] for r in keep_l]
        row_ids += [self._d_ids[j] for j in d_keep]
        # re-gather property columns in the same keep + delta order (fresh
        # lists: views pinned pre-merge keep reading their own copies)
        keys = set(self._eprops)
        for j in d_keep:
            p = self._d_props[j]
            if p:
                keys.update(p)
        eprops: dict[str, list] = {}
        for k in keys:
            old = self._eprops.get(k)
            col = ([old[r] for r in keep_l] if old is not None
                   else [None] * len(keep_l))
            for j in d_keep:
                p = self._d_props[j]
                col.append(p.get(k) if p else None)
            eprops[k] = col
        self._erow_src, self._erow_dst, self._erow_type = src, dst, typ
        self._eprops = eprops
        self._row_ids = row_ids
        self._row_of = {eid: r for r, eid in enumerate(row_ids)}
        self._m = len(row_ids)
        self._row_alive = np.ones(self._m, bool)
        self._tombstones = 0
        self._clear_delta_locked()
        self._rebuild_csr_locked()
        self.stats.delta_merges += 1
        self.stats.merged_edges += merged

    # -- vocab --------------------------------------------------------------
    def generation(self) -> int:
        with self._lock:
            return self._generation

    def index_of(self, node_id: str) -> Optional[int]:
        with self._lock:
            i = self._idx.get(node_id)
            if i is None or not self._alive[i]:
                return None
            return i

    def id_of(self, idx: int) -> str:
        with self._lock:
            return self._ids[idx]

    def ids_of(self, idxs: Iterable[int]) -> list[str]:
        with self._lock:
            ids = self._ids
            return [ids[i] for i in idxs]

    def type_codes(self, types) -> Optional[list[int]]:
        """Codes for a rel-type filter; None means no filter. Types never
        seen on any edge resolve to nothing — expansions are empty."""
        if not types:
            return None
        with self._lock:
            return [c for t in types
                    if (c := self._type_code.get(t)) is not None]

    # -- expansion ----------------------------------------------------------
    def expand_pairs(self, node_id: str, direction: str,
                     types=None) -> Optional[list[tuple[str, str]]]:
        """(edge_id, other_node_id) pairs, sorted — the matcher `_expand`
        contract. None when the node is unknown to the snapshot (caller
        falls back to the engine path)."""
        idx = self.index_of(node_id)
        if idx is None:
            return None
        codes = self.type_codes(types)
        if types and not codes:
            return []
        adj = self.expand_frontier([idx], direction, codes)
        with self._lock:
            ids = self._ids
            out = [(eid, ids[o]) for eid, o in adj.get(idx, ())]
        out.sort()
        return out

    def _maybe_merge_locked(self) -> None:
        """Fold an over-threshold delta before serving a read — EVERY read
        entry point calls this, so the overlay stays bounded even for
        workloads whose queries never go through ensure()."""
        if self._built and self._pending > self.merge_threshold:
            self._merge_locked()

    def _gather_csr_locked(
        self, direction: str, arr: np.ndarray,
        codes: Optional[list[int]],
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        if direction == "out":
            off, nbr, rows = self._out_off, self._out_nbr, self._out_rows
        else:
            off, nbr, rows = self._in_off, self._in_nbr, self._in_rows
        return _gather_csr(off, nbr, rows, self._row_alive, self._erow_type,
                           self._n_csr, arr, codes)

    def _delta_neighbors_locked(self, direction: str, idx: int,
                                code_set: Optional[set]
                                ) -> Iterable[tuple[str, int]]:
        dmap = self._d_out if direction == "out" else self._d_in
        for j in dmap.get(idx, ()):
            if not self._d_alive[j]:
                continue
            if code_set is not None and self._d_type[j] not in code_set:
                continue
            yield (self._d_ids[j],
                   self._d_dst[j] if direction == "out" else self._d_src[j])

    def expand_frontier(
        self, idxs: list[int], direction: str,
        codes: Optional[list[int]] = None,
    ) -> dict[int, list[tuple[str, int]]]:
        """Batched CSR expansion: one gather over the frontier instead of
        one engine call per node. Returns idx -> [(edge_id, other_idx)],
        each list sorted by edge id (the order the generic matcher's
        per-edge sort produces)."""
        dirs = (("out",) if direction == "out"
                else ("in",) if direction == "in" else ("out", "in"))
        out: dict[int, list[tuple[str, int]]] = {i: [] for i in idxs}
        gathered = []
        # Lock only for the array gathers and the (threshold-bounded) delta
        # extraction; the per-edge Python tuple building runs outside so a
        # large frontier level never stalls writers' event handlers.
        # `_row_ids` is replaced (never mutated) by merges, so the captured
        # list stays aligned with the gathered row indices.
        with self._lock:
            self._maybe_merge_locked()
            self.stats.expansions += 1
            arr_all = np.fromiter(idxs, np.int64, len(idxs))
            code_set = None if codes is None else set(codes)
            row_ids = self._row_ids
            for d in dirs:
                heads, r, nb = self._gather_csr_locked(d, arr_all, codes)
                deltas = None
                if self._d_out or self._d_in:
                    deltas = {
                        i: list(self._delta_neighbors_locked(d, i, code_set))
                        for i in idxs
                    }
                gathered.append((heads, r, nb, deltas))
        for heads, r, nb, deltas in gathered:
            for k in range(heads.size):
                out[int(heads[k])].append((row_ids[int(r[k])], int(nb[k])))
            if deltas:
                for i, pairs in deltas.items():
                    out[i].extend(pairs)
        for lst in out.values():
            lst.sort()
        return out

    def bfs_distances(self, start_id: str, direction: str = "both",
                      types=None) -> Optional[np.ndarray]:
        """Frontier-batched BFS over the CSR arrays: hop distance per node
        index (-1 unreached). The whole loop is numpy gathers + dedup —
        no per-node engine calls, no per-edge Python."""
        start = self.index_of(start_id)
        if start is None:
            return None
        codes = self.type_codes(types)
        if types and not codes:
            codes = [-1]  # matches nothing
        dirs = (("out",) if direction == "out"
                else ("in",) if direction == "in" else ("out", "in"))
        # Capture a consistent view under the lock, then run the whole BFS
        # outside it: a multi-level walk over a big component must not
        # stall every writer's event handler for its full duration. The
        # CSR arrays are replaced (never resized) by merges; row_alive is
        # COPIED because tombstones flip it in place — the copy pins one
        # graph state for the whole walk instead of tearing mid-level.
        # The delta overlay is copied out while bounded by merge_threshold.
        with self._lock:
            self._maybe_merge_locked()
            n = len(self._ids)
            n_csr = self._n_csr
            row_alive, row_type = self._row_alive.copy(), self._erow_type
            csr = {"out": (self._out_off, self._out_nbr, self._out_rows),
                   "in": (self._in_off, self._in_nbr, self._in_rows)}
            code_set = None if codes is None else set(codes)
            delta: dict[str, dict[int, list[int]]] = {d: {} for d in dirs}
            for d in dirs:
                dmap = self._d_out if d == "out" else self._d_in
                for i in dmap:
                    others = [o for _eid, o in
                              self._delta_neighbors_locked(d, i, code_set)]
                    if others:
                        delta[d][i] = others
        dist = np.full(n, -1, np.int32)
        dist[start] = 0
        frontier = np.asarray([start], np.int64)
        level = 0
        while frontier.size:
            nxt_parts = []
            for d in dirs:
                off, nbr, rows = csr[d]
                _, _, nb = _gather_csr(off, nbr, rows, row_alive, row_type,
                                       n_csr, frontier, codes)
                if nb.size:
                    nxt_parts.append(nb)
                if delta[d]:
                    extra = [o for i in frontier.tolist()
                             for o in delta[d].get(i, ())]
                    if extra:
                        nxt_parts.append(np.asarray(extra, np.int64))
            if not nxt_parts:
                break
            cand = np.concatenate(nxt_parts).astype(np.int64)
            cand = cand[dist[cand] < 0]
            if not cand.size:
                break
            frontier = np.unique(cand)
            level += 1
            dist[frontier] = level
        return dist

    def indices_of(self, ids: list[str]) -> np.ndarray:
        """Batched id -> vocab index lookup (-1 for unknown/dead nodes) —
        one locked pass instead of a locked call per node."""
        with self._lock:
            idx = self._idx
            alive = self._alive
            out = np.empty(len(ids), np.int64)
            for k, s in enumerate(ids):
                i = idx.get(s)
                out[k] = i if (i is not None and alive[i]) else -1
            return out

    def csr_view(self) -> Optional[CSRView]:
        """Delta-folded, generation-pinned :class:`CSRView` for the
        columnar Cypher pipeline, or None before the first build.

        A pure-array consumer has no delta-overlay logic, so pending delta
        ADDS must be folded into the CSR before it reads.  But a fold
        rebuilds the canonical arrays AND the edge-id rank (O(m log m) —
        measured ~250ms at 500k edges), so a single trickled write must
        not pay that per read: small graphs fold eagerly (cheap), large
        graphs wait for the delta to amortize the rebuild and serve the
        interim reads generically (returning None — the matcher's
        existing delta overlay answers them).  Tombstoned deletes need no
        fold (the pinned ``row_alive`` copy filters them).  Repeat
        queries on an unchanged graph reuse the cached view (and its
        rank array) for free."""
        with self._lock:
            if not self._built:
                return None
            if self._d_ids:
                if self._m <= VIEW_FOLD_EAGER_EDGES \
                        or self._pending >= VIEW_FOLD_MIN_PENDING:
                    self._merge_locked()
                else:
                    return None
            view = self._csr_view
            if view is not None and view.generation == self._generation:
                return view
            if self._rank_src is not self._row_ids:
                # dense rank of each edge row in edge-ID-sorted order;
                # one C-speed argsort per merge, reused by every query
                if self._m:
                    order = np.argsort(np.asarray(self._row_ids))
                    rank = np.empty(self._m, np.int64)
                    rank[order] = np.arange(self._m)
                else:
                    rank = np.zeros(0, np.int64)
                self._rank_src = self._row_ids
                self._rank_arr = rank
            view = CSRView(
                generation=self._generation,
                n_csr=self._n_csr,
                ids=self._ids,
                node_alive=np.asarray(self._alive, bool),
                row_alive=self._row_alive.copy(),
                erow_type=self._erow_type,
                erow_rank=self._rank_arr,
                row_ids=self._row_ids,
                type_code=dict(self._type_code),
                eprops=dict(self._eprops),
                csr={"out": (self._out_off, self._out_nbr, self._out_rows),
                     "in": (self._in_off, self._in_nbr, self._in_rows)},
            )
            self._csr_view = view
            return view

    def export_arrays(self) -> Optional[tuple[dict, dict]]:
        """Merged, self-contained copies of the CSR arrays + vocab for the
        cross-process shared-memory read plane (server/readplane.py).

        Returns ``(arrays, vocab)`` or None when the snapshot was never
        built. Any pending delta/tombstones are folded first (exports are
        infrequent relative to merges), so readers need no delta-overlay
        logic: the exported CSR alone answers every expansion the
        in-process snapshot would — the twin-path equivalence the worker
        traversal tests assert."""
        with self._lock:
            if not self._built:
                return None
            if self._pending or self._tombstones:
                self._merge_locked()
            arrays = {
                "out_off": self._out_off.copy(),
                "out_nbr": self._out_nbr.copy(),
                "out_rows": self._out_rows.copy(),
                "in_off": self._in_off.copy(),
                "in_nbr": self._in_nbr.copy(),
                "in_rows": self._in_rows.copy(),
                "erow_type": self._erow_type.copy(),
                "row_alive": self._row_alive.copy(),
                "node_alive": np.asarray(self._alive, bool),
            }
            vocab = {
                "ids": list(self._ids),
                "row_ids": list(self._row_ids),
                "type_names": list(self._type_names),
                "generation": self._generation,
                "n_csr": self._n_csr,
            }
        return arrays, vocab

    # -- derived views ------------------------------------------------------
    def edge_arrays(self) -> EdgeArraysView:
        """Sorted-id (ids, index, src, dst) projection — the `_edge_arrays`
        contract in cypher/gds_procedures.py — generation-cached so
        repeated GDS calls on an unchanged graph reuse the same arrays."""
        with self._lock:
            self._maybe_merge_locked()
            view = self._view_cache
            if view is not None and view.generation == self._generation:
                return view
            alive_ids = sorted(
                id_ for i, id_ in enumerate(self._ids) if self._alive[i])
            index = {id_: i for i, id_ in enumerate(alive_ids)}
            pos = np.full(len(self._ids), -1, np.int64)
            for id_, p in index.items():
                pos[self._idx[id_]] = p
            keep = np.nonzero(self._row_alive)[0]
            s_parts = [self._erow_src[keep]]
            d_parts = [self._erow_dst[keep]]
            t_parts = [self._erow_type[keep]]
            d_live = [j for j, a in enumerate(self._d_alive) if a]
            if d_live:
                s_parts.append(np.asarray(
                    [self._d_src[j] for j in d_live], np.int32))
                d_parts.append(np.asarray(
                    [self._d_dst[j] for j in d_live], np.int32))
                t_parts.append(np.asarray(
                    [self._d_type[j] for j in d_live], np.int32))
            s_raw = np.concatenate(s_parts) if s_parts else \
                np.zeros(0, np.int32)
            d_raw = np.concatenate(d_parts) if d_parts else \
                np.zeros(0, np.int32)
            t_raw = np.concatenate(t_parts) if t_parts else \
                np.zeros(0, np.int32)
            src = pos[s_raw]
            dst = pos[d_raw]
            ok = (src >= 0) & (dst >= 0)  # drop edges touching dead nodes
            view = EdgeArraysView(
                ids=alive_ids,
                index=index,
                src=src[ok].astype(np.int32),
                dst=dst[ok].astype(np.int32),
                type_codes=t_raw[ok],
                type_names=list(self._type_names),
                generation=self._generation,
            )
            self._view_cache = view
            return view

    def graph_view(self, edge_types=None):
        """Undirected linkpredict Graph built from the snapshot arrays —
        no engine scan, cached per (generation, type filter)."""
        from nornicdb_tpu.linkpredict.topology import Graph

        key = tuple(sorted(edge_types)) if edge_types else None
        view = self.edge_arrays()
        with self._lock:
            hit = self._graph_cache.get(key)
            if hit is not None and hit[0] == view.generation:
                return hit[1]
        src, dst = view.src, view.dst
        if edge_types:
            wanted = {c for c, name in enumerate(view.type_names)
                      if name in set(edge_types)}
            if wanted:
                mask = np.isin(view.type_codes, list(wanted))
                src, dst = src[mask], dst[mask]
            else:
                src = dst = np.zeros(0, np.int32)
        neighbors: list[set[int]] = [set() for _ in view.ids]
        for a, b in zip(src.tolist(), dst.tolist()):
            if a != b:
                neighbors[a].add(b)
                neighbors[b].add(a)
        g = Graph(list(view.ids), dict(view.index), neighbors)
        with self._lock:
            if len(self._graph_cache) > 8:
                self._graph_cache.clear()
            self._graph_cache[key] = (view.generation, g)
        return g

    # -- stats --------------------------------------------------------------
    def stats_snapshot(self) -> dict[str, Any]:
        with self._lock:
            edges_live = int(self._row_alive.sum()) + sum(
                1 for a in self._d_alive if a)
            csr_bytes = int(
                self._out_off.nbytes + self._out_nbr.nbytes
                + self._out_rows.nbytes + self._in_off.nbytes
                + self._in_nbr.nbytes + self._in_rows.nbytes
                + self._erow_src.nbytes + self._erow_dst.nbytes
                + self._erow_type.nbytes)
            return {
                "built": self._built,
                "generation": self._generation,
                "nodes": self._alive_count,
                "edges": edges_live,
                "builds": self.stats.builds,
                "epoch_retries": self.stats.epoch_retries,
                "delta_merges": self.stats.delta_merges,
                "merged_edges": self.stats.merged_edges,
                "delta_events": self.stats.delta_events,
                "delta_pending": self._pending,
                "expansions": self.stats.expansions,
                "bytes": csr_bytes,
                "merge_threshold": self.merge_threshold,
            }
