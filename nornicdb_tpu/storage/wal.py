"""Write-ahead log with CRC-validated atomic records, snapshots and
transaction-aware recovery.

Behavioral reference: /root/reference/pkg/storage/wal.go,
wal_atomic_record.go:8-39 (record framing: magic, version, length, payload,
CRC32, trailer, 8-byte alignment), wal.go:819-938 (CreateSnapshot /
TruncateAfterSnapshot), wal.go:1512-1845 (recovery incl. incomplete-tx undo).

Record layout (own format, same guarantees as the reference's v2 records):

    [magic:4 = b"NWAL"][version:1][oplen:4 LE][payload: oplen bytes]
    [crc32:4 LE over payload][seq:8 LE][padding to 8-byte boundary]

A torn tail (partial record, bad magic, or CRC mismatch) terminates replay at
the last good record; preceding records are preserved.
"""

from __future__ import annotations

import errno as _errno
import json
import logging
import os
import struct
import threading
import time as _time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.errors import DurabilityError, WALCorruptionError
from nornicdb_tpu.storage import native as _native
from nornicdb_tpu.storage.faults import INJECTOR as _FAULTS
from nornicdb_tpu.storage.types import Edge, Engine, Node
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

_WAL_APPEND_HIST = _REGISTRY.histogram(
    "nornicdb_wal_append_seconds",
    "WAL append latency (encode + write + flush, incl. fsync when sync=True)",
)
_WAL_FSYNC_HIST = _REGISTRY.histogram(
    "nornicdb_wal_fsync_seconds",
    "WAL fsync latency (sync=True appends only)",
)
_WAL_APPEND_FAILURES = _REGISTRY.counter(
    "nornicdb_wal_append_failures_total",
    "WAL appends that failed durability (write/fsync error, ENOSPC) and "
    "were rolled back off the tail — surfaced to callers as DurabilityError",
    labels=("kind",),
)
for _k in ("enospc", "io", "fsync"):
    _WAL_APPEND_FAILURES.labels(_k)  # eager cells: render 0, not absent
del _k

MAGIC = b"NWAL"
VERSION = 1
_HEADER = struct.Struct("<4sBI")  # magic, version, oplen
_FOOTER = struct.Struct("<IQ")  # crc32, seq

# Operation kinds
OP_CREATE_NODE = "create_node"
OP_UPDATE_NODE = "update_node"
OP_DELETE_NODE = "delete_node"
OP_CREATE_EDGE = "create_edge"
OP_UPDATE_EDGE = "update_edge"
OP_DELETE_EDGE = "delete_edge"
OP_TX_BEGIN = "tx_begin"
OP_TX_COMMIT = "tx_commit"
OP_TX_ROLLBACK = "tx_rollback"
OP_MARK_PENDING = "mark_pending_embed"
OP_UNMARK_PENDING = "unmark_pending_embed"


@dataclass
class WALEntry:
    seq: int
    op: str
    data: dict[str, Any] = field(default_factory=dict)
    txid: Optional[str] = None

    def encode(self, encryptor=None, use_native: bool = False) -> bytes:
        """Frame one record. ``use_native`` is resolved ONCE by the owning
        WAL at init (outside any lock): deciding here via _native.enabled()
        would put the first-call dlopen — and possibly a compiler build —
        inside WAL.append's critical section. Both codecs emit identical
        bytes, so a bare encode() (tests, tooling) is format-compatible."""
        payload = json.dumps(
            {"op": self.op, "data": self.data, "txid": self.txid},
            separators=(",", ":"),
        ).encode("utf-8")
        if encryptor is not None:
            payload = encryptor.encrypt(payload)
        if use_native:
            native_rec = _native.encode(payload, self.seq)
            if native_rec is not None:
                return native_rec
        rec = _HEADER.pack(MAGIC, VERSION, len(payload)) + payload
        rec += _FOOTER.pack(zlib.crc32(payload) & 0xFFFFFFFF, self.seq)
        pad = (-len(rec)) % 8
        return rec + b"\x00" * pad


def apply_storage_op(engine: Engine, op: str, d: dict[str, Any]) -> None:
    """Apply one logged storage op. Shared by WAL recovery and the
    replication layer (HA shipping, Raft apply) so the dispatch never forks.

    Idempotent-best-effort: duplicate create / missing delete after a
    snapshot race is not fatal (ref: wal.go replay tolerates
    AlreadyExists/NotFound during recovery)."""
    try:
        if op == OP_CREATE_NODE:
            engine.create_node(Node.from_dict(d))
        elif op == OP_UPDATE_NODE:
            engine.update_node(Node.from_dict(d))
        elif op == OP_DELETE_NODE:
            engine.delete_node(d["id"])
        elif op == OP_CREATE_EDGE:
            engine.create_edge(Edge.from_dict(d))
        elif op == OP_UPDATE_EDGE:
            engine.update_edge(Edge.from_dict(d))
        elif op == OP_DELETE_EDGE:
            engine.delete_edge(d["id"])
        elif op == OP_MARK_PENDING:
            engine.mark_pending_embed(d["id"])
        elif op == OP_UNMARK_PENDING:
            engine.unmark_pending_embed(d["id"])
    except Exception:
        # tolerated (duplicate create / missing delete after a snapshot
        # race), but silent data divergence is undebuggable — leave a trace
        log.debug("replayed op %s skipped", op, exc_info=True)


@dataclass
class WALStats:
    entries: int = 0
    bytes_written: int = 0
    snapshots: int = 0
    recovered_entries: int = 0
    truncated_tail_records: int = 0
    # appends that failed durability and were rolled back (DurabilityError
    # surfaced to the caller; nothing was acked)
    append_failures: int = 0
    # degraded mode (ref: wal_degraded.go): recovery stopped at MID-FILE
    # corruption with real records after it — data was lost, unlike the
    # benign torn-tail case. Surfaced via /status and /admin/stats.
    degraded: bool = False
    corruption_info: str = ""


class WAL:
    """Append-only log file + snapshot management (ref: storage.WAL wal.go:263).

    With a passphrase, record payloads and snapshots are encrypted at rest
    with AES-256-GCM (the reference delegates at-rest encryption to Badger
    with a PBKDF2-derived key, db.go:781-809; here the WAL is the storage of
    record so it encrypts its own payloads). The PBKDF2 salt persists next
    to the log.
    """

    LOG_NAME = "wal.log"
    SNAPSHOT_NAME = "snapshot.json"
    SALT_NAME = "wal.salt"

    def __init__(self, directory: str, sync: bool = False,
                 passphrase: Optional[str] = None):
        self.dir = directory
        self.sync = sync
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, self.LOG_NAME)
        self._lock = threading.Lock()
        self._tail_dirty = False
        # (scan end offset, file length) from the most recent read_all —
        # lets the open-time misalignment check reuse the scan it already
        # paid for instead of re-reading the whole log
        self._tail_scan = (0, 0)
        # resolve the native codec HERE, before any append can run: the
        # first _native.enabled() call dlopens (and may `make`-build) the
        # library — work that must never happen inside the append lock
        self._use_native = _native.enabled()
        self.stats = WALStats()
        self._encryptor = None
        if passphrase:
            from nornicdb_tpu.encryption import Encryptor, load_or_create_salt

            salt = load_or_create_salt(os.path.join(directory, self.SALT_NAME))
            self._encryptor = Encryptor.from_passphrase(passphrase, salt)
        self._seq = self._scan_last_seq()
        # seq must stay monotonic across restarts even when compact() left an
        # empty log: recovery filters replay on `seq > snapshot seq`, so a
        # counter reseeded from the (empty) log alone would hand out seqs the
        # filter silently drops — losing every write acked since the restart
        try:
            snap = self.load_snapshot()
            if snap is not None:
                self._seq = max(self._seq, int(snap.get("seq", 0)))
        except Exception:
            # corrupt/locked snapshot surfaces at recover(), not here
            log.debug("snapshot seq probe failed during WAL open",
                      exc_info=True)
        if self.stats.degraded:
            self._quarantine_corrupt_log()
        # benign torn tail (crash mid-append): the partial record must be
        # repaired before the FIRST append — otherwise the new record
        # lands on the torn bytes and every later record is stranded
        # behind them on the following replay (same contract as the raft
        # durable log's open path, raft.py).  Detection compares the file
        # length against the aligned end of the intact prefix, which also
        # catches a crash INSIDE the final record's alignment padding
        # (the record parses fine, so truncated_tail_records alone would
        # miss it).  Deferred to append() so read-only opens keep the
        # damaged bytes for strict-mode corruption diagnostics.
        self._needs_chop = (not self.stats.degraded
                            and self._tail_misaligned())
        self._f = open(self._path, "ab")

    # -- append ------------------------------------------------------------
    def append(self, op: str, data: dict[str, Any], txid: Optional[str] = None) -> int:
        t0 = _time.perf_counter()
        with _tracer.span("wal.append", {"op": op}):
            with self._lock:
                if self._tail_dirty:
                    # a failed append could not be repaired: appending past
                    # the damaged region would strand every new record
                    # behind it on replay (read_all stops at corruption)
                    raise DurabilityError(
                        "WAL tail damaged by an unrepaired append failure; "
                        "reopen the WAL to recover", kind="wal_disabled",
                    )
                if self._needs_chop:
                    self._needs_chop = False
                    self._f.close()
                    repaired = self._chop_torn_tail()
                    self._f = open(self._path, "ab")
                    if not repaired:
                        raise DurabilityError(
                            "WAL tail repair failed at first append; "
                            "reopen the WAL to retry", kind="wal_disabled",
                        )
                self._seq += 1
                entry = WALEntry(seq=self._seq, op=op, data=data, txid=txid)
                raw = entry.encode(self._encryptor, use_native=self._use_native)
                pos = self._f.tell()
                try:
                    _FAULTS.check_write(self._path, self._f, raw)
                    self._f.write(raw)
                    self._f.flush()
                    if self.sync:
                        # deliberate fsync under the WAL lock: sync=True is
                        # the durability mode — records must hit disk in
                        # seq order
                        t_fsync = _time.perf_counter()
                        try:
                            _FAULTS.check_fsync(self._path)
                            os.fsync(self._f.fileno())  # nornlint: disable=NL-LK02
                        except OSError as e:
                            # tag the failing stage: the message of a real
                            # fsync EIO carries no hint of where it came
                            # from, and the failure-kind metric must not
                            # depend on string contents
                            e.nornicdb_stage = "fsync"
                            raise
                        _WAL_FSYNC_HIST.observe(_time.perf_counter() - t_fsync)
                except OSError as e:
                    self._abort_append(pos, e)  # raises DurabilityError
                self.stats.entries += 1
                self.stats.bytes_written += len(raw)
                seq = self._seq
        _WAL_APPEND_HIST.observe(_time.perf_counter() - t0)
        return seq

    def _abort_append(self, pos: int, cause: OSError) -> None:
        """A record failed to become durable (write error, torn tail,
        ENOSPC, fsync failure).  Roll the append back so the log ends at
        its last good record: the seq is un-issued (recovery filters on
        seq ordering, so a hole would silently drop later replays) and any
        partially-written tail bytes are truncated away.  Always raises
        :class:`DurabilityError` — the caller must NOT ack the write."""
        self._seq -= 1
        self.stats.append_failures += 1
        kind = ("enospc" if cause.errno == _errno.ENOSPC
                else getattr(cause, "nornicdb_stage", None) or "io")
        _WAL_APPEND_FAILURES.labels(kind).inc()
        repairable = getattr(cause, "nornicdb_repairable", True)
        if repairable:
            try:
                self._f.seek(pos)
                self._f.truncate(pos)
                self._f.flush()
            except OSError:
                log.error("WAL tail repair after failed append at offset %d "
                          "also failed; disabling appends until reopen",
                          pos, exc_info=True)
                self._tail_dirty = True
        else:
            # crash-shaped: the torn bytes stay on disk; replay stops at
            # the last good record (benign torn tail) but appending past
            # them would strand new records — require a reopen
            self._tail_dirty = True
        raise DurabilityError(
            f"WAL append not durable: {cause}", kind=kind,
        ) from cause

    @property
    def last_seq(self) -> int:
        return self._seq

    def _decrypt(self, payload: bytes) -> bytes:
        if self._encryptor is None:
            return payload
        return self._encryptor.decrypt(payload)

    # -- read / replay -----------------------------------------------------
    def read_all(self, strict: bool = False) -> list[WALEntry]:
        """Read every valid record. A corrupt/torn tail stops the scan; with
        strict=True it raises WALCorruptionError instead (ref: corruption
        diagnostics wal.go:75-110)."""
        entries: list[WALEntry] = []
        try:
            with open(self._path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            self._tail_scan = (0, 0)
            return entries
        # opt-in native path: C++ does framing + CRC sweep; Python parses JSON
        native_out = _native.scan(buf) if _native.enabled() else None
        if native_out is not None:
            records, valid_bytes = native_out
            self._tail_scan = (valid_bytes, len(buf))
            if valid_bytes < len(buf):
                if strict:
                    raise WALCorruptionError(
                        f"bad record at offset {valid_bytes}"
                    )
                self.stats.truncated_tail_records += 1
                self._note_corruption(valid_bytes, len(buf), buf)
            for idx, (payload, seq) in enumerate(records):
                try:
                    obj = json.loads(self._decrypt(payload).decode("utf-8"))
                except Exception:
                    if strict:
                        raise WALCorruptionError("bad payload")
                    self.stats.truncated_tail_records += 1
                    if idx < len(records) - 1:
                        # CRC-valid records FOLLOW the undecodable one:
                        # committed data is being dropped -> degraded
                        self.stats.degraded = True
                        self.stats.corruption_info = (
                            f"undecodable payload at record {idx}; "
                            f"{len(records) - idx - 1} later records skipped"
                        )
                    break
                entries.append(
                    WALEntry(seq=seq, op=obj["op"], data=obj.get("data", {}),
                             txid=obj.get("txid"))
                )
            return entries
        off = 0
        n = len(buf)
        while off + _HEADER.size <= n:
            magic, ver, oplen = _HEADER.unpack_from(buf, off)
            body_end = off + _HEADER.size + oplen + _FOOTER.size
            if magic != MAGIC or ver != VERSION or body_end > n:
                if strict:
                    raise WALCorruptionError(f"bad record header at offset {off}")
                self.stats.truncated_tail_records += 1
                self._note_corruption(off, n, buf)
                break
            payload = buf[off + _HEADER.size : off + _HEADER.size + oplen]
            crc, seq = _FOOTER.unpack_from(buf, off + _HEADER.size + oplen)
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                if strict:
                    raise WALCorruptionError(f"CRC mismatch at offset {off}")
                self.stats.truncated_tail_records += 1
                self._note_corruption(off, n, buf)
                break
            try:
                obj = json.loads(self._decrypt(payload).decode("utf-8"))
            except Exception:
                if strict:
                    raise WALCorruptionError(f"bad payload at offset {off}")
                self.stats.truncated_tail_records += 1
                self._note_corruption(off, n, buf)
                break
            entries.append(
                WALEntry(seq=seq, op=obj["op"], data=obj.get("data", {}), txid=obj.get("txid"))
            )
            off = body_end + ((-(body_end - off)) % 8)
        # where the scan actually stopped vs the file length: the open-time
        # misalignment check compares these (clean exit leaves off at the
        # aligned end; any break leaves it at the bad record's start)
        self._tail_scan = (off, n)
        return entries

    def _note_corruption(self, offset: int, total: int,
                         buf: Optional[bytes] = None) -> None:
        """Classify a recovery stop (ref: wal_degraded.go). A torn tail
        (crash mid-append: the FINAL record is partial) is expected and
        benign. If any intact record exists after the corruption point,
        committed data was lost -> degraded mode."""
        if buf is None or not self._has_valid_record_after(buf, offset):
            return
        self.stats.degraded = True
        self.stats.corruption_info = (
            f"unreadable record at offset {offset} with intact records "
            f"after it; {total - offset} bytes were skipped"
        )

    @staticmethod
    def _has_valid_record_after(buf: bytes, offset: int) -> bool:
        pos = buf.find(MAGIC, offset + 1)
        while pos != -1:
            if pos + _HEADER.size <= len(buf):
                magic, ver, oplen = _HEADER.unpack_from(buf, pos)
                end = pos + _HEADER.size + oplen + _FOOTER.size
                if ver == VERSION and end <= len(buf):
                    payload = buf[pos + _HEADER.size : pos + _HEADER.size + oplen]
                    crc, _seq = _FOOTER.unpack_from(buf, pos + _HEADER.size + oplen)
                    if zlib.crc32(payload) & 0xFFFFFFFF == crc:
                        return True
            pos = buf.find(MAGIC, pos + 1)
        return False

    def _quarantine_corrupt_log(self) -> None:
        """Degraded open: appending after a corrupt region would strand
        every new record behind it on the next recovery (read_all stops at
        the corruption). Preserve the damaged file for forensics, then
        rewrite the log with only the readable records so subsequent
        appends stay recoverable. The degraded flag stays set."""
        n = 1
        while os.path.exists(f"{self._path}.corrupt-{n}"):
            n += 1
        os.replace(self._path, f"{self._path}.corrupt-{n}")
        try:
            with open(f"{self._path}.corrupt-{n}", "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            buf = b""
        with open(self._path, "wb") as out:
            for e in self._parse_buffer(buf):
                out.write(e.encode(self._encryptor, use_native=self._use_native))
            out.flush()
            os.fsync(out.fileno())
        self.stats.corruption_info += (
            f"; valid prefix rewritten, damaged log kept as "
            f"{os.path.basename(self._path)}.corrupt-{n}"
        )

    @staticmethod
    def _iter_frames(buf: bytes):
        """Yield ``(payload, seq, end_off)`` for each intact leading
        record, stopping at the first bad header / short body / CRC
        mismatch — the frame-walk shared by the torn-tail repair and the
        quarantine salvage scan.  (``read_all`` keeps its own walk: it
        needs per-stop diagnostics — WHICH offset failed and why — for
        strict mode and degraded-mode classification.)  The last yielded
        ``end_off`` is the aligned intact-prefix length and may exceed
        ``len(buf)`` when the final record's padding was cut short."""
        off = 0
        n = len(buf)
        while off + _HEADER.size <= n:
            magic, ver, oplen = _HEADER.unpack_from(buf, off)
            body_end = off + _HEADER.size + oplen + _FOOTER.size
            if magic != MAGIC or ver != VERSION or body_end > n:
                return
            payload = buf[off + _HEADER.size : off + _HEADER.size + oplen]
            crc, seq = _FOOTER.unpack_from(buf, off + _HEADER.size + oplen)
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                return
            off = body_end + ((-(body_end - off)) % 8)
            yield payload, seq, off

    def _parse_buffer(self, buf: bytes) -> list[WALEntry]:
        """Parse records from a raw buffer (decrypted), stopping at the
        first unreadable record. Used by quarantine; does not touch stats."""
        entries: list[WALEntry] = []
        for payload, seq, off in self._iter_frames(buf):
            try:
                obj = json.loads(self._decrypt(payload).decode("utf-8"))
            except Exception:
                # corrupt record: keep only the prefix (quarantine semantics)
                log.warning("undecodable WAL record before offset %d stops "
                            "the salvage scan", off, exc_info=True)
                break
            entries.append(WALEntry(seq=seq, op=obj["op"],
                                    data=obj.get("data", {}),
                                    txid=obj.get("txid")))
        return entries

    def _intact_prefix_end(self, buf: bytes) -> int:
        """Aligned end offset of the intact leading records.  May exceed
        ``len(buf)`` when a crash cut the final record's alignment
        padding short (the record itself is whole)."""
        off = 0
        for _payload, _seq, off in self._iter_frames(buf):
            pass
        return off

    def _tail_misaligned(self) -> bool:
        """True when the file does not end exactly at the aligned end of
        its intact prefix — torn garbage after it, or short padding.
        Reuses the scan _scan_last_seq already paid for (``_tail_scan``)
        instead of re-reading the log."""
        end, n = self._tail_scan
        return n > 0 and end != n

    def _chop_torn_tail(self) -> bool:
        """Repair the log tail before the first append: truncate torn
        bytes after the last intact record, or complete a final record's
        crash-shortened alignment padding with zeros.  Only reached for a
        benign torn tail — mid-file corruption takes the quarantine path.
        Returns False (and poisons the tail) when the repair itself
        failed: appending past unrepaired damage would strand every new
        record on the next replay."""
        try:
            with open(self._path, "rb") as f:
                buf = f.read()
        except FileNotFoundError:
            return True
        n = len(buf)
        end = self._intact_prefix_end(buf)
        try:
            if end < n:
                log.warning("chopping %d torn tail bytes off %s at offset "
                            "%d", n - end, self._path, end)
                os.truncate(self._path, end)
            elif end > n:
                # crash inside the trailing padding: the record is whole,
                # only zero-padding is missing — complete it in place
                log.warning("completing %d missing padding bytes on %s",
                            end - n, self._path)
                with open(self._path, "ab") as f:
                    f.write(b"\x00" * (end - n))
                    f.flush()
                    # deliberate fsync under the WAL lock: this one-time
                    # open repair must be durable before the append that
                    # triggered it lands — same serialized-durability
                    # contract as append() itself
                    os.fsync(f.fileno())  # nornlint: disable=NL-LK02
        except OSError:
            log.error("torn-tail repair failed; disabling appends until "
                      "reopen", exc_info=True)
            self._tail_dirty = True
            return False
        return True

    def _scan_last_seq(self) -> int:
        last = 0
        for e in self.read_all():
            last = max(last, e.seq)
        return last

    def verify_integrity(self) -> tuple[int, bool]:
        """Returns (valid_records, clean). clean=False when a torn tail was hit."""
        before = self.stats.truncated_tail_records
        entries = self.read_all()
        return len(entries), self.stats.truncated_tail_records == before

    # -- snapshot / compaction --------------------------------------------
    def snapshot_state(self, engine: Engine) -> dict[str, Any]:
        """In-memory engine dump (no IO) — callable under a write-blocking
        lock so serialization and disk writes can happen outside it."""
        return {
            "seq": self._seq,
            "nodes": [n.to_dict() for n in engine.all_nodes()],
            "edges": [e.to_dict() for e in engine.all_edges()],
            "pending_embed": engine.pending_embed_ids(),
        }

    def create_snapshot(self, engine: Engine) -> str:
        """Full engine dump (ref: WAL.CreateSnapshot wal.go:819)."""
        return self.write_snapshot(self.snapshot_state(engine))

    def write_snapshot(self, snap: dict[str, Any]) -> str:
        path = os.path.join(self.dir, self.SNAPSHOT_NAME)
        tmp = path + ".tmp"
        blob = json.dumps(snap).encode("utf-8")
        if self._encryptor is not None:
            blob = b"NSNAPENC" + self._encryptor.encrypt(blob)
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            # deliberate fsync under the compact lock (never the mutation
            # lock): _compact_lock exists to host exactly this slow disk
            # work so concurrent appends don't stall — see WALEngine.compact
            os.fsync(f.fileno())  # nornlint: disable=NL-LK02
        os.replace(tmp, path)
        self.stats.snapshots += 1
        return path

    def truncate_after_snapshot(self) -> None:
        """Drop the log; the snapshot now carries all state up to its seq
        (ref: TruncateAfterSnapshot wal.go:938)."""
        with self._lock:
            self._f.close()
            self._f = open(self._path, "wb")
            self._tail_dirty = False  # fresh file: damaged tail is gone
            self._needs_chop = False

    def truncate_up_to(self, seq: int) -> None:
        """Rewrite the log keeping only entries with seq > `seq` (appended
        while the snapshot was being written; recovery replays exactly those
        on top of the snapshot). Atomic via tmp+replace."""
        with self._lock:
            self._f.close()
            keep = [e for e in self.read_all() if e.seq > seq]
            tmp = self._path + ".tmp"
            with open(tmp, "wb") as f:
                for e in keep:
                    f.write(e.encode(self._encryptor, use_native=self._use_native))
                f.flush()
                # deliberate fsync under the WAL lock: truncation races an
                # in-flight append otherwise — same serialized-durability
                # contract as append() itself
                os.fsync(f.fileno())  # nornlint: disable=NL-LK02
            os.replace(tmp, self._path)
            self._f = open(self._path, "ab")
            self._tail_dirty = False  # rewrite kept only intact records
            self._needs_chop = False

    def load_snapshot(self) -> Optional[dict[str, Any]]:
        path = os.path.join(self.dir, self.SNAPSHOT_NAME)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            blob = f.read()
        if blob.startswith(b"NSNAPENC"):
            if self._encryptor is None:
                raise WALCorruptionError(
                    "snapshot is encrypted; passphrase required"
                )
            blob = self._encryptor.decrypt(blob[8:])
        return json.loads(blob.decode("utf-8"))

    # -- recovery ----------------------------------------------------------
    def recover(self, engine: Engine) -> int:
        """Load snapshot + replay tail with incomplete-transaction undo
        (ref: RecoverWithTransactions wal.go:1845). Returns replayed count."""
        snap = self.load_snapshot()
        snap_seq = 0
        if snap is not None:
            snap_seq = snap.get("seq", 0)
            for nd in snap.get("nodes", []):
                engine.create_node(Node.from_dict(nd))
            for ed in snap.get("edges", []):
                engine.create_edge(Edge.from_dict(ed))
            for nid in snap.get("pending_embed", []):
                engine.mark_pending_embed(nid)

        entries = [e for e in self.read_all() if e.seq > snap_seq]
        # First pass: find committed transactions.
        committed: set[str] = set()
        rolled_back: set[str] = set()
        seen_tx: set[str] = set()
        for e in entries:
            if e.op == OP_TX_BEGIN and e.txid:
                seen_tx.add(e.txid)
            elif e.op == OP_TX_COMMIT and e.txid:
                committed.add(e.txid)
            elif e.op == OP_TX_ROLLBACK and e.txid:
                rolled_back.add(e.txid)
        # Second pass: apply non-tx ops and ops of committed transactions only.
        applied = 0
        for e in entries:
            if e.op in (OP_TX_BEGIN, OP_TX_COMMIT, OP_TX_ROLLBACK):
                continue
            if e.txid is not None and e.txid not in committed:
                continue  # incomplete or rolled-back tx: skip (undo-by-omission)
            self._apply(engine, e)
            applied += 1
        self.stats.recovered_entries = applied
        return applied

    @staticmethod
    def _apply(engine: Engine, e: WALEntry) -> None:
        apply_storage_op(engine, e.op, e.data)

    def close(self) -> None:
        with self._lock:
            self._f.close()


class WALEngine(Engine):
    """Write-ahead decorator: every mutation is logged before it is applied
    (ref: NewWALEngine wal_engine.go:45; auto-compaction wal_engine.go:65-149).
    """

    def __init__(
        self,
        base: Engine,
        wal: WAL,
        auto_compact_interval: float = 300.0,
        auto_compact: bool = False,
    ):
        super().__init__()
        self.base = base
        self.wal = wal
        self._txid: Optional[str] = None  # set by transaction scope
        # serializes whole mutations (log + apply) against compaction: a
        # record appended after the snapshot's engine dump but before the
        # truncate would otherwise be erased yet absent from the snapshot,
        # losing the write on recovery (reachable via the auto-compact timer)
        self._mut_lock = threading.RLock()
        # serializes compact-vs-compact: Timer.cancel() cannot stop an
        # already-running tick, so close()'s final compact could otherwise
        # interleave with it (older snapshot overwriting a newer one while
        # the log is truncated past it)
        self._compact_lock = threading.Lock()
        self._compact_timer: Optional[threading.Timer] = None
        self._auto_compact_interval = auto_compact_interval
        self._closed = False
        base.on_event(self._emit)  # forward base events
        if auto_compact:
            self._schedule_compact()

    def _schedule_compact(self) -> None:
        if self._closed:
            return
        self._compact_timer = threading.Timer(self._auto_compact_interval, self._compact_tick)
        self._compact_timer.daemon = True
        self._compact_timer.start()

    def _compact_tick(self) -> None:
        try:
            self.compact()
        except Exception:
            # the next tick retries, but a persistently failing compaction
            # means unbounded log growth — operators need the trace
            log.warning("WAL auto-compaction failed; will retry",
                        exc_info=True)
        self._schedule_compact()

    def compact(self) -> None:
        """Snapshot + truncate (ref: wal_engine.go:65-149, 5-min default).

        _mut_lock is held only for the in-memory engine dump; serialization,
        fsync, and the log rewrite happen outside it, so writes stall for the
        copy, not the disk IO. The truncate keeps entries newer than the
        snapshot's seq (appended during the write) — recovery replays exactly
        those on top of the snapshot (recover() filters on seq > snap seq).

        Deferred while an explicit transaction is open: the base engine holds
        the tx's uncommitted ops, so a snapshot taken now would bake them in
        while dropping their txid-tagged records — recovery could then no
        longer undo an incomplete transaction (ref: tx-aware recovery
        wal.go:1845). The auto-compact timer retries next interval; protocol
        layers roll back on RESET/disconnect so a vanished client cannot
        defer compaction forever (bolt.py abort_tx).
        """
        with self._compact_lock:
            if self._closed:
                return
            with self._mut_lock:
                if self._txid is not None:
                    return
                snap = self.wal.snapshot_state(self.base)
            self.wal.write_snapshot(snap)
            self.wal.truncate_up_to(snap["seq"])

    # -- transaction scoping ----------------------------------------------
    def tx_begin(self, txid: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_TX_BEGIN, {}, txid=txid)
            self._txid = txid

    def tx_commit(self, txid: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_TX_COMMIT, {}, txid=txid)
            self._txid = None

    def tx_rollback(self, txid: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_TX_ROLLBACK, {}, txid=txid)
            self._txid = None

    # -- mutations (log first, then apply; atomic vs compact) ---------------
    def create_node(self, node: Node) -> Node:
        with self._mut_lock:
            self.wal.append(OP_CREATE_NODE, node.to_dict(), txid=self._txid)
            return self.base.create_node(node)

    def update_node(self, node: Node) -> Node:
        with self._mut_lock:
            self.wal.append(OP_UPDATE_NODE, node.to_dict(), txid=self._txid)
            return self.base.update_node(node)

    def delete_node(self, node_id: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_DELETE_NODE, {"id": node_id}, txid=self._txid)
            self.base.delete_node(node_id)

    def create_edge(self, edge: Edge) -> Edge:
        with self._mut_lock:
            self.wal.append(OP_CREATE_EDGE, edge.to_dict(), txid=self._txid)
            return self.base.create_edge(edge)

    def update_edge(self, edge: Edge) -> Edge:
        with self._mut_lock:
            self.wal.append(OP_UPDATE_EDGE, edge.to_dict(), txid=self._txid)
            return self.base.update_edge(edge)

    def delete_edge(self, edge_id: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_DELETE_EDGE, {"id": edge_id}, txid=self._txid)
            self.base.delete_edge(edge_id)

    def mark_pending_embed(self, node_id: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_MARK_PENDING, {"id": node_id}, txid=self._txid)
            self.base.mark_pending_embed(node_id)

    def unmark_pending_embed(self, node_id: str) -> None:
        with self._mut_lock:
            self.wal.append(OP_UNMARK_PENDING, {"id": node_id}, txid=self._txid)
            self.base.unmark_pending_embed(node_id)

    # -- reads: delegate ---------------------------------------------------
    def get_node(self, node_id: str) -> Node:
        return self.base.get_node(node_id)

    def get_nodes_by_label(self, label: str) -> list[Node]:
        return self.base.get_nodes_by_label(label)

    def all_nodes(self):
        return self.base.all_nodes()

    def all_node_ids(self):
        return self.base.all_node_ids()  # AttributeError -> caller fallback

    def batch_get_nodes(self, ids):
        return self.base.batch_get_nodes(ids)

    def get_edge(self, edge_id: str) -> Edge:
        return self.base.get_edge(edge_id)

    def get_edges_by_type(self, edge_type: str) -> list[Edge]:
        return self.base.get_edges_by_type(edge_type)

    def get_outgoing_edges(self, node_id: str) -> list[Edge]:
        return self.base.get_outgoing_edges(node_id)

    def get_incoming_edges(self, node_id: str) -> list[Edge]:
        return self.base.get_incoming_edges(node_id)

    def all_edges(self):
        return self.base.all_edges()

    def node_count(self) -> int:
        return self.base.node_count()

    def edge_count(self) -> int:
        return self.base.edge_count()

    def count_nodes_by_label(self, label: str) -> int:
        return self.base.count_nodes_by_label(label)

    def count_edges_by_type(self, edge_type: str) -> int:
        return self.base.count_edges_by_type(edge_type)

    def pending_embed_ids(self, limit: int = 0) -> list[str]:
        return self.base.pending_embed_ids(limit)

    def flush(self) -> None:
        self.base.flush()

    def close(self) -> None:
        if self._compact_timer is not None:
            self._compact_timer.cancel()
        self.compact()  # final snapshot; serialized with any in-flight tick
        with self._compact_lock:
            # an in-flight tick has finished; nothing may touch the WAL after
            self._closed = True
        self.wal.close()
        self.base.close()
