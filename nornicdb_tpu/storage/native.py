"""ctypes bindings for the native WAL codec (native/walcodec.cc).

The reference implements its hot native paths in CUDA/Metal/ObjC; here the
TPU compute path is JAX, so native C++ covers the host runtime instead —
starting with WAL record framing + CRC sweeps (the durability hot path,
ref: pkg/storage/wal_atomic_record.go). Built on demand when g++ is
available, loaded via dlopen with no hard dependency (same spirit as the
reference's purego path, pkg/gpu/vulkan/vulkan_purego.go).

Measured honestly (50k records, 280B JSON payloads): the per-record ctypes
marshalling makes this codec 0.8-1.0x of the pure-Python path, because
Python's zlib.crc32/struct are already C and the payload slices must cross
into Python regardless. It is therefore OPT-IN (NORNICDB_NATIVE_WAL=1) and
exists as the tested foundation for the next native step — a C++ segment
store where payload bytes stay native end-to-end instead of crossing the
FFI per record.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

# NORNICDB_NATIVE_DIR overrides for installed deployments (Docker image
# places prebuilt .so files outside the source tree)
_NATIVE_DIR = os.environ.get("NORNICDB_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libwalcodec.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "walcodec.cc")
    if not os.path.exists(src):
        return False
    try:
        # deliberate subprocess under the module load lock: this is the
        # build-once gate — it runs a single time per process, at load()
        # time, and engines resolve the codec at init (WAL.__init__), never
        # inside their own append/flush critical sections
        subprocess.run(  # nornlint: disable=NL-LK02
            ["make", "-C", _NATIVE_DIR],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the codec library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.wal_encode.restype = ctypes.c_int64
        lib.wal_encode.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
        ]
        lib.wal_scan.restype = ctypes.c_int64
        lib.wal_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.wal_crc32.restype = ctypes.c_uint32
        lib.wal_crc32.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def enabled() -> bool:
    """Native WAL codec is opt-in (see module docstring for the measurement)."""
    return os.environ.get("NORNICDB_NATIVE_WAL", "").lower() in ("1", "true") and available()


def encode(payload: bytes, seq: int) -> Optional[bytes]:
    # hot path: uses the handle cached by a prior load()/enabled() call and
    # never takes the module lock — WAL.append runs this under its own lock,
    # and re-entering load() there would put the (first-call) compiler build
    # inside the WAL critical section
    lib = _lib
    if lib is None:
        return None
    cap = len(payload) + 32
    out = (ctypes.c_uint8 * cap)()
    n = lib.wal_encode(payload, len(payload), seq, out, cap)
    if n < 0:
        return None
    return bytes(out[:n])


_MIN_RECORD = 24  # header(9) + footer(12) padded to 8


def scan(buf: bytes, max_records: int = 0):
    """Returns (records, valid_bytes) where records = [(payload, seq), ...],
    or None when the native library is unavailable."""
    lib = _lib  # cached by a prior load()/enabled(); see encode()
    if lib is None:
        return None
    if max_records <= 0:
        max_records = max(len(buf) // _MIN_RECORD + 1, 1)
    offsets = (ctypes.c_uint64 * max_records)()
    lengths = (ctypes.c_uint64 * max_records)()
    seqs = (ctypes.c_uint64 * max_records)()
    valid = ctypes.c_uint64(0)
    n = lib.wal_scan(
        buf, len(buf), offsets, lengths, seqs, max_records,
        ctypes.byref(valid),
    )
    records = [
        (buf[offsets[i] : offsets[i] + lengths[i]], int(seqs[i]))
        for i in range(n)
    ]
    return records, int(valid.value)
