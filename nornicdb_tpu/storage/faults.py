"""Deterministic storage fault injection at the WAL write/fsync seams.

The reference proves WAL resilience with chaos suites that corrupt logs
offline (tests/test_storage_chaos.py here); this module makes the same
fault classes injectable into a LIVE WAL so the soak harness
(nornicdb_tpu.soak) can compose storage faults with replication and
backend faults in one run:

* ``fsync_fail``  — the durability fsync raises EIO.  The record already
  hit the page cache but its durability promise is void: the append is
  rolled back (tail truncated to the last good record) and surfaces as a
  typed :class:`~nornicdb_tpu.errors.DurabilityError`; nothing is acked.
* ``torn_tail``   — only a prefix of the framed record reaches the file
  before the write "fails" mid-flight (crash-shaped partial record).
  With repair enabled (the default) the WAL truncates the torn bytes so
  later appends stay recoverable; with ``repairable=False`` the partial
  record is left in place, exactly like a power cut mid-append — replay
  then stops at the last good record (torn-tail recovery).
* ``enospc``      — the write raises ENOSPC before any byte lands
  (transient full disk).  Disarm and the next append succeeds.

Faults are **armed, counted, and scoped**: each plan fires ``count``
times against paths under ``path_prefix`` (empty = any WAL), then goes
inert.  The process-global :data:`INJECTOR` is deliberately inert by
default — production code pays one attribute read per append.
"""

from __future__ import annotations

import errno
import os
import threading
from dataclasses import dataclass

from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY

KINDS = ("fsync_fail", "torn_tail", "enospc")

_INJECTED = _REGISTRY.counter(
    "nornicdb_storage_faults_injected_total",
    "Storage faults fired by the deterministic injector (soak/chaos runs)",
    labels=("kind",),
)
for _k in KINDS:
    _INJECTED.labels(_k)  # eager cells: render at 0 before the first fault


@dataclass
class FaultPlan:
    kind: str
    remaining: int = 1
    path_prefix: str = ""  # "" matches every WAL path
    repairable: bool = True  # torn_tail only: allow the WAL tail repair
    fired: int = 0


class StorageFaultInjector:
    """Armed fault plans consulted by ``WAL.append`` at its two seams
    (record write, durability fsync).  Thread-safe; plans are consumed
    deterministically in arm order."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._plans: list[FaultPlan] = []
        self.fired: dict[str, int] = {k: 0 for k in KINDS}
        # lock-free inert flag: WAL.append reads this (one attribute read)
        # before touching the lock, so an unarmed injector adds no
        # cross-WAL contention to the durability hot path.  Updated under
        # the lock by arm/disarm/_take; stale-True just means one extra
        # locked check, stale-False cannot happen (arm sets it last).
        self.armed = False

    # -- arming ------------------------------------------------------------
    def arm(self, kind: str, count: int = 1, path_prefix: str = "",
            repairable: bool = True) -> FaultPlan:
        if kind not in KINDS:
            raise ValueError(f"unknown storage fault kind {kind!r}")
        # normalize: WALs opened via a relative data_dir carry relative
        # paths, and a prefix armed with an absolute path must still
        # match.  The trailing separator makes the match component-wise:
        # a prefix of <data>/wal must not fire against <data>/wal2
        plan = FaultPlan(kind=kind, remaining=int(count),
                         path_prefix=(os.path.abspath(path_prefix) + os.sep
                                      if path_prefix else ""),
                         repairable=repairable)
        with self._lock:
            self._plans.append(plan)
            self.armed = True
        return plan

    def disarm(self, kind: str | None = None) -> None:
        with self._lock:
            if kind is None:
                self._plans.clear()
            else:
                self._plans = [p for p in self._plans if p.kind != kind]
            self.armed = any(p.remaining > 0 for p in self._plans)

    def active(self) -> bool:
        with self._lock:
            return any(p.remaining > 0 for p in self._plans)

    def _take(self, kind: str, path: str) -> FaultPlan | None:
        """Consume one shot of the first matching armed plan, or None."""
        if not self.armed:  # lock-free: the common production path
            return None
        with self._lock:
            abs_path = os.path.abspath(path)
            taken = None
            for p in self._plans:
                if p.kind != kind or p.remaining <= 0:
                    continue
                if p.path_prefix and not (abs_path + os.sep).startswith(
                        p.path_prefix):
                    continue
                p.remaining -= 1
                p.fired += 1
                self.fired[kind] += 1
                _INJECTED.labels(kind).inc()
                taken = p
                break
            self.armed = any(p.remaining > 0 for p in self._plans)
            return taken

    # -- seams (called by WAL.append under its lock; must never block) -----
    def check_write(self, path: str, f, raw: bytes) -> bool:
        """Write seam.  Returns True when the full record may be written;
        raises OSError for an injected write fault.  ``torn_tail`` writes
        the partial prefix itself before raising, so the file looks
        exactly like a crash mid-append."""
        plan = self._take("enospc", path)
        if plan is not None:
            raise OSError(errno.ENOSPC,
                          "injected transient ENOSPC (storage fault)")
        plan = self._take("torn_tail", path)
        if plan is not None:
            f.write(raw[: max(1, len(raw) // 2)])
            f.flush()
            e = OSError(errno.EIO, "injected torn tail write (storage fault)")
            e.nornicdb_repairable = plan.repairable
            raise e
        return True

    def check_fsync(self, path: str) -> None:
        """Fsync seam.  Raises OSError when an ``fsync_fail`` plan is armed;
        the caller then rolls the un-durable record back off the tail."""
        if self._take("fsync_fail", path) is not None:
            raise OSError(errno.EIO, "injected fsync failure (storage fault)")


#: process-global injector, inert unless a chaos/soak driver arms it
INJECTOR = StorageFaultInjector()
