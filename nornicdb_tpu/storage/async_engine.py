"""Write-behind async engine decorator.

Behavioral reference: /root/reference/pkg/storage/async_engine.go —
mutations buffer in an in-memory overlay and flush to the base engine on a
short interval (~50ms in the reference); reads consult the overlay first so
the engine is read-your-writes consistent; counts combine overlay + base
(the reference grew dedicated regression tests for that:
async_engine_count_flush_race_test.go, async_count_bug_test.go).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterable, Iterator, Optional

from nornicdb_tpu.errors import AlreadyExistsError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Engine, Node
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

_FLUSH_HIST = _REGISTRY.histogram(
    "nornicdb_async_flush_seconds",
    "AsyncEngine overlay flush duration (only flushes that drained ops)",
)
_FLUSH_OPS = _REGISTRY.counter(
    "nornicdb_async_flush_ops_total",
    "Overlay operations drained to the base engine",
)

_TOMBSTONE = object()


class AsyncEngine(Engine):
    def __init__(self, base: Engine, flush_interval: float = 0.05):
        super().__init__()
        self.base = base
        self.flush_interval = flush_interval
        self._lock = threading.RLock()
        # overlay: id -> Node/Edge (pending upsert) or _TOMBSTONE (pending delete)
        self._nodes: dict[str, object] = {}
        self._edges: dict[str, object] = {}
        self._node_is_create: set[str] = set()
        self._edge_is_create: set[str] = set()
        self._flush_lock = threading.Lock()
        self._closed = False
        # trace hand-off across the flush hop: the FIRST writer into an
        # empty overlay becomes the batch leader — the (often background)
        # flush that drains the batch attaches that writer's span so
        # storage.flush lands in the originating request's trace
        self._flush_ctx = None
        # Creates/updates are emitted by THIS engine at write time; the base
        # engine's events for those same ops fire later at flush and would
        # double-notify listeners. Node deletes run directly against the
        # base (incl. edge cascades), so the base's events are
        # authoritative there. Edge deletes are emitted at write time too —
        # a tombstoned edge is already invisible to reads, and event-
        # maintained indexes (adjacency snapshot, namespaced counts) must
        # not serve it until flush — so the base's flush-time replay of the
        # same delete is suppressed by id. A create deleted before it ever
        # flushed never reaches the base at all; without the write-time
        # emit no listener would ever hear about its deletion.
        self._deleted_emitted: set[str] = set()
        base.on_event(self._forward_base_event)
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True)
        self._flusher.start()

    def _forward_base_event(self, kind: str, entity) -> None:
        if kind == "edge_deleted":
            with self._lock:
                if entity.id in self._deleted_emitted:
                    self._deleted_emitted.discard(entity.id)
                    return  # already announced at write time
            self._emit(kind, entity)
        elif kind == "node_deleted":
            self._emit(kind, entity)

    # -- flush loop --------------------------------------------------------
    def _flush_loop(self) -> None:
        stop = threading.Event()
        while not self._closed:
            stop.wait(self.flush_interval)
            try:
                self.flush()
            except Exception:
                # the loop must survive, but a failing flush means the
                # overlay is not draining — writes pile up silently
                log.warning("background flush failed; retrying next tick",
                            exc_info=True)

    def flush(self) -> None:
        """Drain the overlay into the base engine, preserving op order per id.

        Serialized: an explicit flush must not return while a background
        flush that already popped overlay entries is still applying them
        (counts would transiently miss those entries)."""
        with self._flush_lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        with self._lock:
            nodes = list(self._nodes.items())
            node_creates = set(self._node_is_create)
            edges = list(self._edges.items())
            edge_creates = set(self._edge_is_create)
            self._nodes.clear()
            self._edges.clear()
            self._node_is_create.clear()
            self._edge_is_create.clear()
            ctx, self._flush_ctx = self._flush_ctx, None
        n_ops = len(nodes) + len(edges)
        if n_ops == 0:
            # read-path flushes with an empty overlay are the common case;
            # recording them would drown the histogram in ~0 samples
            self.base.flush()
            return
        t0 = time.perf_counter()
        # the batch leader's trace (first writer into this overlay window)
        # adopts the flush — a background drain still shows up in the
        # request trace that caused it
        with _tracer.attach(ctx):
            with _tracer.span("storage.flush", {"ops": n_ops}):
                self._apply_ops(nodes, node_creates, edges, edge_creates)
                self.base.flush()
        _FLUSH_HIST.observe(time.perf_counter() - t0)
        _FLUSH_OPS.inc(n_ops)

    def _apply_ops(self, nodes, node_creates, edges, edge_creates) -> None:
        for nid, val in nodes:
            try:
                if val is _TOMBSTONE:
                    try:
                        self.base.delete_node(nid)
                    except NotFoundError:
                        pass
                elif nid in node_creates:
                    self.base.create_node(val)  # type: ignore[arg-type]
                else:
                    self.base.update_node(val)  # type: ignore[arg-type]
            except Exception:
                # the overlay entry is already popped: this node write is
                # LOST if we stay silent
                log.error("flush dropped node op for %s", nid, exc_info=True)
        for eid, val in edges:
            try:
                if val is _TOMBSTONE:
                    try:
                        self.base.delete_edge(eid)
                    except NotFoundError:
                        pass
                elif eid in edge_creates:
                    try:
                        self.base.create_edge(val)  # type: ignore[arg-type]
                    except AlreadyExistsError:
                        # this create overwrote a same-id tombstone in the
                        # overlay, so the delete never reached the base:
                        # apply as an update, not a lost write
                        self.base.update_edge(val)  # type: ignore[arg-type]
                else:
                    self.base.update_edge(val)  # type: ignore[arg-type]
            except Exception:
                # same contract as the node loop above: dropped == lost
                log.error("flush dropped edge op for %s", eid, exc_info=True)

    def _note_writer_locked(self) -> None:
        """First writer into an empty overlay claims flush-trace leadership
        (one contextvar read; None when the writer isn't traced)."""
        if self._flush_ctx is None:
            self._flush_ctx = _tracer.capture()

    # -- nodes -------------------------------------------------------------
    def create_node(self, node: Node) -> Node:
        with self._lock:
            existing = self._nodes.get(node.id)
            if existing is not None and existing is not _TOMBSTONE:
                raise AlreadyExistsError(f"node {node.id} already exists")
            if existing is None:
                try:
                    self.base.get_node(node.id)
                    raise AlreadyExistsError(f"node {node.id} already exists")
                except NotFoundError:
                    pass
            stored = node.copy()
            self._nodes[node.id] = stored
            self._node_is_create.add(node.id)
            self._note_writer_locked()
        self._emit("node_created", stored.copy())
        return stored.copy()

    def get_node(self, node_id: str) -> Node:
        with self._lock:
            val = self._nodes.get(node_id)
            if val is _TOMBSTONE:
                raise NotFoundError(f"node {node_id} not found")
            if val is not None:
                return val.copy()  # type: ignore[union-attr]
        try:
            return self.base.get_node(node_id)
        except NotFoundError:
            # a background flush may have popped the entry from the overlay
            # but not yet applied it to the base; retry once the in-flight
            # flush (if any) has drained
            with self._flush_lock:
                return self.base.get_node(node_id)

    def update_node(self, node: Node) -> Node:
        with self._lock:
            val = self._nodes.get(node.id)
            if val is _TOMBSTONE:
                raise NotFoundError(f"node {node.id} not found")
            if val is None:
                self.base.get_node(node.id)  # raises if absent
            stored = node.copy()
            was_create = node.id in self._node_is_create
            self._nodes[node.id] = stored
            if was_create:
                self._node_is_create.add(node.id)
            self._note_writer_locked()
        self._emit("node_updated", stored.copy())
        return stored.copy()

    def delete_node(self, node_id: str) -> None:
        # Node deletion cascades to attached edges in the base engine; a
        # tombstone overlay cannot mirror that cascade, so counts and edge
        # reads would go stale until flush (the class of bug behind the
        # reference's async_count_bug_test.go). Deletes are rare: flush and
        # delete synchronously.
        self.flush()
        self.base.delete_node(node_id)

    def get_nodes_by_label(self, label: str) -> list[Node]:
        self.flush()
        return self.base.get_nodes_by_label(label)

    def all_nodes(self) -> Iterator[Node]:
        self.flush()
        return self.base.all_nodes()

    def all_node_ids(self) -> list[str]:
        self.flush()
        return self.base.all_node_ids()  # AttributeError -> caller fallback

    # -- edges -------------------------------------------------------------
    def create_edge(self, edge: Edge) -> Edge:
        # Endpoint validation must see overlay nodes too.
        self.get_node(edge.start_node)
        self.get_node(edge.end_node)
        with self._lock:
            existing = self._edges.get(edge.id)
            if existing is not None and existing is not _TOMBSTONE:
                raise AlreadyExistsError(f"edge {edge.id} already exists")
            if existing is _TOMBSTONE:
                # the tombstone this create overwrites will never reach the
                # base, so its flush-replay suppression must not linger and
                # swallow a future genuine delete of this id
                self._deleted_emitted.discard(edge.id)
            stored = edge.copy()
            self._edges[edge.id] = stored
            self._edge_is_create.add(edge.id)
            self._note_writer_locked()
        self._emit("edge_created", stored.copy())
        return stored.copy()

    def get_edge(self, edge_id: str) -> Edge:
        with self._lock:
            val = self._edges.get(edge_id)
            if val is _TOMBSTONE:
                raise NotFoundError(f"edge {edge_id} not found")
            if val is not None:
                return val.copy()  # type: ignore[union-attr]
        return self.base.get_edge(edge_id)

    def update_edge(self, edge: Edge) -> Edge:
        with self._lock:
            val = self._edges.get(edge.id)
            if val is _TOMBSTONE:
                raise NotFoundError(f"edge {edge.id} not found")
            if val is None:
                self.base.get_edge(edge.id)
            stored = edge.copy()
            self._edges[edge.id] = stored
            self._note_writer_locked()
        self._emit("edge_updated", stored.copy())
        return stored.copy()

    def delete_edge(self, edge_id: str) -> None:
        try:
            self._delete_edge_once(edge_id)
        except NotFoundError:
            # a background flush may have popped the create from the overlay
            # but not yet applied it to the base (same window get_node
            # handles); drain the in-flight flush OUTSIDE self._lock —
            # flush takes _flush_lock then _lock — and retry once
            with self._flush_lock:
                pass
            self._delete_edge_once(edge_id)

    def _delete_edge_once(self, edge_id: str) -> None:
        with self._lock:
            val = self._edges.get(edge_id)
            if val is _TOMBSTONE:
                raise NotFoundError(f"edge {edge_id} not found")
            entity = val.copy() if val is not None else self.base.get_edge(edge_id)
            if edge_id in self._edge_is_create:
                self._edges.pop(edge_id, None)
                self._edge_is_create.discard(edge_id)
            else:
                self._edges[edge_id] = _TOMBSTONE
                # the base replays this delete at flush; don't notify twice
                self._deleted_emitted.add(edge_id)
                self._note_writer_locked()
        self._emit("edge_deleted", entity)

    def get_edges_by_type(self, edge_type: str) -> list[Edge]:
        self.flush()
        return self.base.get_edges_by_type(edge_type)

    def get_outgoing_edges(self, node_id: str) -> list[Edge]:
        self.flush()
        return self.base.get_outgoing_edges(node_id)

    def get_incoming_edges(self, node_id: str) -> list[Edge]:
        self.flush()
        return self.base.get_incoming_edges(node_id)

    def all_edges(self) -> Iterator[Edge]:
        self.flush()
        return self.base.all_edges()

    # -- counts: overlay-aware (ref: async_count_bug_test.go). The flush
    # lock keeps the popped-but-not-yet-applied window out of the count.
    def node_count(self) -> int:
        with self._flush_lock:
            with self._lock:
                delta = 0
                for nid, val in self._nodes.items():
                    if val is _TOMBSTONE:
                        delta -= 1
                    elif nid in self._node_is_create:
                        delta += 1
            return self.base.node_count() + delta

    def edge_count(self) -> int:
        with self._flush_lock:
            with self._lock:
                delta = 0
                for eid, val in self._edges.items():
                    if val is _TOMBSTONE:
                        delta -= 1
                    elif eid in self._edge_is_create:
                        delta += 1
            return self.base.edge_count() + delta

    def count_nodes_by_label(self, label: str) -> int:
        self.flush()
        return self.base.count_nodes_by_label(label)

    def count_edges_by_type(self, edge_type: str) -> int:
        self.flush()
        return self.base.count_edges_by_type(edge_type)

    # -- pending embed -----------------------------------------------------
    def mark_pending_embed(self, node_id: str) -> None:
        self.flush()
        self.base.mark_pending_embed(node_id)

    def unmark_pending_embed(self, node_id: str) -> None:
        self.base.unmark_pending_embed(node_id)

    def pending_embed_ids(self, limit: int = 0) -> list[str]:
        return self.base.pending_embed_ids(limit)

    def close(self) -> None:
        self._closed = True
        self.flush()
        self.base.close()
