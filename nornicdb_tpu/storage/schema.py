"""Schema manager: constraints and index metadata.

Behavioral reference: /root/reference/pkg/storage/schema.go:42 — unique
constraints, property / composite / fulltext / vector / range indexes.
Here the property index also maintains a live value->ids map used by the
Cypher executor for index-backed lookups (the reference's Badger engine gets
this from key-prefix scans; a TPU-host build keeps it as a hash index).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from nornicdb_tpu.errors import AlreadyExistsError, ConstraintViolationError, NotFoundError
from nornicdb_tpu.storage.types import Engine, Node

log = logging.getLogger(__name__)

INDEX_PROPERTY = "property"
INDEX_COMPOSITE = "composite"
INDEX_FULLTEXT = "fulltext"
INDEX_VECTOR = "vector"
INDEX_RANGE = "range"


@dataclass
class IndexDef:
    name: str
    kind: str
    label: str
    properties: list[str]
    options: dict[str, Any] = field(default_factory=dict)  # vector: dimensions, similarity


@dataclass
class ConstraintDef:
    name: str
    label: str
    properties: list[str]
    kind: str = "unique"


def _norm(properties) -> tuple:
    """Internal prop-map keys are SORTED property tuples: equality lookup
    over (a, b) and (b, a) is the same index, and callers (the matcher,
    the fastpath probe) present keys sorted — a composite index declared
    in non-alphabetical order must not be invisible to them."""
    return tuple(sorted(properties))


def _freeze(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    return v


class SchemaManager:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._indexes: dict[str, IndexDef] = {}
        self._constraints: dict[str, ConstraintDef] = {}
        # (label, props-tuple) -> frozen-value-tuple -> set of node ids
        self._prop_maps: dict[tuple, dict[Any, set[str]]] = {}
        # node id -> set of (map-key, value-tuple) it is indexed under,
        # so updates can drop stale entries
        self._node_entries: dict[str, set[tuple]] = {}
        self._engine = None
        self._subscribed = False
        # DDL generation: bumped on index/constraint create/drop so the
        # columnar plan cache (cypher/plan.py) can invalidate plans whose
        # anchor strategy was chosen against a different index set —
        # including DDL issued via another executor sharing this manager
        self.generation = 0

    # -- index DDL ---------------------------------------------------------
    def create_index(
        self,
        name: str,
        kind: str,
        label: str,
        properties: list[str],
        options: Optional[dict[str, Any]] = None,
        if_not_exists: bool = False,
    ) -> IndexDef:
        with self._lock:
            if name in self._indexes:
                if if_not_exists:
                    return self._indexes[name]
                raise AlreadyExistsError(f"index {name} already exists")
            idx = IndexDef(name, kind, label, list(properties), options or {})
            self._indexes[name] = idx
            self.generation += 1
            if kind in (INDEX_PROPERTY, INDEX_COMPOSITE, INDEX_RANGE):
                self._subscribe()
                self._prop_maps.setdefault((label, _norm(properties)), {})
                self._backfill(label, _norm(properties))
            return idx

    def drop_index(self, name: str, if_exists: bool = False) -> None:
        with self._lock:
            idx = self._indexes.pop(name, None)
            if idx is None:
                if if_exists:
                    return
                raise NotFoundError(f"index {name} not found")
            self.generation += 1
            key = (idx.label, _norm(idx.properties))
            if not any(
                (i.label, _norm(i.properties)) == key
                for i in self._indexes.values()
                if i.kind in (INDEX_PROPERTY, INDEX_COMPOSITE, INDEX_RANGE)
            ):
                self._prop_maps.pop(key, None)

    def get_index(self, name: str) -> Optional[IndexDef]:
        with self._lock:
            return self._indexes.get(name)

    def list_indexes(self) -> list[IndexDef]:
        with self._lock:
            return list(self._indexes.values())

    def vector_indexes(self) -> list[IndexDef]:
        return [i for i in self.list_indexes() if i.kind == INDEX_VECTOR]

    def has_prop_index(self, label: str, properties: list[str]) -> bool:
        """True when an equality-lookup map exists for (label, properties)
        — i.e. lookup() would answer (property/composite/range/constraint
        maps, NOT fulltext/vector defs). Order-insensitive."""
        with self._lock:
            return (label, _norm(properties)) in self._prop_maps

    def find_index(self, label: str, properties: list[str]) -> Optional[IndexDef]:
        with self._lock:
            for i in self._indexes.values():
                if i.label == label and i.properties == list(properties):
                    return i
        return None

    # -- constraints -------------------------------------------------------
    def create_constraint(
        self,
        name: str,
        label: str,
        properties: list[str],
        kind: str = "unique",
        if_not_exists: bool = False,
    ) -> ConstraintDef:
        with self._lock:
            if name in self._constraints:
                if if_not_exists:
                    return self._constraints[name]
                raise AlreadyExistsError(f"constraint {name} already exists")
            c = ConstraintDef(name, label, list(properties), kind)
            self._constraints[name] = c
            self.generation += 1
            self._subscribe()
            key = (label, _norm(properties))
            created_map = key not in self._prop_maps
            self._prop_maps.setdefault(key, {})
            self._backfill(label, key[1])
            if kind == "unique":
                # Neo4j refuses to create a unique constraint over data
                # that already violates it
                dup = next(
                    (vals for vals, ids in self._prop_maps[key].items()
                     if len(ids) > 1),
                    None,
                )
                if dup is not None:
                    del self._constraints[name]
                    if created_map and not any(
                        (i.label, _norm(i.properties)) == key
                        for i in self._indexes.values()
                    ):
                        # drop the map we just created, or index_node would
                        # maintain it forever for a constraint that doesn't
                        # exist (every entry also leaves _node_entries)
                        for vals, ids in self._prop_maps[key].items():
                            for nid in ids:
                                self._node_entries.get(nid, set()).discard(
                                    (key, vals))
                        del self._prop_maps[key]
                    raise ConstraintViolationError(
                        f"cannot create unique constraint {name}: existing "
                        f"duplicate value {dup!r} on {label}"
                        f"({', '.join(properties)})"
                    )
            return c

    def drop_constraint(self, name: str, if_exists: bool = False) -> None:
        with self._lock:
            if self._constraints.pop(name, None) is None and not if_exists:
                raise NotFoundError(f"constraint {name} not found")
            self.generation += 1

    def list_constraints(self) -> list[ConstraintDef]:
        with self._lock:
            return list(self._constraints.values())

    # -- maintenance (called from engine event stream) ----------------------
    def check_unique(self, node: Node, exclude_id: Optional[str] = None) -> None:
        """Raise ConstraintViolationError if `node` collides with an existing
        unique-constrained value."""
        with self._lock:
            for c in self._constraints.values():
                if c.kind != "unique" or c.label not in node.labels:
                    continue
                props = _norm(c.properties)
                vals = tuple(_freeze(node.properties.get(p)) for p in props)
                if any(v is None for v in vals):
                    continue
                ids = self._prop_maps.get((c.label, props), {}).get(vals)
                if ids and any(i != (exclude_id or node.id) for i in ids):
                    raise ConstraintViolationError(
                        f"unique constraint {c.name} violated on {c.label}"
                        f"({', '.join(c.properties)})"
                    )

    def index_node(self, node: Node) -> None:
        with self._lock:
            self._unindex_locked(node.id)
            entries = set()
            for (label, props), valmap in self._prop_maps.items():
                if label in node.labels:
                    vals = tuple(_freeze(node.properties.get(p)) for p in props)
                    if any(v is None for v in vals):
                        continue
                    valmap.setdefault(vals, set()).add(node.id)
                    entries.add(((label, props), vals))
            if entries:
                self._node_entries[node.id] = entries

    def _unindex_locked(self, node_id: str) -> None:
        for key, vals in self._node_entries.pop(node_id, set()):
            valmap = self._prop_maps.get(key)
            if valmap is None:
                continue
            ids = valmap.get(vals)
            if ids:
                ids.discard(node_id)
                if not ids:
                    valmap.pop(vals, None)

    def unindex_node(self, node: Node) -> None:
        with self._lock:
            self._unindex_locked(node.id)

    def lookup(self, label: str, properties: list[str], values: list[Any]) -> Optional[set[str]]:
        """Index-backed equality lookup; None when no such index exists.
        Property order is irrelevant: (prop, value) pairs are normalized
        to the sorted-key layout the maps use."""
        pairs = sorted(zip(properties, values))
        with self._lock:
            valmap = self._prop_maps.get((label, tuple(p for p, _ in pairs)))
            if valmap is None:
                return None
            return set(valmap.get(tuple(_freeze(v) for _, v in pairs), set()))

    def attach(self, engine: Engine) -> None:
        """Subscribe to engine events so index maps stay current, and index
        whatever the engine already holds."""
        self._engine = engine
        self._subscribe()
        for n in engine.all_nodes():
            self.index_node(n)

    def attach_lazy(self, engine: Engine) -> None:
        """Remember the engine but defer the event subscription (and any
        node scan) until the first index/constraint DDL. Per-request
        CypherExecutor construction over a shared long-lived engine must
        not accumulate dead subscriptions or pay O(N) scans when no index
        is ever created; _backfill covers pre-existing data at DDL time."""
        self._engine = engine

    def _subscribe(self) -> None:
        if self._subscribed or self._engine is None:
            return
        self._subscribed = True

        def _on(kind: str, entity) -> None:
            if not isinstance(entity, Node):
                return
            if kind == "node_created" or kind == "node_updated":
                self.index_node(entity)
            elif kind == "node_deleted":
                self.unindex_node(entity)

        self._engine.on_event(_on)

    def _backfill(self, label: str, properties: tuple) -> None:
        """Populate a NEW prop map from data that already exists — an index
        or constraint created after writes must see earlier nodes (Neo4j
        indexes existing data at creation time)."""
        engine = getattr(self, "_engine", None)
        if engine is None:
            return
        valmap = self._prop_maps.get((label, properties))
        if valmap is None or valmap:
            return  # nothing registered, or map already live (shared key)
        try:
            nodes = engine.get_nodes_by_label(label)
        except Exception:
            # an index created over a broken engine scan starts empty; log
            # it, or the missing backfill looks like silent data loss later
            log.warning("index backfill scan failed for label %r", label,
                        exc_info=True)
            return
        for n in nodes:
            vals = tuple(_freeze(n.properties.get(p)) for p in properties)
            if any(v is None for v in vals):
                continue
            valmap.setdefault(vals, set()).add(n.id)
            self._node_entries.setdefault(n.id, set()).add(
                ((label, properties), vals))
