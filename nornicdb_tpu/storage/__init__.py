"""Graph storage engines (ref: /root/reference/pkg/storage/).

Engine decorator chain mirrors the reference assembly in
pkg/nornicdb/db.go:750-914:

    NamespacedEngine -> AsyncEngine -> WALEngine -> MemoryEngine (+WAL files)

`open_storage("")` yields a pure in-memory chain (the reference's Open("")
path, db.go:898-913) so tests never touch disk.
"""

from __future__ import annotations

import os
from typing import Optional

from nornicdb_tpu.errors import NornicError
from nornicdb_tpu.storage.adjacency import AdjacencySnapshot, attach_snapshot
from nornicdb_tpu.storage.async_engine import AsyncEngine
from nornicdb_tpu.storage.namespaced import NamespacedEngine
from nornicdb_tpu.storage.schema import (
    INDEX_COMPOSITE,
    INDEX_FULLTEXT,
    INDEX_PROPERTY,
    INDEX_RANGE,
    INDEX_VECTOR,
    ConstraintDef,
    IndexDef,
    SchemaManager,
)
from nornicdb_tpu.storage.types import (
    EDGE_CREATED,
    EDGE_DELETED,
    EDGE_UPDATED,
    EPISODIC,
    NODE_CREATED,
    NODE_DELETED,
    NODE_UPDATED,
    PROCEDURAL,
    SEMANTIC,
    Edge,
    Engine,
    MemoryEngine,
    Node,
    new_id,
)
from nornicdb_tpu.storage.faults import INJECTOR as FAULT_INJECTOR
from nornicdb_tpu.storage.faults import StorageFaultInjector
from nornicdb_tpu.storage.wal import WAL, WALEngine, WALEntry

__all__ = [
    "FAULT_INJECTOR",
    "StorageFaultInjector",
    "AdjacencySnapshot",
    "AsyncEngine",
    "NamespacedEngine",
    "attach_snapshot",
    "SchemaManager",
    "IndexDef",
    "ConstraintDef",
    "INDEX_PROPERTY",
    "INDEX_COMPOSITE",
    "INDEX_FULLTEXT",
    "INDEX_VECTOR",
    "INDEX_RANGE",
    "Edge",
    "Engine",
    "MemoryEngine",
    "Node",
    "new_id",
    "WAL",
    "WALEngine",
    "WALEntry",
    "EPISODIC",
    "SEMANTIC",
    "PROCEDURAL",
    "NODE_CREATED",
    "NODE_UPDATED",
    "NODE_DELETED",
    "EDGE_CREATED",
    "EDGE_UPDATED",
    "EDGE_DELETED",
    "open_storage",
]


def open_storage(
    data_dir: str = "",
    *,
    async_writes: bool = True,
    flush_interval: float = 0.05,
    wal_sync: bool = False,
    auto_compact: bool = False,
    auto_compact_interval: float = 300.0,
    encryption_passphrase: str = "",
    engine: str = "wal",  # wal (memory+WAL replay) | segment (native C++ KV)
) -> Engine:
    """Assemble the storage chain (ref: pkg/nornicdb/db.go:750-914).

    data_dir == "" -> in-memory only (no WAL), mirroring reference Open("").
    engine="segment" uses the native C++ segment store (the BadgerEngine
    role) as the durable base instead of WAL-replayed memory.
    """
    base: Engine = MemoryEngine()
    if data_dir and engine == "segment":
        from nornicdb_tpu.storage.segment import SegmentEngine

        base = SegmentEngine(data_dir, sync=wal_sync,
                             passphrase=encryption_passphrase or None)
    elif data_dir:
        os.makedirs(data_dir, exist_ok=True)
        wal = WAL(os.path.join(data_dir, "wal"), sync=wal_sync,
                  passphrase=encryption_passphrase or None)
        wal.recover(base)
        base = WALEngine(
            base,
            wal,
            auto_compact=auto_compact,
            auto_compact_interval=auto_compact_interval,
        )
    if async_writes:
        base = AsyncEngine(base, flush_interval=flush_interval)
    return base
