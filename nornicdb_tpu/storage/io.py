"""Graph import/export: Neo4j JSON shapes + Mimir export loader.

Behavioral reference: /root/reference/pkg/storage/ —
Neo4j JSON import/export (types.go:475-707), Mimir export loader
(mimir_loader.go, wired at db.go:1138), generic loader (loader.go).
"""

from __future__ import annotations

import json
from typing import Any, Optional

from nornicdb_tpu.errors import AlreadyExistsError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Engine, Node


def export_json(engine: Engine) -> dict[str, Any]:
    """Neo4j-style JSON export (ref: types.go:475-707)."""
    return {
        "nodes": [
            {
                "id": n.id,
                "labels": list(n.labels),
                "properties": dict(n.properties),
            }
            for n in sorted(engine.all_nodes(), key=lambda n: n.id)
        ],
        "relationships": [
            {
                "id": e.id,
                "type": e.type,
                "startNode": e.start_node,
                "endNode": e.end_node,
                "properties": dict(e.properties),
            }
            for e in sorted(engine.all_edges(), key=lambda e: e.id)
        ],
    }


def import_json(engine: Engine, data: dict[str, Any],
                skip_existing: bool = True) -> tuple[int, int]:
    """Neo4j-style JSON import. Returns (nodes, relationships) imported."""
    from nornicdb_tpu.storage.types import new_id

    n_nodes = n_edges = 0
    for nd in data.get("nodes", []):
        node = Node(
            id=str(nd["id"]) if nd.get("id") is not None else new_id(),
            labels=list(nd.get("labels", [])),
            properties=dict(nd.get("properties", {})),
        )
        try:
            engine.create_node(node)
            n_nodes += 1
        except AlreadyExistsError:
            if not skip_existing:
                raise
    for ed in data.get("relationships", data.get("edges", [])):
        edge = Edge(
            id=str(ed["id"]) if ed.get("id") is not None else new_id(),
            start_node=str(ed.get("startNode", ed.get("start_node", ""))),
            end_node=str(ed.get("endNode", ed.get("end_node", ""))),
            type=ed.get("type", "RELATED_TO"),
            properties=dict(ed.get("properties", {})),
        )
        try:
            engine.create_edge(edge)
            n_edges += 1
        except AlreadyExistsError:
            if not skip_existing:
                raise
    return n_nodes, n_edges


def load_mimir(engine: Engine, path: str) -> tuple[int, int]:
    """Mimir memory-export loader (ref: mimir_loader.go; db.go:1138).

    Mimir exports are JSONL: one {"type": "memory"|"relation", ...} per line.
    Memories become Memory-labeled nodes (content + metadata); relations
    become typed edges.
    """
    n_nodes = n_edges = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            kind = obj.get("type", "memory")
            if kind == "memory":
                from nornicdb_tpu.storage.types import new_id as _new_id

                node = Node(
                    id=str(obj["id"]) if obj.get("id") is not None else _new_id(),
                    labels=["Memory"] + list(obj.get("labels", [])),
                    properties={
                        "content": obj.get("content", obj.get("text", "")),
                        **{k: v for k, v in (obj.get("metadata") or {}).items()},
                    },
                )
                if obj.get("importance") is not None:
                    node.properties["importance"] = obj["importance"]
                try:
                    engine.create_node(node)
                    engine.mark_pending_embed(node.id)
                    n_nodes += 1
                except AlreadyExistsError:
                    pass
            elif kind == "relation":
                edge = Edge(
                    start_node=str(obj.get("from", obj.get("source", ""))),
                    end_node=str(obj.get("to", obj.get("target", ""))),
                    type=obj.get("relation", obj.get("rel_type", "RELATED_TO")),
                    properties=dict(obj.get("properties", {})),
                )
                try:
                    engine.create_edge(edge)
                    n_edges += 1
                except (AlreadyExistsError, NotFoundError):
                    # duplicate relation, or a relation whose endpoint was
                    # not part of the import — skip it, count the rest
                    pass
    return n_nodes, n_edges
