"""Query-result cache: LRU + TTL with label-based invalidation.

Behavioral reference: /root/reference/pkg/cache/query_cache.go:54
(QueryCache — keyed by hash(query, params), label invalidation, stats;
global ConfigureGlobalCache wired at cmd/nornicdb/main.go:320).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class CacheStats:
    hits: int = 0
    misses: int = 0
    evictions: int = 0
    invalidations: int = 0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


@dataclass
class _Entry:
    value: Any
    labels: frozenset
    expires: float


class QueryCache:
    """(ref: cache.QueryCache query_cache.go:54)"""

    def __init__(self, capacity: int = 1000, ttl: float = 60.0):
        self.capacity = capacity
        self.ttl = ttl
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self.stats = CacheStats()

    @staticmethod
    def key(query: str, params: Optional[dict] = None) -> str:
        blob = query + "\x00" + json.dumps(params or {}, sort_keys=True, default=str)
        return hashlib.sha256(blob.encode()).hexdigest()

    def get(self, query: str, params: Optional[dict] = None) -> Optional[Any]:
        k = self.key(query, params)
        with self._lock:
            e = self._entries.get(k)
            if e is None or e.expires < time.time():
                if e is not None:
                    del self._entries[k]
                self.stats.misses += 1
                return None
            self._entries.move_to_end(k)
            self.stats.hits += 1
            return e.value

    def put(
        self,
        query: str,
        params: Optional[dict],
        value: Any,
        labels: Optional[set[str]] = None,
    ) -> None:
        k = self.key(query, params)
        with self._lock:
            self._entries[k] = _Entry(
                value, frozenset(labels or ()), time.time() + self.ttl
            )
            self._entries.move_to_end(k)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.stats.evictions += 1

    def invalidate_labels(self, labels: set[str]) -> int:
        """Drop entries that touched any of these labels; entries with no
        recorded labels (label-agnostic scans) are dropped too
        (ref: label-based invalidation query_cache.go)."""
        dropped = 0
        with self._lock:
            for k in list(self._entries):
                e = self._entries[k]
                if not e.labels or e.labels & labels:
                    del self._entries[k]
                    dropped += 1
            self.stats.invalidations += dropped
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
