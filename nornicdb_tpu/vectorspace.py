"""Named vector-space registry shared across subsystems.

Behavioral reference: /root/reference/pkg/vectorspace/registry.go —
VectorSpaceKey :57 (name, dims, distance metric, backend kind, canonical
hash), IndexRegistry :149; used by Cypher vector indexes and Qdrant
collections so every subsystem agrees on a space's geometry.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Optional

from nornicdb_tpu.errors import AlreadyExistsError, NornicError

DISTANCE_COSINE = "cosine"
DISTANCE_DOT = "dot"
DISTANCE_EUCLIDEAN = "euclidean"

BACKEND_TPU = "tpu"
BACKEND_SHARDED = "sharded"
BACKEND_HNSW = "hnsw"


@dataclass(frozen=True)
class VectorSpaceKey:
    """(ref: VectorSpaceKey registry.go:57)"""

    name: str
    dims: int
    distance: str = DISTANCE_COSINE
    backend: str = BACKEND_TPU

    def canonical(self) -> str:
        return f"{self.name.lower()}:{self.dims}:{self.distance}:{self.backend}"

    def hash(self) -> str:
        return hashlib.blake2s(self.canonical().encode()).hexdigest()[:16]


class VectorSpaceRegistry:
    """(ref: IndexRegistry registry.go:149)"""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._spaces: dict[str, VectorSpaceKey] = {}

    def register(self, key: VectorSpaceKey, if_not_exists: bool = True) -> VectorSpaceKey:
        with self._lock:
            existing = self._spaces.get(key.name.lower())
            if existing is not None:
                if existing == key or if_not_exists:
                    if existing.dims != key.dims:
                        raise NornicError(
                            f"vector space {key.name}: dims mismatch "
                            f"({existing.dims} != {key.dims})"
                        )
                    return existing
                raise AlreadyExistsError(f"vector space {key.name} exists")
            self._spaces[key.name.lower()] = key
            return key

    def get(self, name: str) -> Optional[VectorSpaceKey]:
        with self._lock:
            return self._spaces.get(name.lower())

    def drop(self, name: str) -> bool:
        with self._lock:
            return self._spaces.pop(name.lower(), None) is not None

    def list(self) -> list[VectorSpaceKey]:
        with self._lock:
            return sorted(self._spaces.values(), key=lambda k: k.name)
