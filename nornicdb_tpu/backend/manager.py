"""Backend lifecycle manager: probe → acquire → serve → degrade → recover.

The round-5 VERDICT reproduced a production-path deadlock: with the TPU
backend unreachable, the first ``jnp.asarray`` inside ``HostCorpus._sync``
hangs in PJRT init while holding ``_sync_lock``, and every later
``search()`` blocks forever.  This module makes device acquisition a
first-class, *time-bounded* component so that bug class stays dead:

* **One device-owner thread.**  PJRT init and the first-touch
  ``device_put`` run on the manager's worker thread, never on a caller —
  a caller waits on an event with a config timeout and walks away when it
  fires (the hung init keeps running harmlessly in the background; the
  worker discards abandoned results).  Reference shape: the probe chain in
  ``pkg/gpu/gpu.go:354-556``.
* **Explicit lifecycle state machine.**  PROBING → READY → DEGRADED_CPU →
  RECOVERING (→ READY).  A periodic health probe (tiny device round-trip
  with a latency threshold) drives READY→DEGRADED_CPU; hysteresis
  (``degrade_after`` consecutive failures / ``recover_after`` consecutive
  successes) prevents flap-thrash.
* **CPU fallback.**  While DEGRADED_CPU, consumers (``ops/similarity``
  corpora, the embedder) serve from host arrays — the reference's
  device-failure CPU retry, ``pkg/embed/local_gguf.go:202-294``; WindVE
  (PAPERS.md) shows the same CPU↔accelerator decoupling keeping a serving
  stack live.
* **Live recovery.**  When the probe goes green again the manager
  re-acquires on the worker thread, then notifies registered corpora to
  re-upload (full, or trust-the-resident-buffer "dirty" mode) before
  re-entering READY.

The structural invariant — *no device op / backend acquisition under a
held lock* — is enforced three ways: consumers gate through
``await_ready()`` BEFORE taking their locks, nornlint NL-DEV01 flags new
violations statically, and ``await_ready`` itself asserts (under NORNSAN)
that the calling thread holds no instrumented locks.

Import-light by design: ``jax`` is imported lazily inside the real hooks,
so importing this module (or anything that imports it) never triggers
backend init.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.errors import BackendLockHeldError, DeviceUnavailable
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

logger = logging.getLogger(__name__)

# -- lifecycle states --------------------------------------------------------
PROBING = "PROBING"            # initial acquisition in flight
READY = "READY"                # device serving; periodic probe green
DEGRADED_CPU = "DEGRADED_CPU"  # device lost/unreachable; serving from host
RECOVERING = "RECOVERING"      # probe green again; re-acquire + re-upload

STATES = (PROBING, READY, DEGRADED_CPU, RECOVERING)

# -- metrics (cells created at import so the catalog renders before the
#    first transition; only the process-default manager publishes) ----------
_STATE_GAUGE = _REGISTRY.gauge(
    "nornicdb_backend_state",
    "Backend lifecycle state (one-hot: the current state's cell is 1)",
    labels=("state",),
)
_STATE_CELLS = {s: _STATE_GAUGE.labels(s) for s in STATES}
_PROBE_HIST = _REGISTRY.histogram(
    "nornicdb_backend_probe_seconds",
    "Device health-probe round-trip latency",
)
_FALLBACKS = _REGISTRY.counter(
    "nornicdb_backend_fallbacks_total",
    "Device-path requests served from CPU host arrays instead",
    labels=("op",),
)
_FALLBACKS.labels("search")  # eager cells: render at 0 before first use
_FALLBACKS.labels("embed")
_RECOVERIES = _REGISTRY.counter(
    "nornicdb_backend_recoveries_total",
    "DEGRADED_CPU -> READY recoveries (device re-acquired, corpora re-uploaded)",
)
_DEGRADES = _REGISTRY.counter(
    "nornicdb_backend_degrades_total",
    "Transitions into DEGRADED_CPU (acquire timeout or probe failures)",
)
_ACQUIRE_TIMEOUTS = _REGISTRY.counter(
    "nornicdb_backend_acquire_timeouts_total",
    "Device acquisitions abandoned at the configured timeout",
)
_PROBE_FAILURES = _REGISTRY.counter(
    "nornicdb_backend_probe_failures_total",
    "Health probes that timed out, errored, or exceeded the latency threshold",
)
_LOCK_VIOLATIONS = _REGISTRY.counter(
    "nornicdb_backend_lock_violations_total",
    "Backend acquisitions attempted while the caller held a lock (NL-DEV01)",
)


# -- nornsan bridge ----------------------------------------------------------
def _held_lock_sites() -> list[str]:
    """Creation sites of instrumented locks the calling thread holds, when
    the nornsan shim is installed; [] otherwise."""
    import sys

    nornsan = sys.modules.get("nornicdb_tpu.tools.nornsan")
    if nornsan is None or not getattr(nornsan, "active", lambda: False)():
        return []
    held = getattr(nornsan.tracker, "held_sites", None)
    return held() if held is not None else []


# -- device hooks ------------------------------------------------------------
class RealHooks:
    """Actual JAX backend operations. Every method may block (that is the
    point — they only ever run on the manager's worker thread)."""

    def touch(self) -> dict:
        """Acquire: PJRT init + first-touch transfer + tiny round-trip."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        devs = jax.devices()  # PJRT init happens here on cold processes
        x = jax.device_put(np.ones((8,), np.float32), devs[0])
        float(jnp.sum(x))  # first-touch round trip: compile + transfer back
        return {"platform": devs[0].platform, "device_count": len(devs)}

    def probe(self) -> None:
        """Tiny device round-trip; raises if the backend is unhealthy."""
        import jax.numpy as jnp

        float(jnp.asarray(1.0) + 1.0)


class FakeHooks:
    """Fault-injecting backend for tests and the CI chaos step.

    ``mode`` is mutable at runtime so a test can flip a hung backend
    healthy and watch the manager recover:

    * ``ok``   — succeed instantly
    * ``slow`` — succeed after ``delay`` seconds (latency-threshold tests)
    * ``fail`` — raise immediately
    * ``hang`` — block until ``release()`` (or forever)
    """

    def __init__(self, mode: str = "ok", delay: float = 0.0):
        self.mode = mode
        self.delay = delay
        self._release = threading.Event()
        self.touches = 0
        self.probes = 0

    def set_mode(self, mode: str) -> None:
        self.mode = mode
        if mode != "hang":
            self._release.set()
            self._release = threading.Event()

    def release(self) -> None:
        self._release.set()

    def _apply(self) -> None:
        # capture the release event BEFORE reading mode: set_mode sets the
        # old event then swaps in a fresh one, so a waiter that read
        # mode=="hang" must wait on the event set_mode will actually set
        # (waiting on the post-swap event would hang forever)
        release = self._release
        mode = self.mode
        if mode == "hang":
            release.wait()
            # woken by set_mode: re-read and apply the new behavior
            mode = self.mode
        if mode == "fail":
            raise RuntimeError("fake backend failure (NORNICDB_FAKE_BACKEND)")
        if mode == "slow" and self.delay > 0:
            time.sleep(self.delay)

    def touch(self) -> dict:
        self.touches += 1
        self._apply()
        return {"platform": "fake", "device_count": 1}

    def probe(self) -> None:
        self.probes += 1
        self._apply()


def hooks_from_env() -> Optional[FakeHooks]:
    """NORNICDB_FAKE_BACKEND=hang|fail|slow[:seconds]|ok -> FakeHooks."""
    raw = os.environ.get("NORNICDB_FAKE_BACKEND", "").strip().lower()
    if not raw:
        return None
    mode, _, arg = raw.partition(":")
    if mode not in ("ok", "hang", "fail", "slow"):
        logger.warning("NORNICDB_FAKE_BACKEND=%r: unknown mode, ignoring", raw)
        return None
    delay = float(arg) if arg else 0.5
    return FakeHooks(mode=mode, delay=delay)


# -- single-flight device executor ------------------------------------------
class _Result:
    __slots__ = ("event", "value", "error", "abandoned")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.value: Any = None
        self.error: Optional[BaseException] = None
        self.abandoned = False


class _DeviceExecutor:
    """The process's device-owner thread: all potentially-hanging backend
    calls run here.  ``submit()`` waits up to ``timeout`` then abandons the
    call (the worker finishes or hangs in the background; abandoned results
    are discarded).  ``busy`` is True while a call is in flight, so probes
    can count a stuck worker as a failure without stacking work behind it."""

    def __init__(self, name: str = "nornicdb-backend"):
        self._q: "queue.Queue[tuple[Callable[[], Any], _Result]]" = queue.Queue()
        self._busy = 0
        self._mu = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self._thread.start()

    @property
    def busy(self) -> bool:
        with self._mu:
            return self._busy > 0 or not self._q.empty()

    def _loop(self) -> None:
        while True:
            fn, res = self._q.get()
            if fn is None:  # shutdown sentinel
                return
            with self._mu:
                self._busy += 1
            try:
                res.value = fn()
            except BaseException as e:  # delivered to the waiter, not lost
                res.error = e
            finally:
                with self._mu:
                    self._busy -= 1
                res.event.set()

    def stop(self) -> None:
        """Queue a shutdown sentinel.  The worker exits once any in-flight
        (possibly hung) call finishes; a permanently hung call strands the
        daemon thread — nothing can interrupt a wedged PJRT call."""
        self._q.put((None, None))

    def submit(self, fn: Callable[[], Any], timeout: float) -> Any:
        """Run fn on the worker; TimeoutError if it doesn't finish in time
        (the call itself keeps running — nothing can interrupt a hung PJRT
        init — but the caller walks away)."""
        res = _Result()
        self._q.put((fn, res))
        if not res.event.wait(timeout):
            res.abandoned = True
            raise TimeoutError(f"device op exceeded {timeout:.1f}s")
        if res.error is not None:
            raise res.error
        return res.value


# -- the manager -------------------------------------------------------------
@dataclass
class BackendCounters:
    fallbacks: int = 0
    recoveries: int = 0
    degrades: int = 0
    acquire_timeouts: int = 0
    probes: int = 0
    probe_failures: int = 0
    lock_violations: int = 0
    transitions: list = field(default_factory=list)  # (ts, old, new, reason)


class BackendManager:
    """Owns device acquisition + health for the process (or, in tests, for
    one corpus).  Thread-safe; the state lock is never held across a device
    op — device work runs on the executor thread, bounded by timeouts."""

    def __init__(
        self,
        acquire_timeout: float = 15.0,
        probe_interval: float = 5.0,
        probe_timeout: float = 5.0,
        probe_latency_threshold: float = 1.0,
        degrade_after: int = 3,
        recover_after: int = 2,
        fallback: str = "cpu",
        recovery_reupload: str = "full",
        hooks: Optional[Any] = None,
        publish: bool = False,
    ):
        self.acquire_timeout = acquire_timeout
        self.probe_interval = probe_interval
        self.probe_timeout = probe_timeout
        self.probe_latency_threshold = probe_latency_threshold
        self.degrade_after = max(1, int(degrade_after))
        self.recover_after = max(1, int(recover_after))
        self.fallback = fallback
        self.recovery_reupload = recovery_reupload
        self.hooks = hooks if hooks is not None else (
            hooks_from_env() or RealHooks()
        )
        self._publish = publish
        self._state = PROBING
        self._cond = threading.Condition()
        self._started = False
        self._stop = threading.Event()
        self._executor: Optional[_DeviceExecutor] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._fail_streak = 0
        self._ok_streak = 0
        self._device_info: dict = {}
        self._probe_latency = 0.0
        self.counters = BackendCounters()
        # corpora to re-upload on recovery (weak: test corpora must not be
        # kept alive by the process-default manager)
        self._corpora: list = []  # list[weakref.ref]
        if publish:
            _STATE_CELLS[PROBING].set(1.0)

    # -- lifecycle ----------------------------------------------------------
    def ensure_started(self) -> None:
        with self._cond:
            if self._started:
                return
            self._started = True
            self._executor = _DeviceExecutor()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="nornicdb-backend-probe",
                daemon=True,
            )
        # initial acquisition kicks off OUTSIDE the state lock
        self._probe_thread.start()
        threading.Thread(
            target=self._initial_acquire, name="nornicdb-backend-acquire",
            daemon=True,
        ).start()

    def stop(self) -> None:
        self._stop.set()
        if self._executor is not None:
            self._executor.stop()
        with self._cond:
            self._cond.notify_all()

    @property
    def state(self) -> str:
        return self._state

    def ready(self) -> bool:
        """Fast non-blocking check: is the device serving right now?"""
        return self._state == READY

    def await_ready(self, timeout: Optional[float] = None) -> bool:
        """Block (bounded) until the device is serving.  Returns False when
        the wait ends DEGRADED_CPU — callers then serve from host arrays
        (or raise DeviceUnavailable under the "fail" policy via
        require_ready).  Never call this holding a lock: the whole point is
        that the *caller's* locks stay free while acquisition may hang."""
        self._guard_no_locks("await_ready")
        self.ensure_started()
        if self._state == READY:
            return True
        if self._state in (DEGRADED_CPU, RECOVERING):
            # degraded (or mid-recovery, which can include a long corpus
            # re-upload): fail fast to the CPU path — host arrays stay
            # correct, and the probe loop owns getting back to READY
            return False
        deadline = time.monotonic() + (
            self.acquire_timeout if timeout is None else timeout
        )
        with self._cond:
            while self._state == PROBING:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    break
                self._cond.wait(remaining)
        if self._state == READY:
            return True
        if self._state == PROBING:
            # acquisition still in flight past the caller's budget: the
            # caller degrades NOW (its answer can't wait), the manager keeps
            # acquiring in the background
            self._note_acquire_timeout()
        return self._state == READY

    def require_ready(self, timeout: Optional[float] = None) -> None:
        """await_ready that honors the fallback policy: under "fail" a
        degraded backend raises instead of signalling CPU fallback."""
        if not self.await_ready(timeout) and self.fallback != "cpu":
            raise DeviceUnavailable(
                f"backend {self._state}; fallback policy is {self.fallback!r}"
            )

    def note_fallback(self, op: str = "search") -> None:
        """A consumer served a device-path request from CPU host arrays."""
        self.counters.fallbacks += 1
        if self._publish:
            _FALLBACKS.labels(op).inc()

    # -- consumer registration ----------------------------------------------
    def register_corpus(self, corpus: Any) -> None:
        """Corpora re-upload on recovery via _on_backend_recovered(mode)."""
        with self._cond:
            self._corpora = [r for r in self._corpora if r() is not None]
            if not any(r() is corpus for r in self._corpora):
                self._corpora.append(weakref.ref(corpus))

    # -- internals -----------------------------------------------------------
    def _guard_no_locks(self, op: str) -> None:
        held = _held_lock_sites()
        if not held:
            return
        self.counters.lock_violations += 1
        if self._publish:
            _LOCK_VIOLATIONS.inc()
        # held is only ever non-empty under NORNSAN (the instrumented-lock
        # shim), where this is a test failure by contract — the static twin
        # NL-DEV01 covers production builds
        raise BackendLockHeldError(
            f"backend {op} while holding lock(s) {held}: device acquisition "
            "can hang in PJRT init and every thread needing those locks "
            "would block forever (NL-DEV01)"
        )

    def _note_acquire_timeout(self) -> None:
        self.counters.acquire_timeouts += 1
        if self._publish:
            _ACQUIRE_TIMEOUTS.inc()

    def _transition(self, new: str, reason: str) -> None:
        with self._cond:
            old = self._state
            if old == new:
                return
            self._state = new
            self.counters.transitions.append(
                (time.time(), old, new, reason)  # nornlint: disable=NL-TM01
            )
            del self.counters.transitions[:-50]
            self._cond.notify_all()
        logger.warning("backend %s -> %s (%s)", old, new, reason)
        if self._publish:
            for s, cell in _STATE_CELLS.items():
                cell.set(1.0 if s == new else 0.0)
            if new == DEGRADED_CPU:
                _DEGRADES.inc()
            if old in (RECOVERING, DEGRADED_CPU) and new == READY:
                _RECOVERIES.inc()
        if new == DEGRADED_CPU:
            self.counters.degrades += 1
        if old in (RECOVERING, DEGRADED_CPU) and new == READY:
            self.counters.recoveries += 1
        # state transitions are recorded as single-span traces so
        # /admin/traces shows the lifecycle timeline next to request traces
        with _tracer.start_trace(
            "backend.transition",
            attrs={"from": old, "to": new, "reason": reason},
        ):
            pass

    def _initial_acquire(self) -> None:
        try:
            info = self._executor.submit(self.hooks.touch, self.acquire_timeout)
            self._device_info = info or {}
            self._transition(READY, "acquired")
        except TimeoutError:
            self._note_acquire_timeout()
            self._transition(DEGRADED_CPU, "acquire timeout")
        except Exception as e:
            self._transition(DEGRADED_CPU, f"acquire failed: {e}")

    def _run_probe(self) -> bool:
        """One bounded health probe; True when green (and fast enough)."""
        self.counters.probes += 1
        if self._executor.busy:
            # a previous device call is still hung: that IS the failure —
            # don't stack another behind it
            self._note_probe_failure("worker busy/hung")
            return False
        t0 = time.perf_counter()
        try:
            self._executor.submit(self.hooks.probe, self.probe_timeout)
        except TimeoutError:
            self._note_probe_failure("probe timeout")
            return False
        except Exception as e:
            self._note_probe_failure(f"probe error: {e}")
            return False
        latency = time.perf_counter() - t0
        self._probe_latency = latency
        if self._publish:
            _PROBE_HIST.observe(latency)
        if latency > self.probe_latency_threshold:
            self._note_probe_failure(f"probe latency {latency:.3f}s")
            return False
        return True

    def _note_probe_failure(self, reason: str) -> None:
        self.counters.probe_failures += 1
        if self._publish:
            _PROBE_FAILURES.inc()
        logger.debug("backend probe failed: %s", reason)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval):
            try:
                self._probe_tick()
            except Exception:
                logger.exception("backend probe loop iteration failed")

    def _probe_tick(self) -> None:
        """One probe + hysteresis step (the probe loop's body; tests call
        it directly for deterministic streak scenarios)."""
        state = self._state
        if state == PROBING:
            return  # initial acquisition owns this phase
        ok = self._run_probe()
        if ok:
            self._fail_streak = 0
            self._ok_streak += 1
            if (
                state == DEGRADED_CPU
                and self._ok_streak >= self.recover_after
            ):
                self._recover()
        else:
            self._ok_streak = 0
            self._fail_streak += 1
            if (
                state == READY
                and self._fail_streak >= self.degrade_after
            ):
                self._transition(
                    DEGRADED_CPU,
                    f"{self._fail_streak} consecutive probe failures",
                )

    def _recover(self) -> None:
        """Probe went green while degraded: re-acquire, re-upload corpora,
        go READY.  Any failure drops straight back to DEGRADED_CPU."""
        self._transition(RECOVERING, f"{self._ok_streak} consecutive green probes")
        try:
            info = self._executor.submit(self.hooks.touch, self.acquire_timeout)
            self._device_info = info or {}
        except Exception as e:
            self._ok_streak = 0
            self._transition(DEGRADED_CPU, f"re-acquire failed: {e}")
            return
        mode = self.recovery_reupload
        with self._cond:
            corpora = [r() for r in self._corpora]
            self._corpora = [r for r in self._corpora if r() is not None]
        for corpus in corpora:
            if corpus is None:
                continue
            try:
                corpus._on_backend_recovered(mode)
            except Exception:
                logger.exception("corpus recovery notification failed")
        self._transition(READY, "recovered")
        # second notification AFTER the READY transition lands: the
        # pre-transition wake can be consumed by an uploader that still
        # saw RECOVERING (its _sync no-ops and the wake event is spent) —
        # this one guarantees the background re-upload actually runs, and
        # lets corpora re-apply device state (pending cluster installs)
        # that required a serving backend
        for corpus in corpora:
            if corpus is None:
                continue
            try:
                corpus._on_backend_ready()
            except Exception:
                logger.exception("corpus post-recovery notification failed")

    # -- observability --------------------------------------------------------
    def stats(self) -> dict:
        c = self.counters
        return {
            "state": self._state,
            "device": dict(self._device_info),
            "probe_latency_s": round(self._probe_latency, 6),
            "probe_interval_s": self.probe_interval,
            "acquire_timeout_s": self.acquire_timeout,
            "fallback_policy": self.fallback,
            "recovery_reupload": self.recovery_reupload,
            "fallbacks_total": c.fallbacks,
            "recoveries_total": c.recoveries,
            "degrades_total": c.degrades,
            "acquire_timeouts_total": c.acquire_timeouts,
            "probes_total": c.probes,
            "probe_failures_total": c.probe_failures,
            "lock_violations_total": c.lock_violations,
            "transitions": [
                {"ts": ts, "from": a, "to": b, "reason": r}
                for ts, a, b, r in c.transitions[-10:]
            ],
        }
