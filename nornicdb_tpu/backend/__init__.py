"""nornicdb_tpu.backend — device acquisition + health for the process.

Public surface:

* :func:`manager` — the process-default :class:`BackendManager` (created
  lazily; honors ``NORNICDB_FAKE_BACKEND`` fault injection and the
  ``BackendConfig`` applied via :func:`configure`).
* :func:`configure` — apply a ``config.BackendConfig`` (called by
  ``cli serve`` before servers take traffic).
* :func:`devices` — gated ``jax.devices()``: awaits readiness (bounded)
  first, so callers can never cold-init PJRT on their own thread.
* :func:`manager_stats` — stats dict or None when nothing started (the
  ``/admin/stats`` ``backend`` section; never forces manager start).

Consumers (``ops/similarity`` corpora, ``parallel``, ``embed``) gate
device paths through the manager and fall back to CPU host arrays while
it reports DEGRADED_CPU — see docs/backend.md for the state machine and
failure playbook.
"""

from __future__ import annotations

import threading
from typing import Optional

from nornicdb_tpu.backend.manager import (
    BackendManager,
    DEGRADED_CPU,
    FakeHooks,
    PROBING,
    READY,
    RECOVERING,
    RealHooks,
    STATES,
    hooks_from_env,
)
from nornicdb_tpu.errors import BackendLockHeldError, DeviceUnavailable

__all__ = [
    "BackendManager", "BackendLockHeldError", "DeviceUnavailable",
    "FakeHooks", "RealHooks", "hooks_from_env",
    "PROBING", "READY", "DEGRADED_CPU", "RECOVERING", "STATES",
    "manager", "configure", "devices", "manager_stats", "reset_default",
]

_default: Optional[BackendManager] = None
_default_kwargs: dict = {}
_mu = threading.Lock()

_CFG_FIELDS = (
    "acquire_timeout", "probe_interval", "probe_timeout",
    "probe_latency_threshold", "degrade_after", "recover_after",
    "fallback", "recovery_reupload",
)


def configure(cfg=None, **overrides) -> None:
    """Set construction kwargs for the process-default manager.  ``cfg``
    is a ``config.BackendConfig`` (or any object with matching attrs);
    keyword overrides win.  Must run before the first :func:`manager`
    call to take effect (``cli serve`` does)."""
    global _default_kwargs
    kwargs: dict = {}
    if cfg is not None:
        for name in _CFG_FIELDS:
            if hasattr(cfg, name):
                kwargs[name] = getattr(cfg, name)
    kwargs.update(overrides)
    with _mu:
        _default_kwargs = kwargs


def manager() -> BackendManager:
    """The process-default manager (lazily created; publishes metrics).
    Construction kwargs come from :func:`configure` when it ran, layered
    over the env-derived ``BackendConfig`` (NORNICDB_BACKEND_* /
    NORNICDB_DEVICE_* variables), so embedded and test processes that
    never call ``cli serve`` still honor the environment."""
    global _default
    with _mu:
        if _default is None:
            from nornicdb_tpu.config import AppConfig, load_from_env

            base = load_from_env(AppConfig()).backend
            kwargs = {name: getattr(base, name) for name in _CFG_FIELDS}
            kwargs.update(_default_kwargs)
            _default = BackendManager(publish=True, **kwargs)
        return _default


def manager_stats() -> Optional[dict]:
    """Stats for the default manager, or None if nothing created one yet
    (observability surfaces must not force backend management to start)."""
    with _mu:
        mgr = _default
    return None if mgr is None else mgr.stats()


def reset_default() -> None:
    """Drop the process-default manager (tests).  The old manager's
    threads are stopped; corpora registered with it re-register on their
    next device gate."""
    global _default
    with _mu:
        mgr, _default = _default, None
    if mgr is not None:
        mgr.stop()


def devices(timeout: Optional[float] = None):
    """Gated ``jax.devices()``: ensure the backend is acquired (bounded
    wait on the manager's worker thread) before touching JAX from the
    calling thread.  Raises :class:`DeviceUnavailable` when degraded."""
    mgr = manager()
    if not mgr.await_ready(timeout):
        raise DeviceUnavailable(
            f"backend {mgr.state}: device list unavailable "
            "(serving continues on CPU fallback paths)"
        )
    import jax

    return jax.devices()
