"""Inference integration adapters: topology, clusters, Heimdall QC.

Behavioral reference: /root/reference/pkg/inference/ —
TopologyIntegration (topology_integration.go): link-prediction scores feed
suggestion confidence; ClusterIntegration (cluster_integration.go): same
k-means cluster membership boosts similarity suggestions;
HeimdallQC (heimdall_qc.go:1-40): SLM batch review of suggested edges,
gated by NORNICDB_AUTO_TLP_LLM_QC_ENABLED.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Callable, Optional

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.inference.engine import InferenceEngine
from nornicdb_tpu.linkpredict.topology import build_graph, score_pair
from nornicdb_tpu.storage.types import Engine
from nornicdb_tpu.telemetry.metrics import count_error

log = logging.getLogger(__name__)


class TopologyIntegration:
    """Blend GDS topology scores into suggestion confidence
    (ref: topology_integration.go)."""

    def __init__(self, storage: Engine, method: str = "adamicAdar",
                 weight: float = 0.3):
        self.storage = storage
        self.method = method
        self.weight = weight
        self._graph = None
        self._graph_key = None

    def _current_graph(self):
        key = (self.storage.node_count(), self.storage.edge_count())
        if self._graph is None or self._graph_key != key:
            self._graph = build_graph(self.storage)
            self._graph_key = key
        return self._graph

    def adjust_confidence(self, from_id: str, to_id: str, confidence: float) -> float:
        g = self._current_graph()
        topo = score_pair(g, from_id, to_id, self.method)
        topo = topo / (1.0 + topo)  # squash unbounded scorers
        return min((1 - self.weight) * confidence + self.weight * topo, 1.0)

    def attach(self, engine: InferenceEngine) -> None:
        original = engine.process_suggestion

        def wrapped(from_id, to_id, rel_type, confidence):
            return original(
                from_id, to_id, rel_type,
                self.adjust_confidence(from_id, to_id, confidence),
            )

        engine.process_suggestion = wrapped  # type: ignore[method-assign]


class ClusterIntegration:
    """Same-cluster membership boosts similarity suggestions
    (ref: cluster_integration.go)."""

    def __init__(self, assignments_fn: Callable[[], dict[str, int]],
                 boost: float = 0.05, penalty: float = 0.05):
        self.assignments_fn = assignments_fn
        self.boost = boost
        self.penalty = penalty

    def adjust_confidence(self, from_id: str, to_id: str, confidence: float) -> float:
        assignments = self.assignments_fn() or {}
        ca, cb = assignments.get(from_id), assignments.get(to_id)
        if ca is None or cb is None:
            return confidence
        if ca == cb:
            return min(confidence + self.boost, 1.0)
        return max(confidence - self.penalty, 0.0)

    def attach(self, engine: InferenceEngine) -> None:
        original = engine.process_suggestion

        def wrapped(from_id, to_id, rel_type, confidence):
            return original(
                from_id, to_id, rel_type,
                self.adjust_confidence(from_id, to_id, confidence),
            )

        engine.process_suggestion = wrapped  # type: ignore[method-assign]


def qc_enabled() -> bool:
    """(ref: NORNICDB_AUTO_TLP_LLM_QC_ENABLED heimdall_qc.go)"""
    return os.environ.get("NORNICDB_AUTO_TLP_LLM_QC_ENABLED", "").lower() in (
        "1", "true", "yes",
    )


class HeimdallQC:
    """SLM batch review of suggested edges (ref: heimdall_qc.go:1-40).

    The generator is asked to answer per pair whether the relationship is
    plausible; suggestions it rejects are dropped. With the template
    generator this is a pass-through reviewer; with a trained Qwen it
    becomes a real QC gate.
    """

    def __init__(self, heimdall_manager, storage: Engine,
                 batch_size: int = 8):
        self.manager = heimdall_manager
        self.storage = storage
        self.batch_size = batch_size
        self.reviewed = 0
        self.rejected = 0

    def review(self, pairs: list[tuple[str, str, str]]) -> list[bool]:
        """pairs: (from_id, to_id, rel_type) -> keep? per pair.

        The whole batch is submitted through the manager's
        ``generate_many`` in one call: with the genserve continuous
        batching engine behind Heimdall, every pair's review decodes
        concurrently in the shared paged-KV batch instead of serializing
        one synchronous ``generate()`` per edge (the pre-genserve
        behavior, and still the fallback for template backends)."""
        out: list[Optional[bool]] = [None] * len(pairs)
        prompts: list[str] = []
        prompt_slots: list[int] = []
        for i, (from_id, to_id, rel_type) in enumerate(pairs):
            try:
                a = self.storage.get_node(from_id)
                b = self.storage.get_node(to_id)
            except NotFoundError:
                out[i] = False  # endpoint deleted since suggestion
                continue
            prompts.append(
                "Should these two memories be linked as "
                f"{rel_type}? Reply JSON {{\"keep\": true/false}}.\n"
                f"A: {a.properties.get('content', '')[:200]}\n"
                f"B: {b.properties.get('content', '')[:200]}"
            )
            prompt_slots.append(i)
        texts: list[Optional[str]] = []
        if prompts:
            try:
                texts = list(self.manager.generate_many(
                    prompts, max_tokens=16))
            except Exception:
                # QC failure must not block learning — but a QC model
                # that is ALWAYS down silently approves everything
                log.warning("link-QC batch generation failed; keeping "
                            "%d edges", len(prompts), exc_info=True)
                count_error("inference.link_qc")
                texts = [None] * len(prompts)
        for slot, text in zip(prompt_slots, texts):
            if text is None:
                out[slot] = True  # fail open
                continue
            self.reviewed += 1
            keep = True
            try:
                start = text.find("{")
                if start >= 0:
                    obj = json.loads(text[start : text.rfind("}") + 1])
                    keep = bool(obj.get("keep", True))
            except ValueError:
                keep = True  # non-JSON reply: fail open (keep the edge)
            if not keep:
                self.rejected += 1
            out[slot] = keep
        return [bool(k) for k in out]

    def attach(self, engine: InferenceEngine) -> None:
        if not qc_enabled():
            return
        original = engine.process_suggestion

        def wrapped(from_id, to_id, rel_type, confidence):
            if not self.review([(from_id, to_id, rel_type)])[0]:
                return None
            return original(from_id, to_id, rel_type, confidence)

        engine.process_suggestion = wrapped  # type: ignore[method-assign]
