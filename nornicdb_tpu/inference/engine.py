"""Automatic relationship inference ("the graph database that learns").

Behavioral reference: /root/reference/pkg/inference/inference.go —
Engine :216, OnStore :498 (embedding-similarity suggestions),
OnAccess :679 (co-access windows), SuggestTransitive :736 (A->B->C => A->C),
ProcessSuggestion :874 (evidence accumulation + cooldowns to prevent edge
churn); evidence.go, cooldown.go; integration adapters
(topology_integration.go, cluster_integration.go).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from nornicdb_tpu.errors import NotFoundError
from nornicdb_tpu.storage.types import Edge, Engine, Node
from nornicdb_tpu.telemetry.metrics import count_error

log = logging.getLogger(__name__)

SIMILAR_TO = "SIMILAR_TO"
RELATED_TO = "RELATED_TO"
CO_ACCESSED = "CO_ACCESSED_WITH"


@dataclass
class InferenceConfig:
    similarity_threshold: float = 0.85  # min cosine for SIMILAR_TO
    min_evidence: int = 2  # observations before an edge is created
    cooldown: float = 300.0  # per-pair suggestion cooldown seconds
    co_access_min: int = 3  # co-access observations before suggesting
    transitive_min_confidence: float = 0.5
    max_suggestions_per_store: int = 5
    evidence_ttl: float = 7 * 86400.0


@dataclass
class InferenceStats:
    suggestions: int = 0
    edges_created: int = 0
    suppressed_cooldown: int = 0
    suppressed_existing: int = 0


@dataclass
class _Evidence:
    count: int = 0
    confidence_sum: float = 0.0
    first_seen: float = 0.0
    last_seen: float = 0.0
    rel_type: str = SIMILAR_TO


class InferenceEngine:
    """(ref: inference.Engine inference.go:216)"""

    def __init__(
        self,
        storage: Engine,
        similarity_fn: Optional[Callable[[np.ndarray, int], list[tuple[str, float]]]] = None,
        config: Optional[InferenceConfig] = None,
        similarity_threshold: Optional[float] = None,
        now_fn: Callable[[], float] = time.time,
    ):
        self.storage = storage
        self.similarity_fn = similarity_fn  # injected (ref: inference.go:302)
        self.config = config or InferenceConfig()
        if similarity_threshold is not None:
            self.config.similarity_threshold = similarity_threshold
        self.now = now_fn
        self.stats = InferenceStats()
        self._lock = threading.RLock()
        self._evidence: dict[tuple[str, str, str], _Evidence] = {}
        self._cooldown: dict[tuple[str, str], float] = {}
        # rate-limited similarity-failure logging (one traceback per 60s
        # with a suppressed count — same pattern as decay.rate_modifier)
        self._sim_errors = 0
        # -inf, not 0.0: monotonic() has an arbitrary epoch that can start
        # near zero, which would silently suppress the FIRST traceback for
        # up to 60s of process life (decay.py uses the same sentinel)
        self._sim_error_logged_at = float("-inf")
        self._co_access: dict[tuple[str, str], int] = {}
        self._last_access: list[tuple[str, float]] = []

    # -- event hooks ------------------------------------------------------------
    def on_store(self, node: Node) -> list[Edge]:
        """Similarity-driven suggestions when a node (with embedding) lands
        (ref: OnStore inference.go:498)."""
        if node.embedding is None or self.similarity_fn is None:
            return []
        try:
            candidates = self.similarity_fn(
                np.asarray(node.embedding, np.float32),
                self.config.max_suggestions_per_store + 1,
            )
        except Exception:
            # a similarity backend hiccup must not fail the store, but a
            # silently dead suggestion path is undebuggable — count every
            # failure, log one traceback per 60s (a persistently-down
            # backend would otherwise emit one per stored node)
            count_error("inference.similarity")
            self._sim_errors += 1
            mono = time.monotonic()
            if mono - self._sim_error_logged_at >= 60.0:
                self._sim_error_logged_at = mono
                log.warning(
                    "similarity lookup failed during on_store "
                    "(%d failure(s) since last report)",
                    self._sim_errors, exc_info=True,
                )
                self._sim_errors = 0
            return []
        created = []
        for other_id, score in candidates:
            if other_id == node.id:
                continue
            if score < self.config.similarity_threshold:
                continue
            e = self.process_suggestion(node.id, other_id, SIMILAR_TO, float(score))
            if e is not None:
                created.append(e)
        return created

    def on_access(self, node_id: str, ts: Optional[float] = None) -> list[Edge]:
        """Co-access window tracking (ref: OnAccess inference.go:679)."""
        ts = self.now() if ts is None else ts
        created = []
        with self._lock:
            window = 60.0
            self._last_access = [
                (nid, t) for nid, t in self._last_access if ts - t <= window
            ]
            for other_id, _t in self._last_access:
                if other_id == node_id:
                    continue
                pair = tuple(sorted((node_id, other_id)))
                self._co_access[pair] = self._co_access.get(pair, 0) + 1
                count = self._co_access[pair]
                if count >= self.config.co_access_min:
                    conf = min(0.5 + 0.1 * (count - self.config.co_access_min), 0.95)
                    e = self.process_suggestion(pair[0], pair[1], CO_ACCESSED, conf)
                    if e is not None:
                        created.append(e)
            self._last_access.append((node_id, ts))
        return created

    def suggest_transitive(self, node_id: str) -> list[Edge]:
        """A->B->C => suggest A->C (ref: SuggestTransitive inference.go:736)."""
        created = []
        first_hop = self.storage.get_outgoing_edges(node_id)
        direct = {e.end_node for e in first_hop}
        for e1 in first_hop:
            for e2 in self.storage.get_outgoing_edges(e1.end_node):
                target = e2.end_node
                if target == node_id or target in direct:
                    continue
                conf = (
                    min(e1.confidence, e2.confidence)
                    * self.config.transitive_min_confidence
                    * 2.0
                )
                conf = min(conf, 0.9)
                if conf < self.config.transitive_min_confidence:
                    continue
                e = self.process_suggestion(node_id, target, RELATED_TO, conf)
                if e is not None:
                    created.append(e)
        return created

    # -- suggestion pipeline -------------------------------------------------------
    def process_suggestion(
        self, from_id: str, to_id: str, rel_type: str, confidence: float
    ) -> Optional[Edge]:
        """Evidence + cooldown gate, then edge creation
        (ref: ProcessSuggestion inference.go:874, evidence.go, cooldown.go)."""
        now = self.now()
        pair = tuple(sorted((from_id, to_id)))
        with self._lock:
            self.stats.suggestions += 1
            # cooldown (ref: cooldown.go — prevents edge churn)
            until = self._cooldown.get(pair, 0.0)
            if now < until:
                self.stats.suppressed_cooldown += 1
                return None
            # existing edge of this type?
            if self._edge_exists(from_id, to_id, rel_type):
                self.stats.suppressed_existing += 1
                self._cooldown[pair] = now + self.config.cooldown
                return None
            key = (pair[0], pair[1], rel_type)
            ev = self._evidence.get(key)
            if ev is None or now - ev.last_seen > self.config.evidence_ttl:
                ev = _Evidence(first_seen=now, rel_type=rel_type)
                self._evidence[key] = ev
            ev.count += 1
            ev.confidence_sum += confidence
            ev.last_seen = now
            if ev.count < self.config.min_evidence:
                return None
            avg_conf = ev.confidence_sum / ev.count
            del self._evidence[key]
            self._cooldown[pair] = now + self.config.cooldown
        edge = Edge(
            start_node=from_id,
            end_node=to_id,
            type=rel_type,
            confidence=round(avg_conf, 4),
            auto_generated=True,
            properties={"inferred_at": now, "evidence_count": ev.count},
        )
        try:
            created = self.storage.create_edge(edge)
        except Exception:
            # endpoint vanished / duplicate under race: the inference is
            # simply stale — but count it so a systematic failure shows up
            log.debug("inferred edge %s-[%s]->%s not created",
                      edge.start_node, edge.type, edge.end_node,
                      exc_info=True)
            count_error("inference.create_edge")
            return None
        self.stats.edges_created += 1
        return created

    def _edge_exists(self, a: str, b: str, rel_type: str) -> bool:
        for e in self.storage.get_outgoing_edges(a):
            if e.end_node == b and e.type == rel_type:
                return True
        for e in self.storage.get_outgoing_edges(b):
            if e.end_node == a and e.type == rel_type:
                return True
        return False

    # -- maintenance -----------------------------------------------------------------
    def decay_inferred_edges(self, min_confidence: float = 0.1) -> int:
        """Drop stale auto-generated edges below confidence
        (ref: edge_decay.go)."""
        removed = 0
        for e in list(self.storage.all_edges()):
            if e.auto_generated and e.confidence < min_confidence:
                try:
                    self.storage.delete_edge(e.id)
                    removed += 1
                except NotFoundError:
                    pass  # already gone (concurrent decay/delete)
        return removed
