"""Automatic relationship inference (ref: /root/reference/pkg/inference/)."""

from nornicdb_tpu.inference.engine import (
    CO_ACCESSED,
    RELATED_TO,
    SIMILAR_TO,
    InferenceConfig,
    InferenceEngine,
    InferenceStats,
)

__all__ = [
    "CO_ACCESSED", "RELATED_TO", "SIMILAR_TO", "InferenceConfig",
    "InferenceEngine", "InferenceStats",
]
