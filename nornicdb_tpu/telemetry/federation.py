"""Cross-process metrics federation: one /metrics for the whole fleet.

The prefork workers (server/workers.py) are separate processes with
their own ``telemetry.REGISTRY`` — before this module their counters
(broker round-trips, cache hits, shm-plane serves, 429 sheds) were
invisible: the primary's /metrics knew nothing about them and the
workers' own /metrics was a cached copy of the primary's.  The
federation closes the loop with the machinery the read plane already
proved (server/shm.py generation-stamped seqlock segments), flowing the
OTHER direction:

- Each worker runs a :class:`MetricsPublisher`: every ``interval``
  seconds it renders its registry exposition (plus its slow-query ring)
  into a per-worker shm segment.
- The primary's :class:`FleetCollector` (the ``FLEET`` singleton) maps
  every registered worker segment at scrape time, drops stale ones
  (dead worker, publisher wedged — staleness is wall-clock because
  monotonic clocks are not comparable across processes), and
  structurally merges the live expositions into the primary's: every
  worker sample gains a ``proc`` label (``http-worker-N`` /
  ``grpc-worker-N``), families are grouped so TYPE renders once, and
  the merged text still passes the strict parser
  (telemetry/promparse.py) — asserted by tests and the CI smoke.

Worker-side instrumentation families (``nornicdb_worker_*``) live here
too so the tested docs/observability.md catalog renders them in every
process (server/http.py imports this module for exactly that reason).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.promparse import parse_exposition
from nornicdb_tpu.telemetry.slowlog import slow_log as _slow_log

log = logging.getLogger(__name__)

FLEET_SEGMENT = "fleet"

# -- worker-side serving-ladder families (rendered with a proc label once
#    federated; registered here so the catalog renders in every process)
WORKER_REQUESTS = _REGISTRY.counter(
    "nornicdb_worker_requests_total",
    "Worker frontend requests by how they were served "
    "(cache hit / device broker / shm read plane / proxy / shed)",
    labels=("served",),
)
for _served in ("cache", "broker", "shm", "proxy", "limited", "error"):
    WORKER_REQUESTS.labels(_served)
WORKER_BROKER_RTT = _REGISTRY.histogram(
    "nornicdb_worker_broker_roundtrip_seconds",
    "Worker-side device-broker call round trip (encode + socket + fused "
    "dispatch + decode)",
)
# -- primary-side fleet families
FLEET_MEMBERS = _REGISTRY.gauge(
    "nornicdb_fleet_members",
    "Live fleet members by process (1 = exposition merged this scrape)",
    labels=("proc",),
)
FLEET_MEMBERS.labels("primary").set(1.0)
FLEET_AGE = _REGISTRY.gauge(
    "nornicdb_fleet_exposition_age_seconds",
    "Age of each worker's last published exposition at scrape time",
    labels=("proc",),
)
FLEET_STALE_DROPS = _REGISTRY.counter(
    "nornicdb_fleet_stale_drops_total",
    "Worker expositions dropped from a merge because the segment was "
    "stale (dead worker / wedged publisher)",
)
FLEET_MERGE_ERRORS = _REGISTRY.counter(
    "nornicdb_fleet_merge_errors_total",
    "Worker expositions skipped because they failed the strict parse",
)


class MetricsPublisher:
    """Worker-side: publish this process's exposition + slow-query ring
    into a generation-stamped shm segment every ``interval`` seconds."""

    def __init__(self, prefix: str, proc: str, interval: float = 0.5,
                 registry=None):
        from nornicdb_tpu.server.shm import SegmentWriter

        self.proc = proc
        self.interval = interval
        self.registry = registry if registry is not None else _REGISTRY
        self._writer = SegmentWriter(prefix, FLEET_SEGMENT)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.publishes = 0
        self.errors = 0

    def publish_now(self) -> None:
        text = self.registry.render_prometheus()
        arrays = {
            "exposition": np.frombuffer(text.encode(), np.uint8).copy()
            if text else np.zeros(0, np.uint8),
        }
        meta = {
            "proc": self.proc,
            "pid": os.getpid(),
            # wall clock ON PURPOSE: the collector compares this stamp
            # across processes, where monotonic clocks share no epoch
            "ts": time.time(),  # nornlint: disable=NL-TM01
            "slow_queries": _slow_log.snapshot(limit=32),
            "slow_recorded": _slow_log.recorded,
        }
        self._writer.publish(arrays, meta)
        self.publishes += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.publish_now()
            except Exception:
                self.errors += 1
                log.exception("fleet metrics publish failed")

    def start(self) -> "MetricsPublisher":
        if self._thread is None:
            try:
                self.publish_now()
            except Exception:
                self.errors += 1
                log.exception("initial fleet metrics publish failed")
            self._thread = threading.Thread(
                target=self._loop, name="nornicdb-fleet-metrics",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
        self._writer.close()


class WorkerExposition:
    """One live worker's collected exposition."""

    __slots__ = ("proc", "text", "slow_queries", "slow_recorded", "age",
                 "generation", "pid")

    def __init__(self, proc, text, slow_queries, slow_recorded, age,
                 generation, pid):
        self.proc = proc
        self.text = text
        self.slow_queries = slow_queries
        self.slow_recorded = slow_recorded
        self.age = age
        self.generation = generation
        self.pid = pid


class FleetCollector:
    """Primary-side: registered worker segments → merged exposition.

    ``register``/``unregister`` are driven by the WorkerPool lifecycle;
    a registered-but-never-published segment (worker still booting) and
    a stale segment (worker dead, publisher wedged) are both skipped —
    the merge only ever carries expositions fresher than
    ``staleness_s``, so a killed worker's numbers age out of /metrics
    instead of flatlining forever."""

    def __init__(self, staleness_s: float = 10.0):
        self.staleness_s = staleness_s
        self._lock = threading.Lock()
        # proc -> (prefix, SegmentReader-or-None lazily)
        self._members: dict[str, dict[str, Any]] = {}
        self.stale_drops = 0
        self.merges = 0

    def configure(self, staleness_s: Optional[float] = None) -> None:
        if staleness_s is not None:
            self.staleness_s = float(staleness_s)

    def register(self, proc: str, prefix: str) -> None:
        with self._lock:
            old = self._members.pop(proc, None)
            self._members[proc] = {"prefix": prefix, "reader": None}
        if old is not None and old.get("reader") is not None:
            old["reader"].close()

    def unregister(self, proc: str, prefix: Optional[str] = None) -> None:
        """Drop a member; with ``prefix`` given, only when it still maps
        to that prefix — a stopping pool must not evict a newer pool's
        registration under the same proc name."""
        with self._lock:
            member = self._members.get(proc)
            if member is None:
                return
            if prefix is not None and member["prefix"] != prefix:
                return
            self._members.pop(proc, None)
        # the membership one-hot must drop with the member: a stopped
        # pool's workers must not flatline as live forever
        FLEET_MEMBERS.labels(proc).set(0.0)
        FLEET_AGE.labels(proc).set(0.0)
        if member.get("reader") is not None:
            member["reader"].close()

    def members(self) -> list[str]:
        with self._lock:
            return list(self._members)

    def _reader(self, member: dict[str, Any]):
        from nornicdb_tpu.server.shm import SegmentReader

        with self._lock:  # concurrent scrapes must share one reader
            if member["reader"] is None:
                member["reader"] = SegmentReader(member["prefix"],
                                                 FLEET_SEGMENT)
            return member["reader"]

    def collect(self, count_stale: bool = True) -> list[WorkerExposition]:
        """Map every registered segment; skip unpublished/stale ones and
        refresh the fleet gauges.  ``count_stale=False`` for the
        structured read paths (/admin/stats, /admin/slow-queries): the
        stale-drop counter must mean "dropped from a /metrics merge",
        not "a dashboard polled stats while a worker was down"."""
        from nornicdb_tpu.server.shm import SegmentUnavailable

        with self._lock:
            members = list(self._members.items())
        out: list[WorkerExposition] = []
        now = time.time()  # nornlint: disable=NL-TM01  (cross-process)
        for proc, member in members:
            try:
                snap = self._reader(member).snapshot()
            except SegmentUnavailable:
                FLEET_MEMBERS.labels(proc).set(0.0)
                continue
            except Exception:
                log.debug("fleet segment read failed: %s", proc,
                          exc_info=True)
                FLEET_MEMBERS.labels(proc).set(0.0)
                continue
            # wall-clock delta ON PURPOSE: the stamp comes from another
            # process, where monotonic clocks share no epoch
            age = max(  # nornlint: disable=NL-TM01
                0.0, now - float(snap.meta.get("ts", 0.0)))
            FLEET_AGE.labels(proc).set(age)
            if age > self.staleness_s:
                if count_stale:
                    self.stale_drops += 1
                    FLEET_STALE_DROPS.inc()
                FLEET_MEMBERS.labels(proc).set(0.0)
                continue
            FLEET_MEMBERS.labels(proc).set(1.0)
            expo = snap.arrays.get("exposition")
            text = expo.tobytes().decode("utf-8", "replace") \
                if expo is not None and expo.size else ""
            out.append(WorkerExposition(
                proc=str(snap.meta.get("proc", proc)),
                text=text,
                slow_queries=snap.meta.get("slow_queries") or [],
                slow_recorded=int(snap.meta.get("slow_recorded", 0)),
                age=age,
                generation=snap.generation,
                pid=int(snap.meta.get("pid", 0)),
            ))
        return out

    # -- merging -----------------------------------------------------------
    def merged_exposition(self, primary) -> str:
        """The federated /metrics body: the primary's exposition with
        every live worker's families spliced in under a ``proc`` label.
        With no registered members this is the identity function — the
        single-process exposition is byte-identical to before.

        ``primary`` may be the rendered text or a zero-arg render
        callable; pass the callable so the fleet gauges this collect
        refreshes land in the SAME scrape, not the next one."""
        with self._lock:
            have_members = bool(self._members)
        if not have_members:
            return primary() if callable(primary) else primary
        workers = self.collect()
        self.merges += 1
        text = primary() if callable(primary) else primary
        if not workers:
            return text
        return merge_expositions(text, workers)

    def slow_queries(self) -> list[dict[str, Any]]:
        """Worker slow-query entries (each tagged with its proc) for the
        merged /admin/slow-queries view."""
        out: list[dict[str, Any]] = []
        for w in self.collect(count_stale=False):
            for entry in w.slow_queries:
                if isinstance(entry, dict):
                    e = dict(entry)
                    e["proc"] = w.proc
                    out.append(e)
        return out

    def stats(self) -> dict[str, Any]:
        """The /admin/stats ``fleet`` section's federation half."""
        workers = {}
        for w in self.collect(count_stale=False):
            workers[w.proc] = {
                "fresh": True,
                "age_s": round(w.age, 3),
                "generation": w.generation,
                "pid": w.pid,
                "slow_queries_recorded": w.slow_recorded,
            }
        with self._lock:
            for proc in self._members:
                if proc not in workers:
                    workers[proc] = {"fresh": False}
        return {
            "members": workers,
            "staleness_s": self.staleness_s,
            "stale_drops": self.stale_drops,
            "merges": self.merges,
        }


def merge_expositions(primary_text: str, workers) -> str:
    """Structural merge: group every family once (TYPE-once invariant),
    primary samples verbatim, worker samples with ``proc="<name>"``
    appended.  A worker exposition that fails the strict structural
    parse is skipped and counted — never spliced in broken."""
    try:
        fams = parse_exposition(primary_text)
    except ValueError:
        # the primary's own exposition must never fail; if it somehow
        # does, serve it untouched rather than drop the scrape
        log.exception("primary exposition failed structural parse")
        return primary_text
    # (family -> [(proc, FamilyBlock)]) for worker-only families, keyed
    # in first-seen order after the primary's
    extra_order: list[str] = []
    extra: dict[str, list] = {}
    appended: dict[str, list] = {}
    for w in workers:
        if not w.text:
            continue
        try:
            wfams = parse_exposition(w.text)
        except ValueError:
            FLEET_MERGE_ERRORS.inc()
            log.warning("worker %s exposition failed parse; skipped",
                        w.proc)
            continue
        label = f'proc="{w.proc}"'
        for name, fam in wfams.items():
            if not fam.samples:
                continue
            if name.startswith("nornicdb_fleet_"):
                # fleet-membership gauges are primary-side semantics; a
                # worker's own (empty-collector) cells would only shadow
                # them under a proc label
                continue
            if name in fams:
                if fams[name].kind != fam.kind:
                    FLEET_MERGE_ERRORS.inc()
                    continue
                appended.setdefault(name, []).append((label, fam))
            else:
                if name not in extra:
                    extra_order.append(name)
                    extra[name] = []
                extra[name].append((label, fam))
    out: list[str] = []
    for name, fam in fams.items():
        fam.render(out)
        for label, wfam in appended.get(name, ()):
            wfam.render_samples_only(out, label)
    for name in extra_order:
        first = True
        for label, wfam in extra[name]:
            if first:
                wfam.render(out, label)
                first = False
            else:
                wfam.render_samples_only(out, label)
    return "\n".join(out) + ("\n" if out else "")


#: process-global collector (the primary's WorkerPool registers into it;
#: /metrics merges through it)
FLEET = FleetCollector()
