"""Prometheus text-exposition parsing: the one strict reader.

Two consumers, one grammar:

- :func:`parse_prometheus_strict` — the PR 5 validation parser (moved
  here from tests/test_telemetry.py so the CI smoke script and the fleet
  federation tests share it): TYPE declared exactly once per family and
  before its samples, label escaping round-trips, histogram families
  carry cumulative ``_bucket`` series whose ``+Inf`` equals ``_count``.
  Raises :class:`ValueError` on any violation.
- :func:`parse_exposition` — the structural parser the cross-process
  metrics federation (telemetry/federation.py) merges worker expositions
  with: it keeps families in first-seen order with their HELP/TYPE
  comments and raw sample triples so a merged exposition re-renders
  byte-faithfully (modulo the injected ``proc`` label).

Stdlib-only, import-light (telemetry package contract).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+'
    r'(-?[0-9.e+\-]+|\+Inf|-Inf|NaN)$'
)
LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_KINDS = ("counter", "gauge", "histogram", "summary", "untyped")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


@dataclass
class FamilyBlock:
    """One metric family as parsed text: identity + raw sample lines.

    ``samples`` holds ``(name, labelstr, value)`` triples — ``labelstr``
    is the raw inside-the-braces text (no braces; empty for unlabeled
    samples) so re-rendering preserves the producer's exact escaping."""

    name: str
    kind: str
    help: str = ""
    samples: list[tuple[str, str, str]] = field(default_factory=list)

    def render(self, out: list[str], extra_label: str = "") -> None:
        """Append this family's lines; ``extra_label`` (e.g.
        ``proc="http-worker-0"``) is injected into every sample."""
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        self.render_samples_only(out, extra_label)

    def render_samples_only(self, out: list[str],
                            extra_label: str = "") -> None:
        """Samples without HELP/TYPE — for appending a second producer's
        cells to a family already declared in the output."""
        extra_name = extra_label.split("=", 1)[0] if extra_label else ""
        for name, labelstr, value in self.samples:
            labels = labelstr
            if extra_label:
                if extra_name and f'{extra_name}="' in labelstr:
                    # the producer already carries this label (e.g. a
                    # re-federated exposition): drop the stale pair so
                    # the injected identity wins and names stay unique
                    pairs = [p for p in LABEL_PAIR_RE.findall(labelstr)
                             if p[0] != extra_name]
                    labelstr = ",".join(f'{k}="{v}"' for k, v in pairs)
                labels = (f"{labelstr},{extra_label}" if labelstr
                          else extra_label)
            if labels:
                out.append(f"{name}{{{labels}}} {value}")
            else:
                out.append(f"{name} {value}")


def _family_of(name: str, types: dict[str, str]) -> str:
    """Resolve a sample name to its family (histogram suffix folding)."""
    if name in types:
        return name
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix) and name[: -len(suffix)] in types:
            return name[: -len(suffix)]
    return name


def parse_exposition(text: str) -> dict[str, FamilyBlock]:
    """Structural parse preserving family order and raw sample text.

    Raises ValueError on malformed lines, duplicate TYPE declarations,
    or samples without a preceding TYPE — the federation merge must
    never splice an unparseable worker exposition into /metrics."""
    fams: dict[str, FamilyBlock] = {}
    helps: dict[str, str] = {}
    types: dict[str, str] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            helps[name] = help_
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            if name in types:
                raise ValueError(f"TYPE for {name} declared twice")
            if kind not in _KINDS:
                raise ValueError(f"unknown TYPE kind: {line!r}")
            types[name] = kind
            fams[name] = FamilyBlock(name, kind, helps.get(name, ""))
            continue
        if line.startswith("#"):
            raise ValueError(f"unknown comment line: {line!r}")
        m = SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, _, labelstr, value = m.groups()
        base = _family_of(name, types)
        fam = fams.get(base)
        if fam is None:
            raise ValueError(f"sample {name} has no TYPE declaration")
        fam.samples.append((name, labelstr or "", value))
    return fams


def parse_prometheus_strict(
    text: str,
) -> tuple[dict[str, str], list[tuple[str, dict, float]]]:
    """Strict text-exposition reader (the PR 5 golden-test parser):
    TYPE declared exactly once per family and before its samples; samples
    parse; label escaping round-trips; histogram families carry
    cumulative ``_bucket`` series with a trailing ``+Inf`` equal to
    ``_count``.  Returns ``(types, samples)``; raises ValueError on any
    violation."""
    types: dict[str, str] = {}
    samples: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            if name in types:
                raise ValueError(f"TYPE for {name} declared twice")
            if kind not in ("counter", "gauge", "histogram", "summary"):
                raise ValueError(f"bad TYPE line: {line!r}")
            types[name] = kind
            continue
        if line.startswith("#"):
            raise ValueError(f"unknown comment line: {line!r}")
        m = SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, _, labelstr, value = m.groups()
        labels = dict(LABEL_PAIR_RE.findall(labelstr or ""))
        if labelstr:
            reconstructed = ",".join(
                f'{k}="{v}"' for k, v in LABEL_PAIR_RE.findall(labelstr)
            )
            if reconstructed != labelstr:
                raise ValueError(f"bad label escaping: {line!r}")
        samples.append((name, labels, float(value)))
    # every sample belongs to a declared family
    for name, labels, _ in samples:
        base = _family_of(name, types)
        if base not in types:
            raise ValueError(f"sample {name} has no TYPE declaration")
        if base != name and types[base] != "histogram":
            raise ValueError(
                f"suffixed sample {name} on non-histogram family {base}"
            )
    # histogram triple consistency (per non-le labelset)
    hist_names = [n for n, k in types.items() if k == "histogram"]
    for hname in hist_names:
        series: dict[tuple, list[tuple[float, float]]] = {}
        counts: dict[tuple, float] = {}
        for name, labels, value in samples:
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            if name == f"{hname}_bucket":
                series.setdefault(key, []).append(
                    (float(labels["le"]), value)
                )
            elif name == f"{hname}_count":
                counts[key] = value
        for key, buckets in series.items():
            buckets.sort(key=lambda b: b[0])
            cum = [c for _, c in buckets]
            if cum != sorted(cum):
                raise ValueError(f"{hname} buckets not cumulative")
            if buckets[-1][0] != float("inf"):
                raise ValueError(f"{hname} missing +Inf bucket")
            if key not in counts or buckets[-1][1] != counts[key]:
                raise ValueError(f"{hname} +Inf bucket != _count")
    return types, samples
