"""Unified telemetry: metrics registry, request tracing, slow-query capture.

One process-wide instrumentation layer for the whole serving stack
(HTTP/Bolt/gRPC -> cypher executor -> search/batcher -> storage/WAL ->
device sync -> replication). Three pillars:

- ``metrics`` — counters / gauges / fixed-bucket histograms with label
  sets.  Cells are resolved once at the instrumentation site and updated
  with a per-cell lock (no registry-wide locking, no allocation per
  observe).  ``Registry.render_prometheus()`` produces the full text
  exposition served at ``/metrics``; ``stats_callback`` adapts existing
  ``stats()`` / ``stats_snapshot()`` dicts into gauges without hand
  plumbing.
- ``tracing`` — contextvar-propagated trace context with spans recorded
  into a bounded ring buffer; W3C ``traceparent`` in/out on HTTP, carried
  across the Bolt/gRPC servers, the QueryBatcher worker hop, and
  replication RPCs.  Disabled or unsampled paths cost one contextvar read
  and allocate nothing (``tracer.span`` returns a shared no-op handle).
- ``slowlog`` — executor-recorded ring buffer of queries over a
  configurable threshold, with redacted query text, plan summary, span
  breakdown, and adjacency/device-sync counter deltas; served at
  ``/admin/slow-queries``.

The package is stdlib-only and import-light so any subsystem can
instrument itself without layering concerns.
"""

from __future__ import annotations

from nornicdb_tpu.telemetry.metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    REGISTRY,
    Registry,
    count_error,
)
from nornicdb_tpu.telemetry.slowlog import slow_log  # noqa: F401
from nornicdb_tpu.telemetry.tracing import (  # noqa: F401
    format_traceparent,
    parse_traceparent,
    tracer,
)


def configure(
    tracing_enabled=None,
    trace_sample=None,
    trace_buffer=None,
    slow_query_ms=None,
    slow_buffer=None,
    fleet_staleness_s=None,
    profile_max_seconds=None,
    cost_conservatism=None,
    cost_min_confidence=None,
    predictive_admission=None,
    slo_targets=None,
    slo_objective=None,
) -> None:
    """Apply config-file / CLI settings to the process-global telemetry
    singletons (config.TelemetryConfig maps 1:1 onto these arguments)."""
    tracer.configure(
        enabled=tracing_enabled,
        sample_rate=trace_sample,
        capacity=trace_buffer,
    )
    slow_log.configure(
        threshold_s=None if slow_query_ms is None else slow_query_ms / 1000.0,
        capacity=slow_buffer,
    )
    if fleet_staleness_s is not None:
        from nornicdb_tpu.telemetry.federation import FLEET

        FLEET.configure(staleness_s=fleet_staleness_s)
    if profile_max_seconds is not None:
        global profile_max_s
        profile_max_s = float(profile_max_seconds)
    if any(v is not None for v in (cost_conservatism, cost_min_confidence,
                                   predictive_admission, slo_targets,
                                   slo_objective)):
        from nornicdb_tpu.telemetry.costmodel import COST_MODEL

        COST_MODEL.configure(
            conservatism=cost_conservatism,
            min_confidence=cost_min_confidence,
            predictive_admission=predictive_admission,
            slo_targets=slo_targets,
            slo_objective=slo_objective,
        )


#: upper bound for POST /admin/profile?seconds=N captures (configurable
#: via TelemetryConfig.profile_max_seconds)
profile_max_s = 60.0
