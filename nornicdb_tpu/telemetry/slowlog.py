"""Slow-query capture: a bounded ring of queries over a threshold.

The Cypher executor calls ``slow_log.maybe_record(...)`` after every
statement; queries at or above ``threshold_s`` are recorded with:

- **redacted query text** — string literals are replaced with ``'?'``
  (parameter placeholders like ``$name`` are already value-free), and
  parameter values are reduced to type/size descriptors, so the ring
  never holds user data;
- a **plan summary** (EXPLAIN output, computed only for slow queries);
- the **span breakdown** of the active trace so far (time per span name);
- **adjacency / device-sync counter deltas** between query start and end
  (a lightweight integer probe on the hot path, diffed only when slow).

Served at ``/admin/slow-queries``; knobs: ``NORNICDB_SLOW_QUERY_MS``
(default 1000; 0 disables) and ``NORNICDB_SLOW_QUERY_BUFFER`` (128), or
``config.TelemetryConfig`` via ``telemetry.configure``.
"""

from __future__ import annotations

import os
import re
import time
from collections import deque
from typing import Any, Optional

from nornicdb_tpu.telemetry import budget as _budget

_STRING_LIT_RE = re.compile(
    r"""'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*\"""", re.S
)

# counters probed around every query; diffed only for slow ones.
# (name, attr-path) pairs resolved against the DB facade.
_MAX_QUERY_CHARS = 4096
_MAX_PLAN_CHARS = 2048


def redact_query(text: str) -> str:
    """Strip inline string literals; parameters stay as placeholders."""
    out = _STRING_LIT_RE.sub("'?'", text)
    if len(out) > _MAX_QUERY_CHARS:
        out = out[:_MAX_QUERY_CHARS] + "…"
    return out


def redact_params(params: Optional[dict]) -> dict[str, str]:
    """Parameter VALUES never enter the ring — only shape descriptors."""
    if not params:
        return {}
    out = {}
    for k, v in params.items():
        if isinstance(v, (list, tuple, set)):
            out[str(k)] = f"<{type(v).__name__}[{len(v)}]>"
        elif isinstance(v, dict):
            out[str(k)] = f"<dict[{len(v)}]>"
        elif isinstance(v, str):
            out[str(k)] = f"<str[{len(v)}]>"
        elif isinstance(v, bool) or v is None or isinstance(v, (int, float)):
            # scalars of these types are structural, not payload — but a
            # number can still be sensitive; keep only the type
            out[str(k)] = f"<{type(v).__name__}>"
        else:
            out[str(k)] = f"<{type(v).__name__}>"
    return out


def counters_probe(db) -> Optional[dict[str, float]]:
    """Cheap integer reads of the adjacency + device-sync counters (no
    dict building through the stats() surfaces, no locks)."""
    if db is None:
        return None
    out: dict[str, float] = {}
    snap = getattr(getattr(db, "storage", None), "_adjacency_snapshot", None)
    stats = getattr(snap, "stats", None)
    if stats is not None:
        out["adjacency_builds"] = stats.builds
        out["adjacency_delta_merges"] = stats.delta_merges
        out["adjacency_merged_edges"] = stats.merged_edges
        out["adjacency_epoch_retries"] = stats.epoch_retries
    search = getattr(db, "_search", None)  # never force lazy creation
    corpus = getattr(search, "_corpus", None)
    sync = getattr(corpus, "sync_stats", None)
    if sync is not None:
        out["sync_patches"] = sync.patches
        out["sync_full_uploads"] = sync.full_uploads
        out["sync_bytes_uploaded"] = sync.bytes_uploaded
        out["sync_query_stall_s"] = sync.query_stall_s
    # active recall-governed IVF plan: a slow search whose probe deltas
    # show a tune (or a drift re-tune) landing mid-query explains itself
    tune = getattr(search, "_tune_state", None)
    if tune is not None:
        out["ivf_n_probe"] = float(tune.n_probe if tune.serving_pruned
                                   else 0)
        out["ivf_local_k"] = float(tune.local_k if tune.serving_pruned
                                   else 0)
        out["ivf_measured_recall"] = float(tune.measured_recall)
        out["ivf_layout_epoch"] = float(tune.layout_epoch)
    counts = getattr(search, "tune_counts", None)
    if counts:
        out["ivf_tunes_total"] = float(sum(counts.values()))
    # columnar plan-cache counters (cypher/plan.py): a slow statement
    # whose deltas show a plan-cache miss just paid a fresh compile.
    # _executor, never the executor property — probing must not force
    # lazy executor construction
    ex = getattr(db, "_executor", None)
    pc = getattr(getattr(ex, "columnar", None), "cache", None)
    if pc is not None:
        out["cypher_plan_cache_hits"] = pc.hits
        out["cypher_plan_cache_misses"] = pc.misses
        out["cypher_plan_cache_invalidations"] = pc.invalidations
    return out or None


class SlowQueryLog:
    def __init__(self):
        try:
            ms = float(os.environ.get("NORNICDB_SLOW_QUERY_MS", "1000"))
        except ValueError:
            ms = 1000.0
        self.threshold_s = ms / 1000.0
        try:
            cap = int(os.environ.get("NORNICDB_SLOW_QUERY_BUFFER", "128"))
        except ValueError:
            cap = 128
        self._ring: deque[dict[str, Any]] = deque(maxlen=max(cap, 1))
        self.recorded = 0

    @property
    def enabled(self) -> bool:
        return self.threshold_s > 0

    def configure(self, threshold_s: Optional[float] = None,
                  capacity: Optional[int] = None) -> None:
        if threshold_s is not None:
            self.threshold_s = float(threshold_s)
        if capacity is not None:
            self._ring = deque(self._ring, maxlen=max(int(capacity), 1))

    def maybe_record(
        self,
        query: str,
        params: Optional[dict],
        duration_s: float,
        database: Optional[str] = None,
        plan: Optional[str] = None,
        probe_before: Optional[dict[str, float]] = None,
        probe_after: Optional[dict[str, float]] = None,
        trace_spans: Optional[list[dict]] = None,
        trace_id: Optional[str] = None,
        columnar: Optional[dict[str, Any]] = None,
        served: Optional[str] = None,
    ) -> bool:
        if not self.enabled or duration_s < self.threshold_s:
            return False
        deltas = None
        if probe_before and probe_after:
            deltas = {
                k: probe_after[k] - probe_before[k]
                for k in probe_after
                if k in probe_before and probe_after[k] != probe_before[k]
            }
        breakdown: dict[str, dict[str, float]] = {}
        for rec in trace_spans or []:
            agg = breakdown.setdefault(
                rec["name"], {"count": 0, "total_ms": 0.0}
            )
            agg["count"] += 1
            agg["total_ms"] += rec["duration_ms"]
        for agg in breakdown.values():
            agg["total_ms"] = round(agg["total_ms"], 3)
        entry = {
            "query": redact_query(query),
            "params": redact_params(params),
            "duration_ms": round(duration_s * 1e3, 3),
            "timestamp": time.time(),
            "database": database,
            "trace_id": trace_id,
            "span_breakdown": breakdown or None,
            # deadline-budget stage attribution (predicted at admission
            # vs actual from the spans) for offloaded device programs
            "budget": _budget.breakdown_for(trace_id, trace_spans),
            "counter_deltas": deltas,
            "plan": (plan[:_MAX_PLAN_CHARS] if plan else None),
            # columnar engine report: plan-cache key hash, outcome, and
            # measured per-operator timings (value-free — operator labels
            # render lifted literals as §N placeholders)
            "columnar": columnar,
        }
        if served is not None:
            # served-path attribution for worker-side vector searches
            # (broker / shm / proxy — the serving-ladder step that
            # actually answered)
            entry["served"] = served
        self._ring.append(entry)  # deque.append: atomic under the GIL
        self.recorded += 1
        return True

    def snapshot(self, limit: int = 100) -> list[dict[str, Any]]:
        """Newest-first for /admin/slow-queries."""
        return list(self._ring)[-limit:][::-1]

    def clear(self) -> None:
        self._ring.clear()


slow_log = SlowQueryLog()
