"""Per-request deadline-budget ledger: where did the deadline go?

A request admitted with a deadline has a fixed budget of wall time; this
module attributes that budget to named pipeline stages so a slow or shed
request explains itself:

- **Stages** are a small closed vocabulary (``admission_queue``,
  ``tokenize_pack``, ``broker_hop``, ``prefill``, ``decode``,
  ``device_sync``, ``host_merge``) mapped from the span names the
  serving stack already records — no new instrumentation on the hot
  path, the tracer's retroactive spans ARE the actuals.
- **Predictions** land at admission: the predictive-admission points
  (serving/genserve/search) call :meth:`BudgetLedger.open` with the cost
  model's per-stage estimates, keyed by trace id on the existing trace
  context.
- **Breakdowns** (:func:`breakdown_for`) join predicted vs actual per
  stage for a finished trace — attached to slow-query entries
  (telemetry/slowlog.py) and the ``/admin/traces/<id>`` detail view.

The ledger is a bounded LRU (no growth under sustained traffic) and the
whole module is stdlib-only (telemetry package contract).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Optional

#: closed stage vocabulary, in pipeline order
STAGES = (
    "admission_queue", "tokenize_pack", "broker_hop", "prefill",
    "decode", "device_sync", "host_merge",
)

#: span name -> budget stage.  Span names are the tracer's existing
#: vocabulary (docs/observability.md trace maps); anything unmapped is
#: simply not budget-attributed (it still shows in the span breakdown).
SPAN_STAGE_MAP = {
    "serving.queue_wait": "admission_queue",
    "search.queue_wait": "admission_queue",
    "genserve.queue_wait": "admission_queue",
    "genserve.admit": "admission_queue",
    "search.embed": "tokenize_pack",
    "worker.broker_call": "broker_hop",
    "worker.shm_search": "broker_hop",
    "genserve.prefill": "prefill",
    "genserve.decode": "decode",
    "serving.batch": "device_sync",
    "search.batch": "device_sync",
    "search.vector": "device_sync",
    "device.sync": "device_sync",
    "search.rank": "host_merge",
}

_MAX_ENTRIES = 512


class _Entry:
    __slots__ = ("route", "slack_s", "predicted_s", "opened_wall")

    def __init__(self, route: str, slack_s: float,
                 predicted_s: dict[str, float]):
        self.route = route
        self.slack_s = slack_s
        self.predicted_s = dict(predicted_s)
        self.opened_wall = time.time()


class BudgetLedger:
    """trace_id -> admission-time prediction, bounded LRU."""

    def __init__(self, capacity: int = _MAX_ENTRIES):
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._capacity = capacity
        self.opened = 0

    def open(self, trace_id: Optional[str], route: str, slack_s: float,
             predicted_s: dict[str, float]) -> None:
        """Record the admission-time stage predictions for a trace.
        No-op without a trace id (untraced/unsampled requests carry no
        budget — the ledger keys on the trace the breakdown joins)."""
        if not trace_id:
            return
        entry = _Entry(route, slack_s, predicted_s)
        with self._lock:
            self._entries[trace_id] = entry
            self._entries.move_to_end(trace_id)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
            self.opened += 1

    def get(self, trace_id: Optional[str]) -> Optional[_Entry]:
        if not trace_id:
            return None
        with self._lock:
            return self._entries.get(trace_id)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


def stage_actuals(spans) -> dict[str, dict[str, float]]:
    """Fold span records into per-stage actuals:
    ``{stage: {"ms": total, "count": n}}`` (unmapped spans skipped)."""
    out: dict[str, dict[str, float]] = {}
    for rec in spans or []:
        if not isinstance(rec, dict):
            continue
        stage = SPAN_STAGE_MAP.get(rec.get("name"))
        if stage is None:
            continue
        agg = out.setdefault(stage, {"ms": 0.0, "count": 0})
        agg["ms"] += float(rec.get("duration_ms") or 0.0)
        agg["count"] += 1
    for agg in out.values():
        agg["ms"] = round(agg["ms"], 3)
    return out


def breakdown_for(trace_id: Optional[str],
                  spans) -> Optional[dict[str, Any]]:
    """Join the ledger's admission-time predictions with the trace's
    span-derived actuals into one stage table (pipeline order; stages
    with neither prediction nor actual are omitted).  None when the
    trace has no budget-attributable content at all."""
    actuals = stage_actuals(spans)
    entry = LEDGER.get(trace_id)
    if not actuals and entry is None:
        return None
    predicted = entry.predicted_s if entry is not None else {}
    stages = []
    for stage in STAGES:
        pred_s = predicted.get(stage)
        act = actuals.get(stage)
        if pred_s is None and act is None:
            continue
        stages.append({
            "stage": stage,
            "predicted_ms": (round(pred_s * 1e3, 3)
                             if pred_s is not None else None),
            "actual_ms": act["ms"] if act else None,
            "spans": act["count"] if act else 0,
        })
    out: dict[str, Any] = {"stages": stages}
    if entry is not None:
        out["route"] = entry.route
        out["deadline_budget_ms"] = round(entry.slack_s * 1e3, 3)
        out["predicted_total_ms"] = round(
            sum(predicted.values()) * 1e3, 3)
    actual_total = sum(s["actual_ms"] or 0.0 for s in stages)
    out["actual_total_ms"] = round(actual_total, 3)
    return out


#: process-global ledger (admission points write, slowlog/traces read)
LEDGER = BudgetLedger()

open_budget = LEDGER.open
