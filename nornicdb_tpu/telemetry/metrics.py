"""Metrics registry: counters, gauges, fixed-bucket histograms.

Design targets (ISSUE 5 tentpole):

- **Hot-path cheap.** An instrumentation site resolves its cell ONCE
  (``family.labels(...)`` caches per label-value tuple) and every
  ``inc``/``set``/``observe`` afterwards is a slot update under that
  cell's own small lock — no registry lock, no dict lookup, no string
  formatting, no allocation.  Rendering walks the registry under the
  registry lock but never holds any cell lock while calling out
  (nornsan: cell locks are leaves).
- **Valid exposition.** ``render_prometheus()`` emits ``# HELP`` /
  ``# TYPE`` once per family, escapes label values, and renders
  histograms as cumulative ``_bucket`` / ``_sum`` / ``_count`` triples —
  the golden-file test in tests/test_telemetry.py parses the output with
  a strict reader.
- **Adapters, not re-plumbing.** ``stats_callback`` registers an existing
  ``stats()`` / ``stats_snapshot()`` dict provider; numeric leaves are
  flattened into gauges at render time (with optional exact-name renames
  for metrics whose names are documented/asserted, e.g.
  ``nornicdb_adjacency_builds_total``).

Registries nest: ``Registry(parent=REGISTRY)`` renders the process-global
instrumentation families plus its own — the HTTP server keeps its
db-specific callbacks in a child registry so multiple servers in one
process (tests) never fight over one namespace.
"""

from __future__ import annotations

import logging
import math
import re
import threading
from bisect import bisect_left
from typing import Any, Callable, Iterable, Optional

log = logging.getLogger(__name__)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# latency buckets (seconds): 100us .. 10s, roughly prometheus defaults
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)
# transfer-size buckets (bytes): 1KiB .. 1GiB
BYTE_BUCKETS = (
    1024.0, 16384.0, 131072.0, 1048576.0, 16777216.0,
    134217728.0, 1073741824.0,
)


def _escape_label(value: Any) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _fmt(v: float) -> str:
    """Prometheus sample value: integral values render without a decimal
    point (``{:g}`` would silently round counters past 6 digits)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 2**63:
        return str(int(f))
    return repr(f)


class CounterCell:
    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def get(self) -> float:
        return self.value


class GaugeCell:
    __slots__ = ("_lock", "value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self.value -= n

    def get(self) -> float:
        return self.value


class HistogramCell:
    __slots__ = ("_lock", "bounds", "counts", "sum", "count")

    def __init__(self, bounds: tuple[float, ...]):
        self._lock = threading.Lock()
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        idx = bisect_left(self.bounds, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v
            self.count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        with self._lock:
            return list(self.counts), self.sum, self.count


_CELL_TYPES = {
    "counter": CounterCell,
    "gauge": GaugeCell,
    "histogram": HistogramCell,
}


class Family:
    """One named metric with a fixed label-name set and per-label-value
    cells.  The zero-label family IS its single cell's facade: ``inc`` /
    ``set`` / ``observe`` delegate to ``labels()``."""

    def __init__(
        self,
        name: str,
        kind: str,
        help_: str,
        labelnames: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.kind = kind
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._cells: dict[tuple[str, ...], Any] = {}

    def labels(self, *values: Any) -> Any:
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got {key}"
            )
        cell = self._cells.get(key)
        if cell is None:
            with self._lock:
                cell = self._cells.get(key)
                if cell is None:
                    if self.kind == "histogram":
                        cell = HistogramCell(self.buckets)
                    else:
                        cell = _CELL_TYPES[self.kind]()
                    self._cells[key] = cell
        return cell

    # zero-label convenience -------------------------------------------------
    def inc(self, n: float = 1.0) -> None:
        self.labels().inc(n)

    def set(self, v: float) -> None:
        self.labels().set(v)

    def observe(self, v: float) -> None:
        self.labels().observe(v)

    def get(self, *values: Any) -> float:
        return self.labels(*values).get()

    # rendering --------------------------------------------------------------
    def _label_str(self, key: tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{ln}="{_escape_label(lv)}"'
            for ln, lv in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self, out: list[str]) -> None:
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            cells = list(self._cells.items())
        for key, cell in sorted(cells):
            if self.kind == "histogram":
                counts, total, n = cell.snapshot()
                cum = 0
                for bound, c in zip(cell.bounds, counts):
                    cum += c
                    le = 'le="%s"' % _fmt(bound)
                    out.append(
                        f"{self.name}_bucket{self._label_str(key, le)} {cum}"
                    )
                cum += counts[-1]
                inf = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket{self._label_str(key, inf)} {cum}"
                )
                out.append(
                    f"{self.name}_sum{self._label_str(key)} {_fmt(total)}"
                )
                out.append(f"{self.name}_count{self._label_str(key)} {n}")
            else:
                out.append(
                    f"{self.name}{self._label_str(key)} {_fmt(cell.get())}"
                )


def _flatten(prefix: str, data: Any, out: dict[str, float]) -> None:
    if isinstance(data, dict):
        for k, v in data.items():
            key = str(k).replace("-", "_").replace(".", "_")
            _flatten(f"{prefix}_{key}" if prefix else key, v, out)
    elif isinstance(data, bool):
        out[prefix] = 1.0 if data else 0.0
    elif isinstance(data, (int, float)):
        out[prefix] = float(data)
    # strings / lists / None are not metrics: skipped


class _StatsAdapter:
    """Render-time adapter flattening a stats() dict into samples."""

    def __init__(
        self,
        prefix: str,
        fn: Callable[[], Optional[dict]],
        help_: str,
        rename: Optional[dict[str, str]],
        counters: Iterable[str],
    ):
        self.prefix = prefix
        self.fn = fn
        self.help = help_
        self.rename = dict(rename or {})
        self.counters = frozenset(counters)

    def samples(self) -> list[tuple[str, str, str, float]]:
        """-> [(metric_name, kind, help, value)]"""
        data = self.fn()
        if not isinstance(data, dict):
            return []
        flat: dict[str, float] = {}
        _flatten(self.prefix, data, flat)
        out = []
        for flat_name, value in sorted(flat.items()):
            name = self.rename.get(flat_name, flat_name)
            if not _NAME_RE.match(name):
                continue
            kind = "counter" if flat_name in self.counters else "gauge"
            out.append((name, kind, self.help, value))
        return out


class Registry:
    """Metric families + render-time callbacks, optionally chained to a
    parent registry whose families render first."""

    def __init__(self, parent: Optional["Registry"] = None):
        self.parent = parent
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}
        # name -> (kind, help, fn) with fn() -> float
        self._callbacks: dict[str, tuple[str, str, Callable[[], float]]] = {}
        self._adapters: dict[str, _StatsAdapter] = {}
        # key -> fn() -> [(name, kind, help, value)], for providers whose
        # metric names/types are only known at render time (heimdall's
        # named-metric registry)
        self._family_callbacks: dict[
            str, Callable[[], list[tuple[str, str, str, float]]]
        ] = {}
        # key -> fn(), invoked BEFORE families render: refresh hooks for
        # labeled gauge families whose values are derived at scrape time
        # (the deviceprof HBM residency collector) — the family itself
        # renders through the normal path afterwards
        self._collect_hooks: dict[str, Callable[[], None]] = {}

    # -- family creation (idempotent: instrumentation sites may re-run) ----
    def _family(
        self,
        name: str,
        kind: str,
        help_: str,
        labels: tuple[str, ...],
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name} re-registered as {kind}{labels} "
                        f"(was {fam.kind}{fam.labelnames})"
                    )
                return fam
            fam = Family(name, kind, help_, tuple(labels), buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Family:
        return self._family(name, "counter", help_, labels)

    def gauge(self, name: str, help_: str = "", labels: tuple[str, ...] = ()) -> Family:
        return self._family(name, "gauge", help_, labels)

    def histogram(
        self,
        name: str,
        help_: str = "",
        labels: tuple[str, ...] = (),
        buckets: tuple[float, ...] = DEFAULT_BUCKETS,
    ) -> Family:
        return self._family(name, "histogram", help_, labels, buckets)

    # -- render-time callbacks (replace-on-re-register: a new server
    # instance in the same process takes over its names) -------------------
    def gauge_callback(
        self, name: str, help_: str, fn: Callable[[], float],
        kind: str = "gauge",
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            self._callbacks[name] = (kind, help_, fn)

    def counter_callback(self, name: str, help_: str, fn: Callable[[], float]) -> None:
        self.gauge_callback(name, help_, fn, kind="counter")

    def stats_callback(
        self,
        prefix: str,
        fn: Callable[[], Optional[dict]],
        help_: str = "",
        rename: Optional[dict[str, str]] = None,
        counters: Iterable[str] = (),
    ) -> None:
        """Adapt an existing stats()/stats_snapshot() provider: numeric
        leaves of the returned dict become gauges named
        ``<prefix>_<path_joined_by_underscores>``.  ``rename`` maps a
        flattened name to an exact metric name (for documented names);
        ``counters`` marks flattened names whose TYPE is counter."""
        with self._lock:
            self._adapters[prefix] = _StatsAdapter(
                prefix, fn, help_, rename, counters
            )

    def families_callback(
        self,
        key: str,
        fn: Callable[[], list[tuple[str, str, str, float]]],
    ) -> None:
        """Register a provider returning fully-formed samples
        ``[(metric_name, kind, help, value)]`` at render time."""
        with self._lock:
            self._family_callbacks[key] = fn

    def collect_hook(self, key: str, fn: Callable[[], None]) -> None:
        """Register a refresh hook run at the START of every render —
        for labeled families whose cell values are derived at scrape
        time (a plain gauge_callback cannot carry labels).  Hooks must
        be cheap and lock-light: they run on the scrape thread."""
        with self._lock:
            self._collect_hooks[key] = fn

    # -- rendering ----------------------------------------------------------
    def render_prometheus(self) -> str:
        out: list[str] = []
        seen: set[str] = set()
        self._render_into(out, seen)
        return "\n".join(out) + ("\n" if out else "")

    def _render_into(self, out: list[str], seen: set[str]) -> None:
        if self.parent is not None:
            self.parent._render_into(out, seen)
        with self._lock:
            collect_hooks = list(self._collect_hooks.items())
        for key, hook in collect_hooks:
            try:
                hook()
            except Exception:
                # a dead refresher must not take the exposition down
                log.debug("collect hook %s failed", key, exc_info=True)
        with self._lock:
            families = sorted(self._families.items())
            callbacks = sorted(self._callbacks.items())
            adapters = sorted(self._adapters.items())
            family_callbacks = sorted(self._family_callbacks.items())
        for name, fam in families:
            if name in seen:
                continue
            seen.add(name)
            fam.render(out)
        for name, (kind, help_, fn) in callbacks:
            if name in seen:
                continue
            try:
                value = fn()
            except Exception:
                # a dead provider (closed db in tests) must not take the
                # whole exposition down
                log.debug("metrics callback %s failed", name, exc_info=True)
                continue
            seen.add(name)
            if help_:
                out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {kind}")
            out.append(f"{name} {_fmt(value)}")
        for _, adapter in adapters:
            try:
                samples = adapter.samples()
            except Exception:
                log.debug(
                    "stats adapter %s failed", adapter.prefix, exc_info=True
                )
                continue
            for name, kind, help_, value in samples:
                if name in seen:
                    continue
                seen.add(name)
                if help_:
                    out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {kind}")
                out.append(f"{name} {_fmt(value)}")
        for key, fn in family_callbacks:
            try:
                samples = fn()
            except Exception:
                log.debug("families callback %s failed", key, exc_info=True)
                continue
            for name, kind, help_, value in samples:
                if name in seen or not _NAME_RE.match(name):
                    continue
                seen.add(name)
                if help_:
                    out.append(f"# HELP {name} {help_}")
                out.append(f"# TYPE {name} {kind}")
                out.append(f"{name} {_fmt(value)}")


#: process-global registry for instrumentation-site metrics (WAL, executor,
#: search, device sync, ...).  Server-owned db-specific callbacks live in a
#: child ``Registry(parent=REGISTRY)`` per server instance.
REGISTRY = Registry()

_component_errors = REGISTRY.counter(
    "nornicdb_component_errors_total",
    "Errors swallowed-but-logged by component (NL-ERR hygiene sites)",
    labels=("component",),
)


def count_error(component: str) -> None:
    """Error-hygiene helper: silent-except sites log AND count here, so
    operators see failure rates without grepping logs."""
    _component_errors.labels(component).inc()
