"""Request tracing: contextvar-propagated spans in a bounded ring buffer.

A trace starts at an ingress (HTTP dispatch, Bolt RUN, gRPC search,
replication RPC delivery) via ``tracer.start_trace(...)`` and flows to
every ``tracer.span(...)`` below it on the same logical context: child
threads inherit via ``contextvars.copy_context()`` (the Raft broadcast
hop), explicit worker hand-offs use ``tracer.capture()`` +
``tracer.attach()`` (the QueryBatcher hop), and process boundaries carry
W3C ``traceparent`` (HTTP header, replication Message field).

Always-on-cheap contract (asserted by the ``-m slow`` microbench in
tests/test_telemetry.py): when tracing is disabled, or no trace is active
on the context, or the trace was not sampled, ``tracer.span()`` performs
ONE contextvar read and returns a shared no-op handle — no allocation, no
locking, no formatting.

Completed traces land in a bounded ring buffer (``deque(maxlen=...)``,
whose appends are atomic under the GIL — no lock held while recording)
served at ``/admin/traces`` and ``/admin/traces/<id>``.  Span lists are
plain lists appended in finish order; ``list.append`` is atomic, so a
worker thread finishing a span never blocks an ingress thread.  A span
finishing after its root closed still lands in the (already ringed)
trace — late device work stays visible.
"""

from __future__ import annotations

import contextvars
import os
import random
import re
import time
import uuid
from collections import deque
from typing import Any, Optional

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

# spans recorded per trace before further spans are counted-but-dropped
MAX_SPANS_PER_TRACE = 512


def parse_traceparent(header: str) -> Optional[tuple[str, str, bool]]:
    """-> (trace_id, parent_span_id, sampled) or None if malformed
    (W3C trace-context: version-traceid-parentid-flags)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 0x01)


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def _new_trace_id() -> str:
    return uuid.uuid4().hex


def _new_span_id() -> str:
    return os.urandom(8).hex()


class _Trace:
    """Collector for one trace: finished-span records + identity."""

    __slots__ = (
        "trace_id", "root_span_id", "remote_parent", "started_wall",
        "spans", "dropped_spans",
    )

    def __init__(self, trace_id: str, root_span_id: str,
                 remote_parent: Optional[str]):
        self.trace_id = trace_id
        self.root_span_id = root_span_id
        self.remote_parent = remote_parent
        self.started_wall = time.time()
        self.spans: list[dict[str, Any]] = []
        self.dropped_spans = 0

    def record(self, rec: dict[str, Any]) -> None:
        if len(self.spans) >= MAX_SPANS_PER_TRACE:
            self.dropped_spans += 1
            return
        self.spans.append(rec)  # list.append: atomic under the GIL


class _NoopSpan:
    """Shared handle for the disabled/unsampled path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_attr(self, key: str, value: Any) -> None:
        pass

    # duck-typed introspection used by ingress code
    trace_id = None
    span_id = None

    def traceparent(self) -> Optional[str]:
        return None


NOOP_SPAN = _NoopSpan()


class Span:
    __slots__ = (
        "_tracer", "trace", "name", "span_id", "parent_id",
        "_t0", "_start_wall", "attrs", "_token", "_is_root", "error",
    )

    def __init__(self, tracer: "Tracer", trace: _Trace, name: str,
                 parent_id: Optional[str], is_root: bool,
                 attrs: Optional[dict] = None):
        self._tracer = tracer
        self.trace = trace
        self.name = name
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.attrs = dict(attrs) if attrs else None
        self._is_root = is_root
        self.error = None
        self._token: Optional[contextvars.Token] = None
        self._t0 = 0.0
        self._start_wall = 0.0

    @property
    def trace_id(self) -> str:
        return self.trace.trace_id

    def traceparent(self) -> str:
        return format_traceparent(self.trace.trace_id, self.span_id)

    def set_attr(self, key: str, value: Any) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self._start_wall = time.time()
        self._t0 = time.perf_counter()
        self._token = self._tracer._var.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        duration = time.perf_counter() - self._t0
        if self._token is not None:
            self._tracer._var.reset(self._token)
            self._token = None
        if exc is not None:
            self.error = f"{type(exc).__name__}: {exc}"
        rec = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self._start_wall,
            "duration_ms": duration * 1e3,
        }
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.error:
            rec["error"] = self.error
        self.trace.record(rec)
        if self._is_root:
            self._tracer._finish(self.trace, self.name, duration)
        return False


class _Attach:
    """Re-enter a captured span on another thread's context (worker
    hand-off, e.g. the QueryBatcher flush thread)."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Optional[Span]):
        self._tracer = tracer
        self._span = span
        self._token = None

    def __enter__(self) -> Optional[Span]:
        if self._span is not None:
            self._token = self._tracer._var.set(self._span)
        return self._span

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            self._tracer._var.reset(self._token)
            self._token = None
        return False


class Tracer:
    def __init__(self, capacity: int = 256):
        self.enabled = os.environ.get(
            "NORNICDB_TRACING", "1"
        ).lower() not in ("0", "false", "no")
        try:
            self.sample_rate = float(
                os.environ.get("NORNICDB_TRACE_SAMPLE", "1.0")
            )
        except ValueError:
            self.sample_rate = 1.0
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._var: contextvars.ContextVar[Optional[Span]] = (
            contextvars.ContextVar("nornicdb_trace_span", default=None)
        )

    def configure(self, enabled=None, sample_rate=None, capacity=None) -> None:
        if enabled is not None:
            self.enabled = bool(enabled)
        if sample_rate is not None:
            self.sample_rate = float(sample_rate)
        if capacity is not None:
            self._ring = deque(self._ring, maxlen=int(capacity))

    # -- span creation -----------------------------------------------------
    def start_trace(self, name: str, traceparent: Optional[str] = None,
                    attrs: Optional[dict] = None):
        """Open a ROOT span (new trace, or continuing an incoming
        ``traceparent``'s trace id).  Unsampled/disabled -> no-op handle."""
        if not self.enabled:
            return NOOP_SPAN
        trace_id = remote_parent = None
        sampled = None
        if traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed is not None:
                trace_id, remote_parent, sampled = parsed
        if sampled is None:
            sampled = (
                self.sample_rate >= 1.0
                or random.random() < self.sample_rate
            )
        if not sampled:
            return NOOP_SPAN
        trace = _Trace(trace_id or _new_trace_id(), "", remote_parent)
        span = Span(self, trace, name, remote_parent, is_root=True,
                    attrs=attrs)
        trace.root_span_id = span.span_id
        return span

    def span(self, name: str, attrs: Optional[dict] = None):
        """Child span of the context's active span; shared no-op handle
        when no trace is active (ONE contextvar read, no allocation)."""
        cur = self._var.get()
        if cur is None:
            return NOOP_SPAN
        return Span(self, cur.trace, name, cur.span_id, is_root=False,
                    attrs=attrs)

    def add_span(self, name: str, start_perf: float, end_perf: float,
                 attrs: Optional[dict] = None,
                 parent: Optional[Span] = None) -> None:
        """Retroactively record a completed span (measured with
        perf_counter timestamps) under ``parent`` or the active span —
        used where the timing is known only after the fact (per-caller
        queue wait inside a shared batch)."""
        cur = parent if parent is not None else self._var.get()
        if cur is None or isinstance(cur, _NoopSpan):
            return
        rec = {
            "name": name,
            "span_id": _new_span_id(),
            "parent_id": cur.span_id,
            # display WALL timestamp back-derived from the perf offset; the
            # duration itself is pure perf_counter arithmetic
            "start": time.time()  # nornlint: disable=NL-TM01
            - (time.perf_counter() - start_perf),
            "duration_ms": (end_perf - start_perf) * 1e3,
        }
        if attrs:
            rec["attrs"] = dict(attrs)
        cur.trace.record(rec)

    # -- context plumbing --------------------------------------------------
    def capture(self) -> Optional[Span]:
        """The active span, for hand-off to a worker via ``attach()``."""
        return self._var.get()

    def attach(self, span: Optional[Span]) -> _Attach:
        return _Attach(self, span)

    def current_traceparent(self) -> Optional[str]:
        cur = self._var.get()
        if cur is None:
            return None
        return cur.traceparent()

    def current_trace_id(self) -> Optional[str]:
        cur = self._var.get()
        return None if cur is None else cur.trace.trace_id

    # -- cross-process merge ------------------------------------------------
    def merge_remote(
        self,
        trace_id: str,
        spans: list[dict],
        root: Optional[str] = None,
        started: Optional[float] = None,
        duration_ms: Optional[float] = None,
        proc: Optional[str] = None,
    ) -> bool:
        """Merge span records exported by ANOTHER process (a prefork
        worker shipping its finished trace over the device broker) into
        the local ring, so ``/admin/traces/<id>`` renders one tree
        spanning both processes.

        Spans keep their own ``span_id``/``parent_id`` identities — the
        worker's traceparent hand-off means local spans already point at
        the remote caller's span id, so the tree builder nests them
        without any re-parenting.  Each merged record is tagged with the
        originating ``proc`` so the tree says which process ran what.
        Records land in the NEWEST ring entry with this trace id, or a
        fresh entry when the local process never recorded one (e.g. a
        shm-served worker search that never touched the primary)."""
        if not self.enabled or not trace_id:
            return False
        clean: list[dict[str, Any]] = []
        for rec in list(spans)[:MAX_SPANS_PER_TRACE]:
            if not isinstance(rec, dict) or not rec.get("span_id"):
                continue
            r = dict(rec)
            if proc:
                r["proc"] = proc
            clean.append(r)
        if not clean:
            return False
        found = None
        # snapshot: iterating the live deque races root-span finishes
        for t in list(self._ring):
            if t["trace_id"] == trace_id:
                found = t  # latest entry with this id wins
        if found is not None:
            found["spans"].extend(clean)  # list.extend: atomic under GIL
            return True
        self._ring.append({
            "trace_id": trace_id,
            "root": root or (clean[0].get("name") or "remote"),
            "started": started if started is not None
            else (clean[0].get("start") or time.time()),
            "duration_ms": duration_ms if duration_ms is not None
            else max((s.get("duration_ms") or 0.0) for s in clean),
            "spans": clean,
            "dropped_spans": 0,
            "remote_parent": None,
        })
        return True

    # -- ring buffer -------------------------------------------------------
    def _finish(self, trace: _Trace, root_name: str, duration: float) -> None:
        self._ring.append({
            "trace_id": trace.trace_id,
            "root": root_name,
            "started": trace.started_wall,
            "duration_ms": duration * 1e3,
            "spans": trace.spans,
            "dropped_spans": trace.dropped_spans,
            "remote_parent": trace.remote_parent,
        })

    def count(self) -> int:
        return len(self._ring)

    def traces(self, limit: int = 100) -> list[dict[str, Any]]:
        """Newest-first summaries for /admin/traces."""
        entries = list(self._ring)[-limit:][::-1]
        return [
            {
                "trace_id": t["trace_id"],
                "root": t["root"],
                "started": t["started"],
                "duration_ms": round(t["duration_ms"], 3),
                "span_count": len(t["spans"]),
                "dropped_spans": t["dropped_spans"],
            }
            for t in entries
        ]

    def trace(self, trace_id: str) -> Optional[dict[str, Any]]:
        """Full span tree for /admin/traces/<id> (children nested under
        parents; spans with a missing parent surface at the top level).

        A trace id may own SEVERAL ring entries — a worker's root and the
        broker handler continuing it in-process, or a replication peer's
        handler entries — so the detail view merges every matching
        entry's spans (deduped by span id) into one tree; identity
        fields come from the latest entry, preserving the old
        single-entry behavior."""
        # snapshot first: iterating the live deque would raise if another
        # thread's root span finishes (ring append) mid-scan
        matches = [t for t in list(self._ring)
                   if t["trace_id"] == trace_id]
        if not matches:
            return None
        found = matches[-1]  # latest entry wins the identity fields
        if len(matches) == 1:
            spans = list(found["spans"])
        else:
            seen_ids: set = set()
            spans = []
            for t in matches:
                for rec in list(t["spans"]):
                    sid = rec.get("span_id")
                    if sid in seen_ids:
                        continue
                    seen_ids.add(sid)
                    spans.append(rec)
        nodes = {
            rec["span_id"]: dict(rec, children=[]) for rec in spans
        }
        roots = []
        for rec in spans:
            node = nodes[rec["span_id"]]
            # .get(): remote-merged records may omit parent_id entirely
            parent = nodes.get(rec.get("parent_id") or "")
            if parent is not None and parent is not node:
                parent["children"].append(node)
            else:
                roots.append(node)
        for node in nodes.values():
            node["children"].sort(key=lambda n: n.get("start", 0.0))
        roots.sort(key=lambda n: n.get("start", 0.0))
        return {
            "trace_id": found["trace_id"],
            "root": found["root"],
            "started": found["started"],
            "duration_ms": found["duration_ms"],
            "dropped_spans": found["dropped_spans"],
            "remote_parent": found["remote_parent"],
            "spans": spans,  # flat finish-order list (tree view below)
            "tree": roots,
        }

    def clear(self) -> None:
        self._ring.clear()


tracer = Tracer()
