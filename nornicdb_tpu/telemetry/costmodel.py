"""Online per-program cost model: the capacity loop's control signal.

The deviceprof ledger (telemetry/deviceprof.py) records what every device
dispatch *did* cost, keyed ``(subsystem, kind, shape-class)``.  This
module learns from that stream — an EWMA per shape class plus a per-unit
EWMA per kind — and turns it around into *predictions* the admission
points consult BEFORE dispatching:

- :meth:`CostModel.observe` is wired as a deviceprof time observer:
  every ``record_execute`` first scores the model (relative error of the
  standing prediction vs the actual, into
  ``nornicdb_cost_model_relative_error``) and then folds the sample in.
- :meth:`CostModel.predict` answers "what will a dispatch of this kind
  and size cost" — exact shape-class EWMA when the class has history,
  per-unit scaling from the kind aggregate otherwise, a cold-start prior
  as the last resort — with a confidence score ``n / (n + K)``.
- :meth:`CostModel.decide` is the predictive-admission primitive: given
  the caller's deadline slack and the work already queued ahead of it,
  shed at submit (``reason="predicted_deadline"``) when the conservative
  prediction cannot fit, fail OPEN while confidence is low (a cold model
  must never turn traffic away), and always admit when predictive
  admission is disabled.  Decisions are counted in
  ``nornicdb_cost_model_admission_total{route,decision}``.
- :meth:`CostModel.record_latency` feeds per-route SLO burn-rate gauges
  (``nornicdb_slo_burn_rate``): the miss fraction over a sliding window
  divided by the error budget ``1 - objective`` — burn > 1 means the
  route is eating budget faster than the SLO allows.
- :meth:`CostModel.capacity_snapshot` renders the whole table for
  ``GET /admin/capacity``: per-program costs, confidence, and a headroom
  estimate (max sustainable qps per workload class, device-serialized).

Knobs (config.TelemetryConfig / ``NORNICDB_TELEMETRY_*`` env):
``cost_conservatism`` (predictions are multiplied by this before the
deadline comparison), ``cost_min_confidence`` (fail-open floor),
``predictive_admission`` (master switch), ``slo_targets``
(``"route=ms,route=ms"``), ``slo_objective``.

Import-light and stdlib-only (telemetry package contract); the
``nornicdb_build_info`` info-gauge also lives here so every process that
can answer /admin/capacity also says what build is answering.
"""

from __future__ import annotations

import logging
import os
import re
import sys
import threading
from collections import deque
from typing import Optional

from nornicdb_tpu.telemetry import deviceprof as _deviceprof
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY

log = logging.getLogger(__name__)

# EWMA smoothing factor: ~10 samples of memory, fast enough to track a
# backend fallback (device -> host) within one scrape interval
ALPHA = 0.3
# confidence = n / (n + K): K observations to reach 0.5
CONFIDENCE_K = 8.0
# recent relative errors kept per kind (accuracy tests + snapshot)
REL_ERR_WINDOW = 256
# per-route SLO window: latency outcomes considered by the burn rate
SLO_WINDOW = 512
# half-open probe cadence: after this many consecutive predicted sheds
# of a (subsystem, kind), admit one request anyway.  A model that sheds
# everything starves itself of observations and can never unlearn an
# outlier-inflated EWMA (a 2s backend hang folded into a 60ms program
# would otherwise shed that route forever).
PROBE_EVERY = 8

# cold-start priors (seconds per dispatch) by (subsystem, kind); the
# generic prior covers unseen kinds.  Deliberately pessimistic for the
# generation path (a prefill chunk is model-forward-sized) and cheap for
# the vector paths (one fused GEMM).
PRIORS: dict[tuple[str, str], float] = {
    ("serving", "embed"): 0.02,
    ("genserve", "ragged"): 0.05,
    ("search", "dense"): 0.005,
    ("search", "ivf"): 0.005,
    ("search", "sharded"): 0.01,
    ("search", "sharded_ivf"): 0.01,
    ("search", "sharded_int8"): 0.01,
    ("cypher", "vector_topk"): 0.005,
    ("cypher", "topk_offload"): 0.005,
}
DEFAULT_PRIOR_S = 0.02

_REL_ERR_BUCKETS = (
    0.01, 0.025, 0.05, 0.1, 0.15, 0.2, 0.3, 0.5, 0.75, 1.0, 2.0, 5.0,
)

PREDICTED_SECONDS = _REGISTRY.counter(
    "nornicdb_cost_model_predicted_seconds_total",
    "Cumulative predicted device seconds by program kind (each ledger "
    "observation adds the prediction that stood before it)",
    labels=("subsystem", "kind"),
)
ACTUAL_SECONDS = _REGISTRY.counter(
    "nornicdb_cost_model_actual_seconds_total",
    "Cumulative actual device seconds by program kind (the deviceprof "
    "ledger stream the cost model learns from)",
    labels=("subsystem", "kind"),
)
OBSERVATIONS = _REGISTRY.counter(
    "nornicdb_cost_model_observations_total",
    "Ledger observations folded into the cost model by program kind",
    labels=("subsystem", "kind"),
)
REL_ERR_HIST = _REGISTRY.histogram(
    "nornicdb_cost_model_relative_error",
    "Relative error |actual - predicted| / actual of the standing "
    "prediction at each ledger observation",
    labels=("subsystem", "kind"),
    buckets=_REL_ERR_BUCKETS,
)
CONFIDENCE = _REGISTRY.gauge(
    "nornicdb_cost_model_confidence",
    "Cost-model confidence n/(n+K) by program kind (admission fails "
    "open below cost_min_confidence)",
    labels=("subsystem", "kind"),
)
ADMISSIONS = _REGISTRY.counter(
    "nornicdb_cost_model_admission_total",
    "Predictive-admission decisions by route (shed = predicted "
    "completion past the deadline at submit; fail_open = confidence "
    "below the floor, admitted unchecked)",
    labels=("route", "decision"),
)
for _route in ("embed", "search", "generate"):
    for _decision in ("admit", "shed", "fail_open"):
        ADMISSIONS.labels(_route, _decision)
SLO_BURN = _REGISTRY.gauge(
    "nornicdb_slo_burn_rate",
    "Per-route SLO burn rate: miss fraction over the sliding window "
    "divided by the error budget (1 - objective); > 1 burns budget",
    labels=("route",),
)
SLO_TARGET = _REGISTRY.gauge(
    "nornicdb_slo_target_seconds",
    "Configured per-route latency SLO target",
    labels=("route",),
)
BUILD_INFO = _REGISTRY.gauge(
    "nornicdb_build_info",
    "Build/runtime identity info-gauge (value is always 1; the labels "
    "are the payload)",
    labels=("version", "backend", "mesh_devices"),
)

_Q_RE = re.compile(r"q(\d+)")
_TRAIL_RE = re.compile(r"(\d+)$")


def shape_units(shape: str) -> Optional[int]:
    """Work units encoded in a bounded shape-class label.

    Deviceprof shape classes are pow2 buckets with a subsystem prefix
    (``b64``, ``t4096``, ``n1024``, bare ``1024``); genserve's fused
    ragged step uses ``f{rows}q{chunk}x{width}`` where the chunk token
    count (``qN``) is the work-proportional axis."""
    m = _Q_RE.search(shape)
    if m:
        return int(m.group(1))
    m = _TRAIL_RE.search(shape)
    if m:
        return int(m.group(1))
    return None


class _ClassEntry:
    """EWMA state for one exact (subsystem, kind, shape-class)."""

    __slots__ = ("ewma_s", "n")

    def __init__(self) -> None:
        self.ewma_s = 0.0
        self.n = 0

    def fold(self, seconds: float) -> None:
        if self.n == 0:
            self.ewma_s = seconds
        else:
            self.ewma_s += ALPHA * (seconds - self.ewma_s)
        self.n += 1

    @property
    def confidence(self) -> float:
        return self.n / (self.n + CONFIDENCE_K)


class _KindStats:
    """Aggregate state for one (subsystem, kind) across shape classes."""

    __slots__ = ("ewma_s", "ewma_per_unit", "n", "rel_errs")

    def __init__(self) -> None:
        self.ewma_s = 0.0  # per dispatch, any shape
        self.ewma_per_unit = 0.0  # per work unit (token/row/chunk)
        self.n = 0
        self.rel_errs: deque[float] = deque(maxlen=REL_ERR_WINDOW)

    def fold(self, seconds: float, units: Optional[int]) -> None:
        if self.n == 0:
            self.ewma_s = seconds
        else:
            self.ewma_s += ALPHA * (seconds - self.ewma_s)
        if units:
            per_unit = seconds / max(units, 1)
            if self.ewma_per_unit <= 0.0:
                self.ewma_per_unit = per_unit
            else:
                self.ewma_per_unit += ALPHA * (per_unit -
                                               self.ewma_per_unit)
        self.n += 1

    @property
    def confidence(self) -> float:
        return self.n / (self.n + CONFIDENCE_K)


class Decision:
    """One predictive-admission verdict."""

    __slots__ = ("admit", "decision", "predicted_s", "confidence",
                 "slack_s")

    def __init__(self, admit: bool, decision: str, predicted_s: float,
                 confidence: float, slack_s: float):
        self.admit = admit
        self.decision = decision  # admit | shed | fail_open
        self.predicted_s = predicted_s
        self.confidence = confidence
        self.slack_s = slack_s


class CostModel:
    """Online per-program cost model + SLO burn tracker."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._classes: dict[tuple[str, str, str], _ClassEntry] = {}
        self._kinds: dict[tuple[str, str], _KindStats] = {}
        # knobs (import-time env, then telemetry.configure overrides)
        self.conservatism = _env_float(
            "NORNICDB_TELEMETRY_COST_CONSERVATISM", 1.5)
        self.min_confidence = _env_float(
            "NORNICDB_TELEMETRY_COST_MIN_CONFIDENCE", 0.25)
        self.predictive_admission = os.environ.get(
            "NORNICDB_TELEMETRY_PREDICTIVE_ADMISSION", "1"
        ).lower() not in ("0", "false", "no")
        self.slo_objective = _env_float(
            "NORNICDB_TELEMETRY_SLO_OBJECTIVE", 0.99)
        self.slo_targets: dict[str, float] = parse_slo_targets(
            os.environ.get("NORNICDB_TELEMETRY_SLO_TARGETS",
                           "embed=250,search=250,generate=5000"))
        self._slo_windows: dict[str, deque[bool]] = {}
        self._shed_streaks: dict[tuple[str, str], int] = {}
        for route, target_s in self.slo_targets.items():
            SLO_TARGET.labels(route).set(target_s)
            SLO_BURN.labels(route)

    # -- configuration -----------------------------------------------------
    def configure(
        self,
        conservatism: Optional[float] = None,
        min_confidence: Optional[float] = None,
        predictive_admission: Optional[bool] = None,
        slo_targets=None,
        slo_objective: Optional[float] = None,
    ) -> None:
        with self._lock:
            if conservatism is not None:
                self.conservatism = max(1.0, float(conservatism))
            if min_confidence is not None:
                self.min_confidence = min(1.0, max(0.0,
                                                   float(min_confidence)))
            if predictive_admission is not None:
                self.predictive_admission = bool(predictive_admission)
            if slo_objective is not None:
                self.slo_objective = min(0.9999,
                                         max(0.5, float(slo_objective)))
            if slo_targets is not None:
                if isinstance(slo_targets, str):
                    slo_targets = parse_slo_targets(slo_targets)
                self.slo_targets = dict(slo_targets)
                for route, target_s in self.slo_targets.items():
                    SLO_TARGET.labels(route).set(target_s)
                    SLO_BURN.labels(route)

    # -- learning ----------------------------------------------------------
    def observe(self, subsystem: str, kind: str, shape: str,
                seconds: float) -> None:
        """Deviceprof time-observer entry point: score the standing
        prediction against the actual, then fold the sample in."""
        shape = str(shape)
        units = shape_units(shape)
        key = (subsystem, kind, shape)
        with self._lock:
            entry = self._classes.get(key)
            if entry is None:
                entry = self._classes[key] = _ClassEntry()
            ks = self._kinds.get((subsystem, kind))
            if ks is None:
                ks = self._kinds[(subsystem, kind)] = _KindStats()
            predicted, had_history = self._predict_locked(
                subsystem, kind, units, entry, ks)
            if had_history and seconds > 0:
                rel = abs(seconds - predicted) / seconds
                ks.rel_errs.append(rel)
            entry.fold(seconds)
            ks.fold(seconds, units)
        if had_history and seconds > 0:
            REL_ERR_HIST.labels(subsystem, kind).observe(rel)
            PREDICTED_SECONDS.labels(subsystem, kind).inc(predicted)
            ACTUAL_SECONDS.labels(subsystem, kind).inc(seconds)
        OBSERVATIONS.labels(subsystem, kind).inc()

    def _predict_locked(self, subsystem: str, kind: str,
                        units: Optional[int],
                        entry: Optional[_ClassEntry],
                        ks: Optional[_KindStats]) -> tuple[float, bool]:
        """-> (predicted seconds for ONE dispatch, had_history)."""
        if entry is not None and entry.n > 0:
            return entry.ewma_s, True
        if ks is not None and ks.n > 0:
            if units and ks.ewma_per_unit > 0.0:
                return ks.ewma_per_unit * units, True
            return ks.ewma_s, True
        return PRIORS.get((subsystem, kind), DEFAULT_PRIOR_S), False

    # -- prediction --------------------------------------------------------
    def predict(self, subsystem: str, kind: str,
                units: Optional[int] = None,
                shape: Optional[str] = None) -> tuple[float, float]:
        """Predicted seconds for one dispatch + confidence in [0, 1)."""
        with self._lock:
            entry = self._classes.get(
                (subsystem, kind, str(shape))) if shape else None
            ks = self._kinds.get((subsystem, kind))
            predicted, _ = self._predict_locked(subsystem, kind, units,
                                                entry, ks)
            if entry is not None and entry.n > 0:
                conf = entry.confidence
            elif ks is not None and ks.n > 0:
                conf = ks.confidence
            else:
                conf = 0.0
        return predicted, conf

    def per_unit(self, subsystem: str, kind: str) -> float:
        """Learned seconds per work unit (0.0 while cold)."""
        with self._lock:
            ks = self._kinds.get((subsystem, kind))
            return ks.ewma_per_unit if ks is not None else 0.0

    def median_rel_error(self, subsystem: str,
                         kind: str) -> Optional[float]:
        """Median of the recent relative errors for a kind (None while
        the model has no scored history) — the accuracy contract the
        tests assert."""
        with self._lock:
            ks = self._kinds.get((subsystem, kind))
            if ks is None or not ks.rel_errs:
                return None
            errs = sorted(ks.rel_errs)
        mid = len(errs) // 2
        if len(errs) % 2:
            return errs[mid]
        return 0.5 * (errs[mid - 1] + errs[mid])

    # -- predictive admission ----------------------------------------------
    def decide(self, route: str, subsystem: str, kind: str,
               units: Optional[int], slack_s: float,
               units_ahead: float = 0.0,
               dispatches_ahead: float = 0.0) -> Decision:
        """Shed-at-submit verdict for one request.

        ``slack_s`` is the remaining deadline budget (<= 0 means no
        deadline: always admit).  ``units_ahead`` / ``dispatches_ahead``
        describe the backlog already queued in front of this request —
        the queue-aware term that makes overload shed *early* instead of
        after the queue has already burned the deadline.

        Decisions: ``admit`` / ``shed`` / ``fail_open`` (confidence too
        low to act on) / ``probe`` (half-open admission after
        ``PROBE_EVERY`` consecutive sheds, keeping observations flowing
        so an inflated EWMA can recover)."""
        if slack_s <= 0 or not self.predictive_admission:
            return Decision(True, "admit", 0.0, 0.0, slack_s)
        predicted_own, conf = self.predict(subsystem, kind, units)
        with self._lock:
            ks = self._kinds.get((subsystem, kind))
            per_unit = ks.ewma_per_unit if ks is not None else 0.0
            per_dispatch = ks.ewma_s if ks is not None else 0.0
            conservatism = self.conservatism
            min_conf = self.min_confidence
        predicted_wait = (per_unit * max(units_ahead, 0.0)
                          + per_dispatch * max(dispatches_ahead, 0.0))
        predicted = predicted_own + predicted_wait
        if conf < min_conf:
            ADMISSIONS.labels(route, "fail_open").inc()
            return Decision(True, "fail_open", predicted, conf, slack_s)
        if predicted * conservatism > slack_s:
            # half-open probe (see PROBE_EVERY): every Nth consecutive
            # would-shed is admitted so the route keeps producing
            # observations and an inflated EWMA can decay back down
            with self._lock:
                streak = self._shed_streaks.get((subsystem, kind), 0) + 1
                if streak >= PROBE_EVERY:
                    self._shed_streaks[(subsystem, kind)] = 0
                else:
                    self._shed_streaks[(subsystem, kind)] = streak
            if streak >= PROBE_EVERY:
                ADMISSIONS.labels(route, "probe").inc()
                return Decision(True, "probe", predicted, conf, slack_s)
            ADMISSIONS.labels(route, "shed").inc()
            return Decision(False, "shed", predicted, conf, slack_s)
        with self._lock:
            self._shed_streaks.pop((subsystem, kind), None)
        ADMISSIONS.labels(route, "admit").inc()
        return Decision(True, "admit", predicted, conf, slack_s)

    # -- SLO burn ----------------------------------------------------------
    def record_latency(self, route: str, seconds: float) -> None:
        """Feed one completed request's end-to-end latency into the
        route's SLO window (routes without a configured target are
        ignored — no unbounded label growth)."""
        with self._lock:
            target = self.slo_targets.get(route)
            if target is None:
                return
            window = self._slo_windows.get(route)
            if window is None:
                window = self._slo_windows[route] = deque(
                    maxlen=SLO_WINDOW)
            window.append(seconds > target)

    def refresh_gauges(self) -> None:
        """Collect-hook: derive the confidence + SLO burn gauges at
        scrape time (cheap: a few dict walks, no allocation-heavy
        work)."""
        with self._lock:
            kinds = list(self._kinds.items())
            budget = max(1e-6, 1.0 - self.slo_objective)
            windows = {r: (sum(w), len(w))
                       for r, w in self._slo_windows.items()}
            targets = dict(self.slo_targets)
        for (subsystem, kind), ks in kinds:
            CONFIDENCE.labels(subsystem, kind).set(ks.confidence)
        for route in targets:
            misses, n = windows.get(route, (0, 0))
            burn = (misses / n) / budget if n else 0.0
            SLO_BURN.labels(route).set(burn)

    # -- capacity ----------------------------------------------------------
    def capacity_snapshot(self) -> dict:
        """The /admin/capacity payload: cost table + headroom."""
        with self._lock:
            programs = [
                {
                    "subsystem": k[0], "kind": k[1], "shape": k[2],
                    "ewma_seconds": round(e.ewma_s, 9),
                    "observations": e.n,
                    "confidence": round(e.confidence, 4),
                }
                for k, e in sorted(self._classes.items())
            ]
            headroom = {}
            for (subsystem, kind), ks in sorted(self._kinds.items()):
                qps = 1.0 / ks.ewma_s if ks.ewma_s > 0 else None
                headroom[f"{subsystem}.{kind}"] = {
                    "ewma_seconds_per_dispatch": round(ks.ewma_s, 9),
                    "seconds_per_unit": round(ks.ewma_per_unit, 12),
                    "max_sustainable_qps":
                        round(qps, 3) if qps is not None else None,
                    "confidence": round(ks.confidence, 4),
                    "observations": ks.n,
                }
            slo = {
                "objective": self.slo_objective,
                "targets_s": dict(self.slo_targets),
                "windows": {
                    r: {"samples": len(w), "misses": sum(w)}
                    for r, w in sorted(self._slo_windows.items())
                },
            }
            knobs = {
                "conservatism": self.conservatism,
                "min_confidence": self.min_confidence,
                "predictive_admission": self.predictive_admission,
            }
        for entry in programs:
            med = self.median_rel_error(entry["subsystem"],
                                        entry["kind"])
            entry["median_rel_error"] = (round(med, 4)
                                         if med is not None else None)
        return {
            "programs": programs,
            "headroom": headroom,
            "slo": slo,
            "admission": knobs,
        }

    def reset(self) -> None:
        """Test helper: drop all learned state (metrics cells persist —
        counters are monotonic by contract)."""
        with self._lock:
            self._classes.clear()
            self._kinds.clear()
            self._slo_windows.clear()
            self._shed_streaks.clear()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def parse_slo_targets(spec) -> dict[str, float]:
    """``"embed=250,search=250"`` (ms) -> ``{"embed": 0.25, ...}``.
    Dicts pass through with values interpreted as SECONDS."""
    if isinstance(spec, dict):
        return {str(k): float(v) for k, v in spec.items()}
    out: dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        route, _, raw = part.partition("=")
        try:
            out[route.strip()] = float(raw) / 1000.0
        except ValueError:
            continue
    return out


# -- build info --------------------------------------------------------------
_build_state = {"cell": None, "backend": None}
_build_lock = threading.Lock()


def _refresh_build_info() -> None:
    """Resolve the build-identity labels lazily at scrape time.  jax is
    never imported here — until something else loads it, the backend
    label reads ``unloaded``; once it appears in sys.modules the cell is
    re-resolved (the stale cell drops to 0, info-gauge semantics)."""
    backend, devices = "unloaded", 0
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        try:
            backend = str(jax_mod.default_backend())
            devices = int(jax_mod.device_count())
        except Exception:
            log.debug("jax backend identity probe failed", exc_info=True)
            backend, devices = "error", 0
    import nornicdb_tpu

    version = getattr(nornicdb_tpu, "__version__", "dev")
    with _build_lock:  # concurrent scrapes race the cell swap
        if _build_state["backend"] == backend and _build_state["cell"]:
            return
        old = _build_state["cell"]
        if old is not None:
            old.set(0.0)
        cell = BUILD_INFO.labels(version, backend, devices)
        cell.set(1.0)
        _build_state["cell"] = cell
        _build_state["backend"] = backend


#: process-global cost model, learning from the deviceprof ledger
COST_MODEL = CostModel()
_deviceprof.PROFILER.add_time_observer(COST_MODEL.observe)
_REGISTRY.collect_hook("costmodel", COST_MODEL.refresh_gauges)
_REGISTRY.collect_hook("build_info", _refresh_build_info)

observe = COST_MODEL.observe
predict = COST_MODEL.predict
decide = COST_MODEL.decide
record_latency = COST_MODEL.record_latency
capacity_snapshot = COST_MODEL.capacity_snapshot
