"""Device-time & HBM profiler: one per-process view of device cost.

Before this module the device-side cost surface was scattered: genserve
kept a compiled-program ledger on its engine, the corpora counted
``device_dispatches`` in SyncStats, and the columnar offload had its own
used/unavailable counters — none comparable, none with time attached,
and HBM residency (the number every capacity decision in ROADMAP items
1/3 hinges on) had no surface at all.  This module unifies them:

- **Program registry** keyed ``(subsystem, kind, shape)``: every device
  dispatch records its execute time; the first execute of a new key also
  counts as a compile (the ledger semantics genserve already proved —
  jitted programs compile once per static shape per process), and
  warmup paths may pre-register keys with :func:`record_compile`.
  Exposed as ``nornicdb_device_programs_total`` (distinct-program
  compile counter) and ``nornicdb_device_program_seconds`` (execute-time
  histogram), both labeled ``(subsystem, kind, shape)`` — callers are
  responsible for bounded shape classes (everything device-side is
  already pow2-bucketed).
- **HBM residency** ``nornicdb_hbm_bytes{component}``: components
  (corpus f32 buffers, int8 codes+scales, IVF block arrays, the genserve
  KV page pool, embedder params) register weakref'd byte providers at
  construction; a registry collect-hook sums the live providers per
  component at scrape time, so the gauge is always current with zero
  hot-path cost.  Providers run on the scrape thread: they must read
  buffer refs lock-free (stats-grade accuracy, never a lock).
- **On-demand profile capture** (:func:`capture_profile`): single-flight
  ``jax.profiler`` trace over N seconds, tarred into a downloadable
  artifact — the ``POST /admin/profile?seconds=N`` endpoint
  (auth-gated, server/http.py) serves it.

Import-light: jax loads only inside :func:`capture_profile`.
"""

from __future__ import annotations

import io
import logging
import os
import shutil
import tarfile
import tempfile
import threading
import time
import weakref
from typing import Callable, Optional

from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY

log = logging.getLogger(__name__)

# components rendered eagerly so the tested docs/observability.md catalog
# exposes the family (at 0) before any device buffer exists
HBM_COMPONENTS = (
    "corpus_f32", "corpus_int8", "ivf", "kv_pages", "kv_prefix",
    "embedder_params",
)

_HBM = _REGISTRY.gauge(
    "nornicdb_hbm_bytes",
    "Device-resident bytes by component (corpus f32 buffers, int8 "
    "codes+scales, IVF block arrays, genserve KV page pool, the pool "
    "slice held by the shared-prefix cache, embedder params)",
    labels=("component",),
)
_HBM_CELLS = {c: _HBM.labels(c) for c in HBM_COMPONENTS}

_PROGRAMS = _REGISTRY.counter(
    "nornicdb_device_programs_total",
    "Distinct compiled device programs by (subsystem, kind, shape) — "
    "ledger semantics: one count per static shape class per process",
    labels=("subsystem", "kind", "shape"),
)
_EXEC_HIST = _REGISTRY.histogram(
    "nornicdb_device_program_seconds",
    "Device program execute time by (subsystem, kind, shape)",
    labels=("subsystem", "kind", "shape"),
)
_PROFILE_CAPTURES = _REGISTRY.counter(
    "nornicdb_profile_captures_total",
    "On-demand jax.profiler captures by outcome",
    labels=("outcome",),
)
for _out in ("ok", "busy", "error"):
    _PROFILE_CAPTURES.labels(_out)


class ProfileBusy(RuntimeError):
    """A capture is already in flight (the endpoint is single-flight:
    two overlapping jax.profiler traces abort the runtime)."""


class _ProgramEntry:
    __slots__ = ("compiles", "executes", "total_s")

    def __init__(self) -> None:
        self.compiles = 0
        self.executes = 0
        self.total_s = 0.0


class DeviceProfiler:
    """Per-process program registry + HBM provider set."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._programs: dict[tuple[str, str, str], _ProgramEntry] = {}
        # id(owner) -> (weakref(owner), fn(owner) -> {component: bytes})
        self._hbm_providers: dict[int, tuple] = {}
        self._capture_lock = threading.Lock()
        self.captures = 0
        # observers see every record_compile/record_execute key on the
        # RECORDING thread, before/around the dispatch it annotates —
        # nornjit's compile sentinel attributes fresh XLA compiles to
        # the last key announced on the compiling thread
        self._observers: list[Callable[[str, str, str], None]] = []
        # time observers additionally receive the execute duration —
        # the per-program cost model (telemetry/costmodel.py) learns its
        # EWMAs from these without touching the key-only observer
        # contract nornjit's compile sentinel depends on
        self._time_observers: list[
            Callable[[str, str, str, float], None]
        ] = []

    def add_observer(self, fn: Callable[[str, str, str], None]) -> None:
        """Register ``fn(subsystem, kind, shape)`` called synchronously
        on every ledger record.  Observers must be cheap and must not
        raise (failures are swallowed at notify time)."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def remove_observer(self, fn: Callable[[str, str, str], None]) -> None:
        with self._lock:
            try:
                self._observers.remove(fn)
            except ValueError:
                pass

    def add_time_observer(
        self, fn: Callable[[str, str, str, float], None],
    ) -> None:
        """Register ``fn(subsystem, kind, shape, seconds)`` called on
        every :meth:`record_execute`.  Same contract as observers:
        cheap, never raises (failures swallowed at notify time)."""
        with self._lock:
            if fn not in self._time_observers:
                self._time_observers.append(fn)

    def remove_time_observer(
        self, fn: Callable[[str, str, str, float], None],
    ) -> None:
        with self._lock:
            try:
                self._time_observers.remove(fn)
            except ValueError:
                pass

    def _notify(self, key: tuple[str, str, str]) -> None:
        for fn in list(self._observers):
            try:
                fn(*key)
            except Exception:
                log.debug("deviceprof observer failed", exc_info=True)

    def _notify_time(self, key: tuple[str, str, str],
                     seconds: float) -> None:
        for fn in list(self._time_observers):
            try:
                fn(key[0], key[1], key[2], seconds)
            except Exception:
                log.debug("deviceprof time observer failed", exc_info=True)

    # -- program ledger ----------------------------------------------------
    def record_compile(self, subsystem: str, kind: str, shape) -> None:
        """Register a program key without an execute (warmup paths).
        Idempotent per key — ledger semantics, not a recompile count."""
        key = (subsystem, kind, str(shape))
        with self._lock:
            entry = self._programs.get(key)
            if entry is None:
                entry = self._programs[key] = _ProgramEntry()
            if entry.compiles == 0:
                entry.compiles = 1
                _PROGRAMS.labels(*key).inc()
        self._notify(key)

    def record_execute(self, subsystem: str, kind: str, shape,
                       seconds: float) -> None:
        """One device dispatch: execute-time histogram + first-seen
        compile count."""
        key = (subsystem, kind, str(shape))
        with self._lock:
            entry = self._programs.get(key)
            if entry is None:
                entry = self._programs[key] = _ProgramEntry()
            if entry.compiles == 0:
                entry.compiles = 1
                _PROGRAMS.labels(*key).inc()
            entry.executes += 1
            entry.total_s += seconds
        _EXEC_HIST.labels(*key).observe(seconds)
        self._notify(key)
        self._notify_time(key, seconds)

    # -- HBM residency -----------------------------------------------------
    def register_hbm(self, owner, fn: Callable[[object], dict]) -> None:
        """Register a residency provider: ``fn(owner) -> {component:
        bytes}``.  ``owner`` is held by weakref — a GC'd corpus/engine
        disappears from the sum without unregistration ceremony.  ``fn``
        must be lock-free (scrape-thread contract)."""
        ref = weakref.ref(owner)
        with self._lock:
            self._hbm_providers[id(owner)] = (ref, fn)

    def refresh_hbm(self) -> None:
        """Collect-hook: sum live providers per component into the gauge
        (runs at the start of every /metrics render)."""
        totals = {c: 0.0 for c in HBM_COMPONENTS}
        with self._lock:
            providers = list(self._hbm_providers.items())
        dead = []
        for key, (ref, fn) in providers:
            owner = ref()
            if owner is None:
                dead.append(key)
                continue
            try:
                contrib = fn(owner)
            except Exception:
                log.debug("hbm provider failed", exc_info=True)
                continue
            for comp, nbytes in (contrib or {}).items():
                totals[comp] = totals.get(comp, 0.0) + float(nbytes or 0)
        if dead:
            with self._lock:
                for key in dead:
                    self._hbm_providers.pop(key, None)
        for comp, total in totals.items():
            cell = _HBM_CELLS.get(comp)
            if cell is None:
                cell = _HBM_CELLS[comp] = _HBM.labels(comp)
            cell.set(total)

    # -- snapshots ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured view for /admin/stats → ``deviceprof``."""
        self.refresh_hbm()
        with self._lock:
            programs = [
                {
                    "subsystem": k[0], "kind": k[1], "shape": k[2],
                    "compiles": e.compiles, "executes": e.executes,
                    "total_s": round(e.total_s, 6),
                }
                for k, e in sorted(self._programs.items())
            ]
        return {
            "programs": programs,
            "program_count": len(programs),
            "hbm_bytes": {c: cell.get()
                          for c, cell in sorted(_HBM_CELLS.items())},
            "captures": self.captures,
        }

    # -- profile capture ---------------------------------------------------
    def capture_profile(self, seconds: float,
                        max_seconds: float = 60.0) -> bytes:
        """Single-flight jax.profiler capture: trace for ``seconds``
        (clamped to [0.05, max_seconds]), return the capture directory
        as a gzipped tar.  Raises :class:`ProfileBusy` when a capture is
        already running; any jax/profiler failure propagates (the
        endpoint maps it to 503)."""
        seconds = max(0.05, min(float(seconds), float(max_seconds)))
        # non-blocking try-acquire: the single-flight gate — on success
        # the very next statement is the try whose finally releases
        if not self._capture_lock.acquire(  # nornlint: disable=NL-CC01
                blocking=False):
            _PROFILE_CAPTURES.labels("busy").inc()
            raise ProfileBusy("a profile capture is already in flight")
        tmpdir = None
        try:
            tmpdir = tempfile.mkdtemp(prefix="nornic-profile-")
            import jax
            import jax.numpy as jnp

            jax.profiler.start_trace(tmpdir)
            try:
                # a token device op so even an idle process produces a
                # non-empty trace (the capture's value is the LIVE
                # traffic recorded during the window, this just
                # guarantees the artifact is never empty)
                x = jnp.ones((128, 128), jnp.float32)
                (x @ x).block_until_ready()
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            buf = io.BytesIO()
            with tarfile.open(fileobj=buf, mode="w:gz") as tar:
                for dirpath, _dirnames, filenames in os.walk(tmpdir):
                    for fname in filenames:
                        full = os.path.join(dirpath, fname)
                        tar.add(full,
                                arcname=os.path.relpath(full, tmpdir))
            self.captures += 1
            _PROFILE_CAPTURES.labels("ok").inc()
            return buf.getvalue()
        except ProfileBusy:
            raise
        except Exception:
            _PROFILE_CAPTURES.labels("error").inc()
            raise
        finally:
            if tmpdir is not None:
                shutil.rmtree(tmpdir, ignore_errors=True)
            self._capture_lock.release()


#: process-global profiler — instrumentation sites resolve it at import.
#: Only the singleton drives the registry's pre-render refresh: a
#: privately-constructed profiler (tests) must not hijack the hook and
#: zero the shared gauges with its own empty provider set.
PROFILER = DeviceProfiler()
_REGISTRY.collect_hook("deviceprof_hbm", PROFILER.refresh_hbm)

add_time_observer = PROFILER.add_time_observer
record_compile = PROFILER.record_compile
record_execute = PROFILER.record_execute
register_hbm = PROFILER.register_hbm
capture_profile = PROFILER.capture_profile
snapshot = PROFILER.snapshot


def pow2_class(n: int, prefix: str = "") -> str:
    """Bounded shape-class label: n rounded up to a power of two."""
    n = max(1, int(n))
    return f"{prefix}{1 << (n - 1).bit_length()}"
