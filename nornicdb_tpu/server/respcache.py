"""Shared serialized-response cache for protocol servers.

One policy, used by both the HTTP search endpoint and the native gRPC
search service (ref: pkg/cache LRU+TTL query cache): entries are dead
the moment the search index generation moves, and expire after a short
TTL so decay/access-count drift stays bounded. The generation must be
snapshotted BEFORE running the search — a mutation racing the search
must make the entry dead on arrival (same rule as the rank cache,
search/service.py gen_before).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Hashable, Optional

log = logging.getLogger(__name__)


class ResponseCache:
    def __init__(self, generation_fn: Callable[[], int],
                 ttl: float = 1.0, max_entries: int = 512):
        self._generation_fn = generation_fn
        self.ttl = ttl
        self.max_entries = max_entries
        self._entries: dict[Hashable, tuple[bytes, int, float]] = {}

    def generation(self) -> int:
        try:
            return self._generation_fn()
        except Exception:
            # sentinel: both get() and put() treat -1 as "cache unusable"
            # (fail open = serve uncached) — a -1 must never match a -1, or
            # a persistently-broken probe would serve stale hits forever
            log.warning("generation probe failed; cache disabled this request",
                        exc_info=True)
            return -1

    def get(self, key: Hashable) -> Optional[bytes]:
        entry = self._entries.get(key)
        if entry is None:
            return None
        payload, gen, expires = entry
        current = self.generation()
        if current == -1 or gen != current or time.time() > expires:
            self._entries.pop(key, None)
            return None
        return payload

    def put(self, key: Hashable, payload: bytes, generation: int) -> None:
        """`generation` must be the value snapshotted before the search
        ran; an entry built from pre-mutation data then mismatches the
        bumped counter and dies on first lookup."""
        if generation == -1:
            return  # probe failed before the search: staleness unknowable
        if len(self._entries) >= self.max_entries:
            self._entries.clear()  # cheap wholesale eviction
        self._entries[key] = (payload, generation, time.time() + self.ttl)
