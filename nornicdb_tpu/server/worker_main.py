"""Worker subprocess entry point (kept separate from workers.py so
`python -m nornicdb_tpu.server.worker_main` doesn't re-execute a module the
server package already imported — runpy warns about that double life)."""

import sys

from nornicdb_tpu.server.workers import _subproc_entry

if __name__ == "__main__":
    _subproc_entry(sys.argv[1:])
