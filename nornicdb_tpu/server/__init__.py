"""Protocol servers (ref: /root/reference/pkg/bolt, pkg/server, pkg/mcp)."""

from nornicdb_tpu.server.bolt import BoltServer
from nornicdb_tpu.server.broker import BrokerClient, DeviceBroker
from nornicdb_tpu.server.http import HttpServer
from nornicdb_tpu.server.packstream import Structure, pack, to_wire, unpack
from nornicdb_tpu.server.readplane import (
    ReadPlanePublisher,
    SharedAdjacencyReader,
    SharedCorpusReader,
)
from nornicdb_tpu.server.workers import WorkerPool

__all__ = [
    "BoltServer", "BrokerClient", "DeviceBroker", "HttpServer",
    "ReadPlanePublisher", "SharedAdjacencyReader", "SharedCorpusReader",
    "Structure", "WorkerPool", "pack", "to_wire", "unpack",
]
