"""Protocol servers (ref: /root/reference/pkg/bolt, pkg/server, pkg/mcp)."""

from nornicdb_tpu.server.bolt import BoltServer
from nornicdb_tpu.server.http import HttpServer
from nornicdb_tpu.server.packstream import Structure, pack, to_wire, unpack
from nornicdb_tpu.server.workers import WorkerPool

__all__ = [
    "BoltServer", "HttpServer", "Structure", "pack", "to_wire", "unpack",
    "WorkerPool",
]
