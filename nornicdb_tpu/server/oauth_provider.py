"""Standalone OAuth 2.0 test provider for integration testing.

Behavioral reference: /root/reference/cmd/oauth-provider (650 LoC Go
binary) — a minimal RFC 6749 authorization-code provider with a consent
form, token exchange, userinfo, discovery metadata, and three
pre-configured test users, used to exercise NornicDB's OAuth integration
locally with zero external dependencies. Run via
`nornicdb oauth-provider [--port N]` or embed OAuthTestProvider in tests.

Endpoints (same paths as the reference):
  GET  /oauth2/v1/authorize          consent form (response_type=code)
  POST /oauth2/v1/authorize/consent  user picks a test identity -> 302 code
  POST /oauth2/v1/token              authorization_code -> access token
  GET  /oauth2/v1/userinfo           Bearer token -> profile JSON
  GET  /.well-known/oauth-authorization-server  discovery metadata
  GET  /health                       {status, users}
"""

from __future__ import annotations

import json
import secrets
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlencode, urlparse

CODE_TTL_S = 120.0
TOKEN_TTL_S = 3600.0


@dataclass
class TestUser:
    sub: str
    email: str
    preferred_username: str
    roles: list[str]
    password: str


# the reference's three pre-configured identities (cmd/oauth-provider README)
DEFAULT_USERS = [
    TestUser("user-001", "admin@localhost", "admin",
             ["admin", "developer"], "admin123"),
    TestUser("user-002", "developer@localhost", "developer",
             ["developer"], "dev123"),
    TestUser("user-003", "viewer@localhost", "viewer",
             ["viewer"], "view123"),
]


@dataclass
class _Grant:
    user: TestUser
    redirect_uri: str
    expires: float
    scope: str = ""


_CONSENT_HTML = """<!DOCTYPE html>
<html><head><title>OAuth Test Provider</title>
<style>body{{font:14px sans-serif;max-width:420px;margin:60px auto}}
button{{display:block;width:100%;margin:6px 0;padding:10px}}</style></head>
<body><h2>Sign in as a test user</h2>
<p>client: <code>{client_id}</code> &rarr; <code>{redirect_uri}</code></p>
<form method="POST" action="/oauth2/v1/authorize/consent">
<input type="hidden" name="rid" value="{rid}">
{buttons}
</form></body></html>
"""


class OAuthTestProvider:
    """In-memory OAuth 2.0 provider (threaded HTTP server)."""

    def __init__(self, port: int = 0, client_id: str = "nornicdb-local-test",
                 client_secret: str = "local-test-secret-123",
                 issuer: Optional[str] = None,
                 users: Optional[list[TestUser]] = None):
        self.client_id = client_id
        self.client_secret = client_secret
        self.users = list(users) if users is not None else list(DEFAULT_USERS)
        self._codes: dict[str, _Grant] = {}
        self._tokens: dict[str, _Grant] = {}
        # authorize requests awaiting consent, keyed by one-time request id:
        # the consent POST carries only the rid, so redirect_uri/state/scope
        # are bound server-side to the validated /authorize request and a
        # direct POST cannot mint a code for an arbitrary redirect_uri
        self._pending: dict[str, dict] = {}
        self._lock = threading.Lock()
        provider = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, status: int, body, content_type="application/json",
                      headers=()):
                data = (json.dumps(body).encode()
                        if not isinstance(body, (bytes, str))
                        else body.encode() if isinstance(body, str) else body)
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                u = urlparse(self.path)
                if u.path == "/health":
                    self._send(200, {"status": "ok",
                                     "users": len(provider.users)})
                elif u.path == "/.well-known/oauth-authorization-server":
                    self._send(200, provider.discovery())
                elif u.path == "/oauth2/v1/authorize":
                    provider._handle_authorize(self, parse_qs(u.query))
                elif u.path == "/oauth2/v1/userinfo":
                    provider._handle_userinfo(self)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length).decode() if length else ""
                form = {k: v[0] for k, v in parse_qs(body).items()}
                u = urlparse(self.path)
                if u.path == "/oauth2/v1/authorize/consent":
                    provider._handle_consent(self, form)
                elif u.path == "/oauth2/v1/token":
                    provider._handle_token(self, form)
                else:
                    self._send(404, {"error": "not found"})

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_port
        self.issuer = issuer or f"http://127.0.0.1:{self.port}"
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "OAuthTestProvider":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="oauth-test-provider")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- metadata ----------------------------------------------------------
    def discovery(self) -> dict:
        return {
            "issuer": self.issuer,
            "authorization_endpoint": f"{self.issuer}/oauth2/v1/authorize",
            "token_endpoint": f"{self.issuer}/oauth2/v1/token",
            "userinfo_endpoint": f"{self.issuer}/oauth2/v1/userinfo",
            "response_types_supported": ["code"],
            "grant_types_supported": ["authorization_code"],
            "token_endpoint_auth_methods_supported": [
                "client_secret_post", "client_secret_basic"],
        }

    # -- flows -------------------------------------------------------------
    def _handle_authorize(self, h, q: dict) -> None:
        if (q.get("response_type") or [""])[0] != "code":
            h._send(400, {"error": "unsupported_response_type"})
            return
        if (q.get("client_id") or [""])[0] != self.client_id:
            h._send(400, {"error": "invalid_client"})
            return
        redirect_uri = (q.get("redirect_uri") or [""])[0]
        if not redirect_uri:
            h._send(400, {"error": "invalid_request",
                          "error_description": "redirect_uri required"})
            return
        import html as _html

        esc = lambda s: _html.escape(str(s), quote=True)  # noqa: E731
        buttons = "".join(
            f'<button name="username" value="{esc(u.preferred_username)}">'
            f"{esc(u.preferred_username)} — {esc(u.email)} "
            f"({esc(', '.join(u.roles))})</button>"
            for u in self.users
        )
        rid = secrets.token_urlsafe(16)
        with self._lock:
            # sweep expired entries so abandoned authorize requests can't
            # grow the dict without bound in a long-lived process
            now = time.time()
            for stale in [r for r, p in self._pending.items()
                          if p["expires"] < now]:
                del self._pending[stale]
            self._pending[rid] = {
                "redirect_uri": redirect_uri,
                "state": (q.get("state") or [""])[0],
                "scope": (q.get("scope") or [""])[0],
                "expires": time.time() + CODE_TTL_S,
            }
        # query-derived values reflected into the page are escaped; the
        # consent form itself carries only the opaque one-time rid
        h._send(200, _CONSENT_HTML.format(
            client_id=esc(self.client_id),
            redirect_uri=esc(redirect_uri),
            rid=esc(rid),
            buttons=buttons,
        ), content_type="text/html; charset=utf-8")

    def _handle_consent(self, h, form: dict) -> None:
        user = next(
            (u for u in self.users
             if u.preferred_username == form.get("username")),
            None,
        )
        with self._lock:
            pending = self._pending.pop(form.get("rid", ""), None)
        if user is None or pending is None or pending["expires"] < time.time():
            h._send(400, {"error": "invalid_request"})
            return
        redirect_uri = pending["redirect_uri"]
        code = secrets.token_urlsafe(24)
        with self._lock:
            self._codes[code] = _Grant(
                user, redirect_uri, time.time() + CODE_TTL_S,
                pending["scope"])
        # urlencode: state may contain '&', '#', spaces, or CR/LF — raw
        # interpolation would corrupt the redirect or inject headers
        params = {"code": code}
        if pending["state"]:
            params["state"] = pending["state"]
        sep = "&" if "?" in redirect_uri else "?"
        target = f"{redirect_uri}{sep}{urlencode(params)}"
        h._send(302, b"", headers=[("Location", target)])

    def _client_ok(self, h, form: dict) -> bool:
        cid = form.get("client_id")
        secret = form.get("client_secret")
        if cid is None:
            auth = h.headers.get("Authorization", "")
            if auth.startswith("Basic "):
                import base64

                try:
                    cid, _, secret = base64.b64decode(
                        auth[6:]).decode().partition(":")
                except (ValueError, UnicodeDecodeError):
                    return False  # malformed base64: not authenticated
        return cid == self.client_id and secret == self.client_secret

    def _handle_token(self, h, form: dict) -> None:
        if form.get("grant_type") != "authorization_code":
            h._send(400, {"error": "unsupported_grant_type"})
            return
        if not self._client_ok(h, form):
            h._send(401, {"error": "invalid_client"})
            return
        with self._lock:
            grant = self._codes.pop(form.get("code", ""), None)
        if grant is None or grant.expires < time.time():
            h._send(400, {"error": "invalid_grant"})
            return
        if form.get("redirect_uri") and form["redirect_uri"] != grant.redirect_uri:
            h._send(400, {"error": "invalid_grant",
                          "error_description": "redirect_uri mismatch"})
            return
        token = secrets.token_urlsafe(32)
        with self._lock:
            self._tokens[token] = _Grant(
                grant.user, grant.redirect_uri,
                time.time() + TOKEN_TTL_S, grant.scope)
        h._send(200, {
            "access_token": token,
            "token_type": "Bearer",
            "expires_in": int(TOKEN_TTL_S),
            "scope": grant.scope,
        })

    def _handle_userinfo(self, h) -> None:
        auth = h.headers.get("Authorization", "")
        token = auth[7:] if auth.startswith("Bearer ") else ""
        with self._lock:
            grant = self._tokens.get(token)
        if grant is None or grant.expires < time.time():
            h._send(401, {"error": "invalid_token"})
            return
        u = grant.user
        h._send(200, {
            "sub": u.sub,
            "email": u.email,
            "preferred_username": u.preferred_username,
            "roles": u.roles,
        })


def main(port: int = 8888, client_id: str = "nornicdb-local-test",
         client_secret: str = "local-test-secret-123") -> int:
    provider = OAuthTestProvider(port=port, client_id=client_id,
                                 client_secret=client_secret)
    provider.start()
    print(f"oauth test provider listening on {provider.issuer}")
    print(f"  client_id={client_id}")
    print(f"  users: " + ", ".join(
        u.preferred_username for u in provider.users))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        provider.stop()
    return 0
