"""Cross-process device broker: one PJRT owner serving every worker.

The chip has one owner — the primary process. Prefork protocol workers
(server/workers.py) are plain subprocesses with no JAX; before this module
their only route to device compute was proxying whole HTTP requests back to
the primary's protocol stack, so worker scaling only scaled cache hits.
The broker is the missing hot path: workers submit **search / embed batch
requests** over a Unix-domain socket with compact length-prefixed binary
framing (f32/int8 query blocks in, top-k ids/scores out — no pickle, no
HTTP, no JSON), and the broker drains every connection's requests into the
primary's existing fused-dispatch machinery:

* search tickets go through ``SearchService.ensure_batcher()`` —
  cross-worker queries coalesce with each other (and with the primary's
  own traffic) into ONE device program per batch window, the WindVE
  many-ingest-one-device shape (PAPERS.md);
* embed requests ride ``Embedder.embed_batch`` — behind ``cli serve`` that
  is the continuous ragged batching ServingEngine with its admission
  control.

The PR 8 taxonomy applies end-to-end: a shed (queue full / deadline) comes
back as a ``RESOURCE_EXHAUSTED`` status frame and the worker surfaces
HTTP 429 / gRPC RESOURCE_EXHAUSTED; a degraded backend comes back as a
``DEGRADED`` status frame and the worker serves its local host-search
fallback from the shared-memory read plane (server/readplane.py) instead
of hammering a device that is not there.

Wire protocol (all little-endian)
---------------------------------
Frame: ``u32 length | u8 msg_type | u64 request_id | u8 tp_len |
traceparent | payload`` where ``length`` covers everything after itself
and ``tp_len`` (0 = untraced) carries an optional W3C traceparent — the
trace-context hop that makes a worker's request and the primary's fused
dispatch ONE trace (the replication ``Message.tp`` pattern, PR 5): the
broker handler continues the worker's trace id, so QueryBatcher
queue-wait and fused-batch spans attribute to the worker's caller and
``/admin/traces/<id>`` renders one cross-process span tree. Responses
echo the request id with ``msg_type | 0x80``. Response payloads begin
with a status byte: ``0`` OK, ``1`` RESOURCE_EXHAUSTED, ``2`` DEGRADED,
``3`` ERROR; non-OK payloads carry ``u32 len | utf-8 message``.

SEARCH (0x01): ``u8 dtype (0=f32, 1=int8) | u8 flags (bit0: with_content)
| u32 B | u32 D | u32 k | f32 min_similarity | data`` — data is ``B*D``
f32, or ``B*D`` int8 followed by ``B`` f32 scales (codes/scale, the
quantize_rows convention). OK payload: ``u32 B`` then per query
``u32 n`` of ``f32 score | u16 id_len | id | u32 content_len | content``
(content_len is 0 unless with_content).

EMBED (0x02): ``u32 n | n × (u32 len | utf-8 text)``. OK payload:
``u32 B | u32 D | B*D f32``.

STATUS (0x03): empty. OK payload: ``u32 len | JSON`` (backend state,
corpus size, broker counters) — diagnostics only, never the hot path.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import struct
import tempfile
import threading
import time
import weakref
from typing import Any, Optional

import numpy as np

from nornicdb_tpu.errors import NotFoundError, ResourceExhausted
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

# message types
MSG_SEARCH = 0x01
MSG_EMBED = 0x02
MSG_STATUS = 0x03
# finished-trace shipment (fleet telemetry plane): a worker whose traced
# request crossed the broker ships its completed span records back so the
# primary's /admin/traces renders ONE tree spanning both processes.
# Payload: u32 len | JSON {trace_id, root, started, duration_ms, proc,
# spans: [...]}. OK payload: empty.
MSG_SPANS = 0x05
# Qdrant collection search (ROADMAP 1b): the points/search surface takes
# raw vectors, so workers ship it over the broker instead of proxying the
# whole HTTP request — the primary answers from the SHARED
# db.qdrant_registry() (the same per-collection device corpora the REST
# and gRPC transports serve). Payload: u8 flags (bit0 with_payload) |
# u32 limit | f32 score_threshold | u16 coll_len | coll utf-8 | u32 D |
# D f32 vector. OK payload: u32 len | JSON hits (registry.search output
# verbatim, so worker responses are body-identical to the primary's).
MSG_QDRANT = 0x04
RESP = 0x80

# response statuses
OK = 0
STATUS_RESOURCE_EXHAUSTED = 1
STATUS_DEGRADED = 2
STATUS_ERROR = 3

_REQUESTS = _REGISTRY.counter(
    "nornicdb_broker_requests_total",
    "Device-broker requests by operation and outcome",
    labels=("op", "outcome"),
)
for _op in ("search", "embed", "status", "qdrant"):
    for _out in ("ok", "shed", "degraded", "error"):
        _REQUESTS.labels(_op, _out)
_REQ_HIST = _REGISTRY.histogram(
    "nornicdb_broker_request_seconds",
    "Device-broker request service time by operation",
    labels=("op",),
)
_REQ_HIST.labels("search")
_REQ_HIST.labels("embed")
_REQ_HIST.labels("qdrant")
_CONNECTIONS = _REGISTRY.gauge(
    "nornicdb_broker_connections",
    "Worker connections currently attached to the device broker",
)
_QUERIES = _REGISTRY.counter(
    "nornicdb_broker_queries_total",
    "Individual search queries received by the broker (fused downstream "
    "by the QueryBatcher)",
)
_BYTES = _REGISTRY.counter(
    "nornicdb_broker_bytes_total",
    "Bytes moved across the broker socket",
    labels=("direction",),
)
_BYTES.labels("rx")
_BYTES.labels("tx")


class BrokerError(RuntimeError):
    """Broker replied with a protocol/server error."""


class BrokerUnavailable(BrokerError):
    """The broker socket is gone (primary down, not yet started, or the
    connection died twice) — workers fall back to the shared-memory host
    search, then to plain proxying."""


class BrokerDegraded(BrokerError):
    """The broker answered DEGRADED: the backend is serving from host
    arrays, so the worker should serve its own shared-memory host search
    instead of a pointless socket round-trip per query."""


# -- framing helpers ---------------------------------------------------------
def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("broker peer closed")
        buf += chunk
    return bytes(buf)


def _read_frame(sock: socket.socket) -> tuple[int, int, str, bytes]:
    head = _recv_exact(sock, 4)
    (length,) = struct.unpack("<I", head)
    if length < 10 or length > (1 << 30):
        raise ConnectionError(f"bad frame length {length}")
    body = _recv_exact(sock, length)
    mtype = body[0]
    (req_id,) = struct.unpack_from("<Q", body, 1)
    tp_len = body[9]
    if 10 + tp_len > length:
        raise ConnectionError(f"bad traceparent length {tp_len}")
    tp = body[10:10 + tp_len].decode("ascii", "replace") if tp_len else ""
    return mtype, req_id, tp, body[10 + tp_len:]


def _send_frame(sock: socket.socket, mtype: int, req_id: int,
                payload: bytes, traceparent: str = "") -> int:
    tp = traceparent.encode("ascii", "replace")[:255]
    frame = struct.pack("<IBQB", 10 + len(tp) + len(payload), mtype,
                        req_id, len(tp)) + tp + payload
    sock.sendall(frame)
    return len(frame)


def _status_payload(status: int, message: str) -> bytes:
    msg = message.encode()[:4096]
    return bytes([status]) + struct.pack("<I", len(msg)) + msg


def encode_search_request(
    queries: np.ndarray, k: int, min_similarity: float,
    with_content: bool = False,
    scales: Optional[np.ndarray] = None,
) -> bytes:
    """f32 block, or int8 codes + per-row scales when ``scales`` given."""
    q = np.ascontiguousarray(np.atleast_2d(queries))
    b, d = q.shape
    if scales is not None:
        codes = q.astype(np.int8, copy=False)
        body = codes.tobytes() + np.ascontiguousarray(
            scales, np.float32
        ).tobytes()
        dtype = 1
    else:
        body = q.astype(np.float32, copy=False).tobytes()
        dtype = 0
    flags = 1 if with_content else 0
    return struct.pack("<BBIIIf", dtype, flags, b, d, k,
                       float(min_similarity)) + body


def decode_search_request(
    payload: bytes,
) -> tuple[np.ndarray, int, float, bool]:
    dtype, flags, b, d, k, min_sim = struct.unpack_from("<BBIIIf", payload)
    off = struct.calcsize("<BBIIIf")
    if dtype == 0:
        q = np.frombuffer(payload, np.float32, b * d, off).reshape(b, d)
    elif dtype == 1:
        codes = np.frombuffer(payload, np.int8, b * d, off).reshape(b, d)
        scales = np.frombuffer(payload, np.float32, b, off + b * d)
        # codes/scale is the quantize_rows convention: x ~= int8 / scale
        q = codes.astype(np.float32) / np.maximum(scales, 1e-9)[:, None]
    else:
        raise ValueError(f"unknown query dtype {dtype}")
    return q, int(k), float(min_sim), bool(flags & 1)


def encode_search_response(
    results: list[list[tuple]], with_content: bool,
) -> bytes:
    out = bytearray([OK])
    out += struct.pack("<I", len(results))
    for row in results:
        out += struct.pack("<I", len(row))
        for hit in row:
            id_b = hit[0].encode()
            content_b = (hit[2].encode() if with_content and len(hit) > 2
                         else b"")
            out += struct.pack("<fH", float(hit[1]), len(id_b))
            out += id_b
            out += struct.pack("<I", len(content_b))
            out += content_b
    return bytes(out)


def decode_search_response(payload: bytes) -> list[list[tuple]]:
    (b,) = struct.unpack_from("<I", payload, 0)
    off = 4
    out: list[list[tuple]] = []
    for _ in range(b):
        (n,) = struct.unpack_from("<I", payload, off)
        off += 4
        row = []
        for _ in range(n):
            score, id_len = struct.unpack_from("<fH", payload, off)
            off += 6
            id_ = payload[off:off + id_len].decode()
            off += id_len
            (c_len,) = struct.unpack_from("<I", payload, off)
            off += 4
            content = payload[off:off + c_len].decode()
            off += c_len
            row.append((id_, score, content))
        out.append(row)
    return out


def encode_qdrant_request(
    collection: str, vector: np.ndarray, limit: int,
    score_threshold: float, with_payload: bool,
) -> bytes:
    coll = collection.encode()
    vec = np.ascontiguousarray(np.asarray(vector, np.float32).reshape(-1))
    return (
        struct.pack("<BIfH", 1 if with_payload else 0, int(limit),
                    float(score_threshold), len(coll))
        + coll
        + struct.pack("<I", vec.shape[0])
        + vec.tobytes()
    )


def decode_qdrant_request(
    payload: bytes,
) -> tuple[str, np.ndarray, int, float, bool]:
    flags, limit, thresh, coll_len = struct.unpack_from("<BIfH", payload)
    off = struct.calcsize("<BIfH")
    coll = payload[off:off + coll_len].decode()
    off += coll_len
    (d,) = struct.unpack_from("<I", payload, off)
    off += 4
    vec = np.frombuffer(payload, np.float32, d, off)
    return coll, vec, int(limit), float(thresh), bool(flags & 1)


def encode_embed_request(texts: list[str]) -> bytes:
    out = bytearray(struct.pack("<I", len(texts)))
    for t in texts:
        b = t.encode()
        out += struct.pack("<I", len(b))
        out += b
    return bytes(out)


def decode_embed_request(payload: bytes) -> list[str]:
    (n,) = struct.unpack_from("<I", payload, 0)
    off = 4
    texts = []
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", payload, off)
        off += 4
        texts.append(payload[off:off + ln].decode())
        off += ln
    return texts


# -- the broker (primary side) -----------------------------------------------
_ACTIVE: "list[weakref.ref]" = []
_ACTIVE_LOCK = threading.Lock()


def active_broker_stats() -> list[dict]:
    """Stats of every live broker (the /admin/stats "broker" section)."""
    out = []
    with _ACTIVE_LOCK:
        refs = list(_ACTIVE)
    for ref in refs:
        b = ref()
        if b is not None:
            out.append(b.stats())
    return out


class DeviceBroker:
    """The per-host device owner's request plane.

    One listener thread accepts worker connections; one thread per
    connection decodes frames and submits work into the fused-dispatch
    paths. Per-connection threads are correct here because a pool has a
    handful of workers with a handful of connections each — the fan-in
    point is the QueryBatcher, not the socket layer."""

    def __init__(self, db, path: Optional[str] = None):
        self.db = db
        self._own_dir: Optional[str] = None
        if path is None:
            self._own_dir = tempfile.mkdtemp(prefix="nornic-broker-")
            path = os.path.join(self._own_dir, "broker.sock")
        self.path = path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(path)
        except OSError:
            pass  # fresh path
        self._sock.bind(path)
        self._sock.listen(64)
        self._stop = threading.Event()
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self.counters = {
            "search_ok": 0, "search_shed": 0, "search_degraded": 0,
            "search_error": 0, "embed_ok": 0, "embed_shed": 0,
            "embed_error": 0, "qdrant_ok": 0, "qdrant_shed": 0,
            "qdrant_error": 0,
            "status": 0, "queries": 0, "connections": 0,
        }
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="nornicdb-broker-accept",
            daemon=True,
        )
        self._accept_thread.start()
        with _ACTIVE_LOCK:
            _ACTIVE[:] = [r for r in _ACTIVE if r() is not None]
            _ACTIVE.append(weakref.ref(self))

    # -- accept / serve ------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                self._conns.add(conn)
                self.counters["connections"] += 1
            _CONNECTIONS.set(float(len(self._conns)))
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="nornicdb-broker-conn", daemon=True,
            ).start()

    # nornlint: thread-role=serve-loop
    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    mtype, req_id, tp, payload = _read_frame(conn)
                except (ConnectionError, OSError):
                    return
                # 4B length + 1B type + 8B req id + 1B tp_len + tp
                # (ascii: chars == bytes) + payload
                _BYTES.labels("rx").inc(14 + len(tp) + len(payload))
                resp = self._dispatch(mtype, payload, tp)
                try:
                    n = _send_frame(conn, mtype | RESP, req_id, resp)
                except OSError:
                    return
                _BYTES.labels("tx").inc(n)
        finally:
            with self._lock:
                self._conns.discard(conn)
            _CONNECTIONS.set(float(len(self._conns)))
            try:
                conn.close()
            except OSError:
                pass  # peer already gone

    def _dispatch(self, mtype: int, payload: bytes,
                  traceparent: str = "") -> bytes:
        if mtype == MSG_SEARCH:
            # continue the WORKER's trace: the root span's parent is the
            # worker-side span that sent the frame, so the shipped-back
            # worker spans and this handler's spans (queue-wait, fused
            # batch) render as one tree at /admin/traces/<id>
            with _tracer.start_trace(
                "broker.search", traceparent=traceparent or None,
            ):
                return self._handle_search(payload)
        if mtype == MSG_EMBED:
            with _tracer.start_trace(
                "broker.embed", traceparent=traceparent or None,
            ):
                return self._handle_embed(payload)
        if mtype == MSG_QDRANT:
            with _tracer.start_trace(
                "broker.qdrant", traceparent=traceparent or None,
            ):
                return self._handle_qdrant(payload)
        if mtype == MSG_SPANS:
            return self._handle_spans(payload)
        if mtype == MSG_STATUS:
            self.counters["status"] += 1
            _REQUESTS.labels("status", "ok").inc()
            blob = json.dumps(self.status_snapshot()).encode()
            return bytes([OK]) + struct.pack("<I", len(blob)) + blob
        return _status_payload(STATUS_ERROR, f"unknown message {mtype}")

    def _handle_spans(self, payload: bytes) -> bytes:
        """Merge a worker's finished-trace span records into the local
        ring (telemetry.tracing.Tracer.merge_remote) — best-effort: a
        malformed shipment is an error reply, never a crash."""
        try:
            (ln,) = struct.unpack_from("<I", payload, 0)
            data = json.loads(payload[4:4 + ln].decode())
            merged = _tracer.merge_remote(
                str(data.get("trace_id") or ""),
                data.get("spans") or [],
                root=data.get("root"),
                started=data.get("started"),
                duration_ms=data.get("duration_ms"),
                proc=data.get("proc"),
            )
        except Exception as e:
            self.counters["spans_error"] = (
                self.counters.get("spans_error", 0) + 1
            )
            return _status_payload(STATUS_ERROR, f"bad spans frame: {e}")
        self.counters["spans_merged"] = (
            self.counters.get("spans_merged", 0) + (1 if merged else 0)
        )
        return bytes([OK])

    # -- handlers ------------------------------------------------------------
    def _handle_search(self, payload: bytes) -> bytes:
        t0 = time.perf_counter()
        try:
            q, k, min_sim, with_content = decode_search_request(payload)
        except Exception as e:
            self.counters["search_error"] += 1
            _REQUESTS.labels("search", "error").inc()
            return _status_payload(STATUS_ERROR, f"bad search frame: {e}")
        self.counters["queries"] += q.shape[0]
        _QUERIES.inc(q.shape[0])
        service = self.db.search
        corpus = service.corpus()
        if corpus is None:
            # nothing indexed yet: every query legitimately matches nothing
            self.counters["search_ok"] += 1
            _REQUESTS.labels("search", "ok").inc()
            return encode_search_response(
                [[] for _ in range(q.shape[0])], with_content
            )
        if q.shape[1] != corpus.dims:
            # reject BEFORE submit: a wrong-dim block fused into the shared
            # batch would error the np.stack and fan the failure out to
            # every other worker's queries in the same window
            self.counters["search_error"] += 1
            _REQUESTS.labels("search", "error").inc()
            return _status_payload(
                STATUS_ERROR,
                f"query dims {q.shape[1]} != corpus dims {corpus.dims}",
            )
        mgr = corpus._backend_mgr()
        if mgr.state in ("DEGRADED_CPU", "RECOVERING"):
            # tell the worker to serve its shared-memory host fallback
            # locally — same host arrays, no socket hop per query
            self.counters["search_degraded"] += 1
            _REQUESTS.labels("search", "degraded").inc()
            return _status_payload(
                STATUS_DEGRADED, f"backend {mgr.state}"
            )
        batcher = service.ensure_batcher()
        try:
            # submit the whole block THEN wait: tickets from this worker,
            # other workers, and the primary's own callers coalesce into
            # the same batch window — the fused-dispatch invariant the
            # multiproc bench asserts
            tickets = [
                batcher.submit(q[i], k, min_sim) for i in range(q.shape[0])
            ]
            results = [batcher.wait(t) for t in tickets]
        except ResourceExhausted as e:
            self.counters["search_shed"] += 1
            _REQUESTS.labels("search", "shed").inc()
            return _status_payload(STATUS_RESOURCE_EXHAUSTED, str(e))
        except Exception as e:
            self.counters["search_error"] += 1
            _REQUESTS.labels("search", "error").inc()
            log.exception("broker search failed")
            return _status_payload(STATUS_ERROR, f"search failed: {e}")
        if with_content:
            results = [
                [(id_, score, self._content(id_)) for id_, score in row]
                for row in results
            ]
        self.counters["search_ok"] += 1
        _REQUESTS.labels("search", "ok").inc()
        _REQ_HIST.labels("search").observe(time.perf_counter() - t0)
        return encode_search_response(results, with_content)

    def _handle_qdrant(self, payload: bytes) -> bytes:
        """Worker-shipped Qdrant points/search: answer from the SHARED
        collection registry (db.qdrant_registry()), whose per-collection
        DeviceCorpus dispatch is the same fused device path the REST/gRPC
        transports serve — so worker hits are id/score/payload-identical
        to the primary's by construction. A degraded backend needs no
        redirect here: collection corpora serve their exact host fallback
        internally, and workers hold no shared-memory mirror of
        collection corpora (only the default search corpus rides the shm
        plane today — ROADMAP 1b residual)."""
        t0 = time.perf_counter()
        try:
            coll, vec, limit, thresh, with_payload = decode_qdrant_request(
                payload
            )
        except Exception as e:
            self.counters["qdrant_error"] += 1
            _REQUESTS.labels("qdrant", "error").inc()
            return _status_payload(STATUS_ERROR, f"bad qdrant frame: {e}")
        registry_fn = getattr(self.db, "qdrant_registry", None)
        if not callable(registry_fn):
            self.counters["qdrant_error"] += 1
            _REQUESTS.labels("qdrant", "error").inc()
            return _status_payload(STATUS_ERROR, "no qdrant registry")
        self.counters["queries"] += 1
        _QUERIES.inc()
        try:
            hits = registry_fn().search(
                coll, vec, limit=limit, score_threshold=thresh,
                with_payload=with_payload,
            )
        except ResourceExhausted as e:
            # backpressure, not failure: the worker surfaces 429 +
            # Retry-After instead of proxying onto the overloaded primary
            self.counters["qdrant_shed"] = (
                self.counters.get("qdrant_shed", 0) + 1
            )
            _REQUESTS.labels("qdrant", "shed").inc()
            return _status_payload(STATUS_RESOURCE_EXHAUSTED, str(e))
        except NotFoundError as e:
            # unknown collection: a real error reply, not a proxy fallback
            # (the primary would 404 the same request)
            self.counters["qdrant_error"] += 1
            _REQUESTS.labels("qdrant", "error").inc()
            return _status_payload(STATUS_ERROR, str(e))
        except Exception as e:
            self.counters["qdrant_error"] += 1
            _REQUESTS.labels("qdrant", "error").inc()
            log.exception("broker qdrant search failed")
            return _status_payload(STATUS_ERROR, f"qdrant search failed: {e}")
        blob = json.dumps(hits).encode()
        self.counters["qdrant_ok"] += 1
        _REQUESTS.labels("qdrant", "ok").inc()
        _REQ_HIST.labels("qdrant").observe(time.perf_counter() - t0)
        return bytes([OK]) + struct.pack("<I", len(blob)) + blob

    def _content(self, node_id: str) -> str:
        try:
            node = self.db.storage.get_node(node_id)
        except NotFoundError:
            return ""  # hit evicted between search and fetch
        return str(node.properties.get("content", ""))

    def _handle_embed(self, payload: bytes) -> bytes:
        t0 = time.perf_counter()
        try:
            texts = decode_embed_request(payload)
        except Exception as e:
            self.counters["embed_error"] += 1
            _REQUESTS.labels("embed", "error").inc()
            return _status_payload(STATUS_ERROR, f"bad embed frame: {e}")
        embedder = self.db.embedder
        if embedder is None:
            self.counters["embed_error"] += 1
            _REQUESTS.labels("embed", "error").inc()
            return _status_payload(STATUS_ERROR, "no embedder configured")
        try:
            vecs = embedder.embed_batch(texts)
        except ResourceExhausted as e:
            self.counters["embed_shed"] += 1
            _REQUESTS.labels("embed", "shed").inc()
            return _status_payload(STATUS_RESOURCE_EXHAUSTED, str(e))
        except Exception as e:
            self.counters["embed_error"] += 1
            _REQUESTS.labels("embed", "error").inc()
            log.exception("broker embed failed")
            return _status_payload(STATUS_ERROR, f"embed failed: {e}")
        block = np.ascontiguousarray(np.stack(vecs), np.float32) if vecs \
            else np.zeros((0, 0), np.float32)
        self.counters["embed_ok"] += 1
        _REQUESTS.labels("embed", "ok").inc()
        _REQ_HIST.labels("embed").observe(time.perf_counter() - t0)
        return (bytes([OK])
                + struct.pack("<II", block.shape[0],
                              block.shape[1] if block.ndim > 1 else 0)
                + block.tobytes())

    # -- observability -------------------------------------------------------
    def status_snapshot(self) -> dict[str, Any]:
        service = self.db.search
        corpus = service.corpus()
        mgr_state = None
        if corpus is not None:
            mgr_state = corpus._backend_mgr().state
        out: dict[str, Any] = {
            "backend_state": mgr_state,
            "corpus_rows": len(corpus) if corpus is not None else 0,
            "counters": dict(self.counters),
        }
        batcher = getattr(service, "_batcher", None)
        if batcher is not None:
            out["batcher"] = batcher.stats.as_dict()
        return out

    def stats(self) -> dict[str, Any]:
        with self._lock:
            conns = len(self._conns)
        return {
            "path": self.path,
            "connections": conns,
            "counters": dict(self.counters),
        }

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass  # already closed
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # peer already gone
            try:
                c.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass  # never created / already removed
        if self._own_dir is not None:
            import shutil

            shutil.rmtree(self._own_dir, ignore_errors=True)


# -- the client (worker side) ------------------------------------------------
class BrokerClient:
    """Worker-side broker connection: one socket per calling thread
    (keep-alive, lazily connected, one reconnect attempt per call)."""

    def __init__(self, path: str, timeout: float = 30.0):
        self.path = path
        self.timeout = timeout
        self._local = threading.local()
        self._req_id = 0
        self._id_lock = threading.Lock()

    def _next_id(self) -> int:
        with self._id_lock:
            self._req_id += 1
            return self._req_id

    def _conn(self) -> socket.socket:
        sock = getattr(self._local, "sock", None)
        if sock is None:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self.timeout)
            sock.connect(self.path)
            self._local.sock = sock
        return sock

    def _drop(self) -> None:
        sock = getattr(self._local, "sock", None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass  # already dead
            self._local.sock = None

    def _call(self, mtype: int, payload: bytes) -> bytes:
        req_id = self._next_id()
        # the caller's active span (if any) rides the frame header, so
        # the primary-side handler continues the SAME trace id
        tp = _tracer.current_traceparent() or ""
        for attempt in (0, 1):
            try:
                sock = self._conn()
                _send_frame(sock, mtype, req_id, payload, tp)
                rtype, rid, _tp, body = _read_frame(sock)
                if rtype != (mtype | RESP) or rid != req_id:
                    raise ConnectionError(
                        f"broker protocol desync (type {rtype}, id {rid})"
                    )
                return body
            except (ConnectionError, OSError) as e:
                self._drop()
                if attempt:
                    raise BrokerUnavailable(
                        f"broker at {self.path}: {e}"
                    ) from e
        raise BrokerUnavailable(self.path)  # unreachable

    @staticmethod
    def _check(body: bytes) -> bytes:
        status = body[0]
        if status == OK:
            return body[1:]
        (ln,) = struct.unpack_from("<I", body, 1)
        msg = body[5:5 + ln].decode()
        if status == STATUS_RESOURCE_EXHAUSTED:
            raise ResourceExhausted(msg, reason="broker")
        if status == STATUS_DEGRADED:
            raise BrokerDegraded(msg)
        raise BrokerError(msg)

    def search(
        self, queries: np.ndarray, k: int, min_similarity: float = -1.0,
        with_content: bool = False,
    ) -> list[list[tuple]]:
        """Per-query [(id, score, content)] — content "" unless requested."""
        body = self._call(
            MSG_SEARCH,
            encode_search_request(queries, k, min_similarity, with_content),
        )
        return decode_search_response(self._check(body))

    def qdrant_search(
        self, collection: str, vector, limit: int = 10,
        score_threshold: float = -1.0, with_payload: bool = True,
    ) -> list[dict]:
        """Qdrant points/search via the broker: returns the registry's
        hit dicts ({"id", "score", "version"[, "payload"]}) verbatim."""
        body = self._check(self._call(
            MSG_QDRANT,
            encode_qdrant_request(collection, np.asarray(vector, np.float32),
                                  limit, score_threshold, with_payload),
        ))
        (ln,) = struct.unpack_from("<I", body, 0)
        return json.loads(body[4:4 + ln].decode())

    def embed(self, texts: list[str]) -> np.ndarray:
        body = self._check(self._call(MSG_EMBED,
                                      encode_embed_request(texts)))
        b, d = struct.unpack_from("<II", body, 0)
        return np.frombuffer(body, np.float32, b * d, 8).reshape(b, d)

    def status(self) -> dict[str, Any]:
        body = self._check(self._call(MSG_STATUS, b""))
        (ln,) = struct.unpack_from("<I", body, 0)
        return json.loads(body[4:4 + ln].decode())

    def ship_spans(self, entry: dict, proc: str) -> None:
        """Ship a finished trace's span records to the primary so its
        /admin/traces renders one cross-process tree. Best-effort:
        failure to ship must never fail the request that produced the
        trace."""
        blob = json.dumps({
            "trace_id": entry.get("trace_id"),
            "root": entry.get("root"),
            "started": entry.get("started"),
            "duration_ms": entry.get("duration_ms"),
            "proc": proc,
            "spans": entry.get("spans") or [],
        }).encode()
        try:
            self._check(self._call(
                MSG_SPANS, struct.pack("<I", len(blob)) + blob,
            ))
        except (BrokerError, OSError) as e:
            log.debug("trace shipment failed: %s", e)

    def close(self) -> None:
        self._drop()
