"""OpenAPI 3.0 description of the HTTP surface + embedded docs explorer.

Behavioral reference: docs/api-reference/openapi.yaml (1,162 lines, 30
paths) and cmd/swagger-ui in the reference. Here the spec is BUILT FROM
CODE next to the handlers it describes (a hand-maintained YAML drifts;
tests assert every documented path is actually routable), served at
/openapi.yaml and /openapi.json, with a self-contained explorer at /docs
(no CDN assets — this image is zero-egress, so swagger-ui's external
bundle would be a blank page).
"""

from __future__ import annotations

import functools
import json
from typing import Any

_ERR = {"type": "object", "properties": {"error": {"type": "string"}}}

_SEARCH_REQ = {
    "type": "object",
    "required": ["query"],
    "properties": {
        "query": {"type": "string"},
        "limit": {"type": "integer", "default": 10},
        "offset": {"type": "integer", "default": 0},
        "min_similarity": {"type": "number"},
        "labels": {"type": "array", "items": {"type": "string"}},
    },
}

_SEARCH_RESP = {
    "type": "object",
    "properties": {
        "results": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "id": {"type": "string"},
                    "content": {"type": "string"},
                    "score": {"type": "number"},
                    "labels": {"type": "array", "items": {"type": "string"}},
                },
            },
        },
        "total": {"type": "integer"},
    },
}

_TX_REQ = {
    "type": "object",
    "properties": {
        "statements": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["statement"],
                "properties": {
                    "statement": {"type": "string"},
                    "parameters": {"type": "object"},
                },
            },
        }
    },
}

_TX_RESP = {
    "type": "object",
    "properties": {
        "results": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "columns": {"type": "array", "items": {"type": "string"}},
                    "data": {"type": "array", "items": {"type": "object"}},
                    "stats": {"type": "object"},
                },
            },
        },
        "errors": {"type": "array", "items": {"type": "object"}},
    },
}


def _op(summary: str, *, tag: str, req: Any = None, resp: Any = None,
        params: list | None = None, auth: bool = True,
        method_desc: str = "", shed: bool = False) -> dict:
    op: dict = {
        "summary": summary,
        "tags": [tag],
        "responses": {
            "200": {"description": "success"},
        },
    }
    if method_desc:
        op["description"] = method_desc
    if resp is not None:
        op["responses"]["200"]["content"] = {
            "application/json": {"schema": resp}
        }
    if shed:
        # serving admission control (docs/operations.md "Embed serving
        # tuning"): bounded queues + deadlines shed under overload
        op["responses"]["429"] = {
            "description": "shed by serving admission control (embed/"
                           "search queue full or deadline exceeded); "
                           "retry with backoff",
            "content": {"application/json": {"schema": _ERR}},
        }
    if auth:
        op["responses"]["401"] = {
            "description": "authentication required (when auth is enabled)",
            "content": {"application/json": {"schema": _ERR}},
        }
        op["security"] = [{"bearerAuth": []}, {"basicAuth": []},
                          {"cookieAuth": []}]
    if req is not None:
        op["requestBody"] = {
            "required": True,
            "content": {"application/json": {"schema": req}},
        }
    if params:
        op["parameters"] = params
    return op


def _path_param(name: str, desc: str) -> dict:
    return {"name": name, "in": "path", "required": True,
            "description": desc, "schema": {"type": "string"}}


@functools.lru_cache(maxsize=4)
def build_spec(version: str = "0.4.0") -> dict:
    """The complete OpenAPI document as a plain dict (memoized: the spec is
    static per version, and /openapi.* is unauthenticated + hot)."""
    paths: dict[str, dict] = {
        # -- service ---------------------------------------------------------
        "/health": {"get": _op("Liveness probe", tag="service", auth=False)},
        "/status": {"get": _op(
            "Server status: node/edge counts, uptime, pending embeds",
            tag="service", auth=False)},
        "/metrics": {"get": _op(
            "Prometheus metrics (text exposition format)",
            tag="service", auth=False)},
        # -- auth ------------------------------------------------------------
        "/auth/config": {"get": _op(
            "Auth configuration for clients (securityEnabled, providers)",
            tag="auth", auth=False)},
        "/auth/token": {"post": _op(
            "Login: exchange username/password for a JWT; also sets the "
            "nornicdb_token session cookie",
            tag="auth", auth=False,
            req={"type": "object",
                 "required": ["username", "password"],
                 "properties": {"username": {"type": "string"},
                                "password": {"type": "string"}}},
            resp={"type": "object",
                  "properties": {"token": {"type": "string"},
                                 "expires_in": {"type": "integer"}}})},
        "/auth/logout": {"post": _op(
            "Revoke the current session token and clear the cookie",
            tag="auth")},
        "/auth/me": {"get": _op(
            "Current identity: username, roles",
            tag="auth",
            resp={"type": "object",
                  "properties": {"username": {"type": "string"},
                                 "roles": {"type": "array",
                                           "items": {"type": "string"}}}})},
        "/auth/password": {"post": _op(
            "Change the current user's password (verifies the old one)",
            tag="auth",
            req={"type": "object",
                 "required": ["old_password", "new_password"],
                 "properties": {"old_password": {"type": "string"},
                                "new_password": {"type": "string"}}})},
        "/auth/api-token": {"post": _op(
            "Generate a long-lived API token (admin only)",
            tag="auth",
            req={"type": "object",
                 "properties": {"subject": {"type": "string"},
                                "expires_in": {"type": "integer"}}})},
        "/auth/users": {
            "get": _op("List users (user_manage permission)", tag="auth"),
            "post": _op(
                "Create a user", tag="auth",
                req={"type": "object",
                     "required": ["username", "password"],
                     "properties": {
                         "username": {"type": "string"},
                         "password": {"type": "string"},
                         "roles": {"type": "array",
                                   "items": {"type": "string"}}}}),
        },
        "/auth/users/{username}": {
            "put": _op("Update a user's roles / disabled flag", tag="auth",
                       params=[_path_param("username", "target user")]),
            "delete": _op("Delete a user", tag="auth",
                          params=[_path_param("username", "target user")]),
        },
        "/auth/oauth/authorize": {"get": _op(
            "OAuth2 authorization-code flow entry point", tag="auth",
            auth=False)},
        "/auth/oauth/token": {"post": _op(
            "OAuth2 token endpoint (authorization_code / client_credentials)",
            tag="auth", auth=False)},
        # -- cypher ----------------------------------------------------------
        "/db/{database}/tx/commit": {"post": _op(
            "Neo4j HTTP transaction API: execute Cypher statements in one "
            "implicit transaction. Explicit BEGIN/COMMIT/ROLLBACK are "
            "rejected (the endpoint is stateless).",
            tag="cypher", req=_TX_REQ, resp=_TX_RESP,
            params=[_path_param("database", "target database or alias")])},
        "/graphql": {"post": _op(
            "GraphQL endpoint (queries, mutations, introspection)",
            tag="graphql",
            req={"type": "object",
                 "required": ["query"],
                 "properties": {"query": {"type": "string"},
                                "variables": {"type": "object"},
                                "operationName": {"type": "string"}}})},
        # -- memory / search -------------------------------------------------
        "/nornicdb/search": {"post": _op(
            "Hybrid search: vector + BM25 + RRF fusion over stored memories",
            tag="memory", req=_SEARCH_REQ, resp=_SEARCH_RESP, shed=True)},
        "/nornicdb/similar": {"post": _op(
            "Find memories similar to a given node",
            tag="memory",
            req={"type": "object",
                 "required": ["id"],
                 "properties": {"id": {"type": "string"},
                                "limit": {"type": "integer"}}},
            resp=_SEARCH_RESP)},
        "/nornicdb/embed": {"post": _op(
            "Embed a text through the continuous batching engine",
            tag="memory", shed=True)},
        "/nornicdb/search/rebuild": {"post": _op(
            "Rebuild the search indexes from storage", tag="memory")},
        "/nornicdb/rag/answer": {"post": _op(
            "GraphRAG answer: hybrid search + one-hop graph expansion "
            "assemble a token-budgeted context prompt, generated through "
            "the paged-KV continuous-batching engine (docs/generation.md)."
            " Without generation weights the answer is extractive from "
            "the retrieved context.",
            tag="memory", shed=True,
            req={"type": "object",
                 "required": ["question"],
                 "properties": {
                     "question": {"type": "string"},
                     "limit": {"type": "integer",
                               "description": "context nodes to retrieve"},
                     "max_tokens": {"type": "integer"},
                     "deadline_ms": {"type": "number"}}},
            resp={"type": "object",
                  "properties": {
                      "answer": {"type": "string"},
                      "mode": {"type": "string",
                               "enum": ["paged", "dense", "extractive"]},
                      "sources": {"type": "array",
                                  "items": {"type": "object"}},
                      "context": {"type": "object"},
                      "generated_tokens": {"type": "integer"},
                      "timings_ms": {"type": "object"}}})},
        # -- admin -----------------------------------------------------------
        "/admin/stats": {"get": _op(
            "Server statistics: storage, cache, query counters, uptime, "
            "search/device-sync/adjacency sections (the search corpus's "
            "`shard` block reports mesh dispatches, rows per shard, "
            "rebalances, local_k overflows — docs/operations.md \"Sharded "
            "serving tuning\"), and the `backend` section (device "
            "lifecycle state PROBING/READY/DEGRADED_CPU/RECOVERING, "
            "fallbacks_total, recoveries_total, probe latency, recent "
            "transitions — docs/backend.md), plus the `genserve` section "
            "when the generation engine is live (queue depth, page-pool "
            "pressure, evictions, sheds by reason — docs/generation.md)",
            tag="admin")},
        "/admin/backup": {"post": _op(
            "Write a full backup archive (gzip) server-side; returns the "
            "file path", tag="admin",
            req={"type": "object",
                 "properties": {"path": {"type": "string"}}},
            resp={"type": "object",
                  "properties": {"file": {"type": "string"}}})},
        "/admin/restore": {"post": _op(
            "Restore from a backup archive", tag="admin",
            req={"type": "object",
                 "required": ["path"],
                 "properties": {"path": {"type": "string"}}})},
        "/admin/config": {
            "get": _op("Running configuration + runtime feature flags",
                       tag="admin"),
            "post": _op(
                "Toggle runtime feature flags", tag="admin",
                req={"type": "object",
                     "properties": {"feature_flags": {"type": "object"}}}),
        },
        "/admin/tpu/status": {"get": _op(
            "Accelerator status (the reference's /admin/gpu/status "
            "analogue); reports initialised-backend state plus the "
            "lifecycle manager's view, never blocks on a down device "
            "relay", tag="admin")},
        "/admin/traces": {"get": _op(
            "Recent completed request traces (newest first): trace id, "
            "root span, duration, span count", tag="admin")},
        "/admin/traces/{trace_id}": {"get": _op(
            "One trace as a span tree (W3C trace id; see "
            "docs/observability.md for the propagation map). Spans "
            "shipped from prefork worker processes merge into the same "
            "tree, tagged with their proc", tag="admin")},
        "/admin/slow-queries": {"get": _op(
            "Slow-query capture ring: over-threshold statements with "
            "redacted text, plan summary, span breakdown and "
            "adjacency/device-sync counter deltas; worker-side vector "
            "search captures merge in with proc + served-path "
            "attribution", tag="admin")},
        "/admin/profile": {"post": _op(
            "On-demand device profiler: single-flight jax.profiler "
            "capture over ?seconds=N (clamped to the configured "
            "maximum), returned as a downloadable .tar.gz artifact; "
            "409 while another capture is in flight "
            "(docs/observability.md \"Device-time & HBM profiler\")",
            tag="admin")},
        # -- compliance ------------------------------------------------------
        "/gdpr/export": {"post": _op(
            "Export all data for a subject (GDPR right of access)",
            tag="compliance",
            req={"type": "object",
                 "properties": {"subject": {"type": "string"}}})},
        "/gdpr/delete": {"post": _op(
            "Erase a subject's data (GDPR right to erasure)",
            tag="compliance",
            req={"type": "object",
                 "properties": {"subject": {"type": "string"}}})},
        # -- assistant -------------------------------------------------------
        "/api/bifrost/chat/completions": {"post": _op(
            "Heimdall assistant chat (OpenAI-compatible shape; SSE when "
            "stream=true)",
            tag="assistant",
            req={"type": "object",
                 "required": ["messages"],
                 "properties": {
                     "messages": {"type": "array", "items": {
                         "type": "object",
                         "properties": {"role": {"type": "string"},
                                        "content": {"type": "string"}}}},
                     "model": {"type": "string"},
                     "stream": {"type": "boolean"}}})},
        "/api/bifrost/status": {"get": _op(
            "Assistant status: model registry, event queue depth",
            tag="assistant")},
        "/api/bifrost/events": {"get": _op(
            "Assistant event stream (SSE)", tag="assistant")},
        "/v1/models": {"get": _op(
            "OpenAI-compatible model list", tag="assistant")},
        "/v1/chat/completions": {"post": _op(
            "OpenAI-compatible alias of the assistant chat endpoint",
            tag="assistant",
            req={"type": "object",
                 "required": ["messages"],
                 "properties": {"messages": {"type": "array"}}})},
        # -- qdrant compat ---------------------------------------------------
        "/collections": {"get": _op(
            "Qdrant-compatible API root: list collections. Collection CRUD, "
            "points upsert/search/scroll and snapshots live under "
            "/collections/{name}/... exactly as in the Qdrant REST API.",
            tag="qdrant")},
        # -- mcp -------------------------------------------------------------
        "/mcp": {"post": _op(
            "Model Context Protocol endpoint (JSON-RPC: initialize, "
            "tools/list, tools/call)",
            tag="mcp",
            req={"type": "object",
                 "properties": {"jsonrpc": {"type": "string"},
                                "method": {"type": "string"},
                                "params": {"type": "object"},
                                "id": {}}})},
        # -- docs ------------------------------------------------------------
        "/openapi.json": {"get": _op(
            "This document (JSON)", tag="docs", auth=False)},
        "/openapi.yaml": {"get": _op(
            "This document (YAML)", tag="docs", auth=False)},
        "/docs": {"get": _op(
            "Embedded API explorer (self-contained HTML)", tag="docs",
            auth=False)},
    }
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "NornicDB-TPU HTTP API",
            "description": (
                "Graph + vector memory database, TPU-native. The HTTP "
                "surface mirrors the reference's REST API "
                "(docs/api-reference/openapi.yaml): Neo4j HTTP tx, hybrid "
                "search, auth/RBAC, admin, GDPR, GraphQL, Qdrant compat, "
                "MCP, and the Heimdall assistant."
            ),
            "version": version,
        },
        "servers": [{"url": "/"}],
        "components": {
            "securitySchemes": {
                "bearerAuth": {"type": "http", "scheme": "bearer",
                               "bearerFormat": "JWT"},
                "basicAuth": {"type": "http", "scheme": "basic"},
                "cookieAuth": {"type": "apiKey", "in": "cookie",
                               "name": "nornicdb_token"},
            },
        },
        "paths": paths,
    }


def to_yaml(spec: dict) -> str:
    """Serialize without requiring PyYAML at runtime (it is present in the
    image, but the spec only needs plain mappings/lists/scalars)."""
    try:
        import yaml

        return yaml.safe_dump(spec, sort_keys=False, allow_unicode=True)
    except ImportError:  # pragma: no cover
        return json.dumps(spec, indent=2)  # JSON is valid YAML


@functools.lru_cache(maxsize=4)
def spec_yaml(version: str = "0.4.0") -> str:
    """Cached YAML bytes for the hot unauthenticated GET."""
    return to_yaml(build_spec(version))


DOCS_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>NornicDB-TPU API</title>
<style>
  :root { --bg:#11151c; --panel:#1a2029; --fg:#d8dee9; --accent:#5fb3b3;
          --muted:#6c7a89; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 ui-monospace, Menlo, monospace; padding:20px; }
  h1 { color:var(--accent); font-size:18px; }
  .tag { margin:18px 0 6px; color:var(--accent); text-transform:uppercase;
         letter-spacing:1px; font-size:12px; }
  .op { background:var(--panel); border-radius:6px; margin:6px 0;
        padding:8px 12px; cursor:pointer; }
  .m { display:inline-block; width:52px; font-weight:bold; }
  .m.get { color:#a3be8c; } .m.post { color:#88c0d0; }
  .m.put { color:#ebcb8b; } .m.delete { color:#bf616a; }
  .path { color:var(--fg); }
  .sum { color:var(--muted); margin-left:8px; }
  pre { background:#0d1117; border-radius:6px; padding:10px;
        overflow:auto; display:none; white-space:pre-wrap; }
  .op.open pre { display:block; }
</style>
</head>
<body>
<h1>NornicDB-TPU API</h1>
<p><a style="color:var(--accent)" href="/openapi.yaml">openapi.yaml</a> ·
   <a style="color:var(--accent)" href="/openapi.json">openapi.json</a></p>
<div id="ops">loading…</div>
<script>
fetch('/openapi.json').then(r => r.json()).then(spec => {
  const byTag = {};
  for (const [path, methods] of Object.entries(spec.paths)) {
    for (const [method, op] of Object.entries(methods)) {
      const tag = (op.tags || ['other'])[0];
      (byTag[tag] = byTag[tag] || []).push({path, method, op});
    }
  }
  const root = document.getElementById('ops');
  root.innerHTML = '';
  for (const [tag, ops] of Object.entries(byTag)) {
    const h = document.createElement('div');
    h.className = 'tag'; h.innerText = tag;
    root.appendChild(h);
    for (const {path, method, op} of ops) {
      const d = document.createElement('div');
      d.className = 'op';
      const detail = {summary: op.summary, description: op.description,
                      parameters: op.parameters,
                      requestBody: op.requestBody, responses: op.responses};
      d.innerHTML = '<span class="m ' + method + '">' +
        method.toUpperCase() + '</span><span class="path"></span>' +
        '<span class="sum"></span><pre></pre>';
      d.querySelector('.path').innerText = path;
      d.querySelector('.sum').innerText = op.summary || '';
      d.querySelector('pre').innerText = JSON.stringify(detail, null, 2);
      d.addEventListener('click', () => d.classList.toggle('open'));
      root.appendChild(d);
    }
  }
});
</script>
</body>
</html>
"""
