"""HTTP server: Neo4j transaction API, search REST, admin, metrics, MCP.

Behavioral reference: /root/reference/pkg/server/server_router.go:53-240 —
/db/{name}/tx/commit (Neo4j HTTP tx API, server_db.go),
/nornicdb/search|similar|embed (server_nornicdb.go:236),
/auth/* endpoints, /admin/stats, /health, /status, /metrics (Prometheus
text, server_public.go:141-200), MCP mounting (pkg/mcp — 6 tools,
tools.go:63-332).
"""

from __future__ import annotations

import base64
import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np

from nornicdb_tpu.errors import (
    AuthError,
    DurabilityError,
    NornicError,
    ResourceExhausted,
)
from nornicdb_tpu.storage.types import Edge, Node


from nornicdb_tpu.cypher import ast as cypher_ast
from nornicdb_tpu.cypher.executor import classify_query_text
from nornicdb_tpu.cypher.parser import parse as cypher_parse
# registers the columnar-Cypher families (plan-cache hits/misses/
# invalidations, per-operator latency, columnar rows, offloads) so the
# tested docs/observability.md catalog renders in every server process
from nornicdb_tpu.cypher import plan as _cypher_plan  # noqa: F401
# registers the serving-engine metric families (packed tokens, pack
# efficiency, sheds, staging overlap, embedder selection) so the tested
# docs/observability.md catalog renders in every server process, whether
# or not a ServingEngine was constructed
from nornicdb_tpu.serving import stats as _serving_stats  # noqa: F401
# same deal for the device-broker and shared-memory read-plane families
# (nornicdb_broker_* / nornicdb_shm_*): registered at import so the tested
# catalog renders even in a single-process server with no worker pool
from nornicdb_tpu.server import broker as _broker_mod  # noqa: F401
from nornicdb_tpu.server import shm as _shm_mod  # noqa: F401


def _worker_pool_stats() -> list[dict]:
    # lazy: workers.py lazily imports RateLimiter from this module
    from nornicdb_tpu.server import workers as _workers_mod

    return _workers_mod.active_pool_stats()
# likewise the generation-engine families (queue depth, page-pool
# utilization, prefill/decode latency, sheds, tokens) — the tested
# observability catalog must render them in every serving process
from nornicdb_tpu.genserve import stats as _genserve_stats  # noqa: F401
# fleet telemetry plane: the federation module registers the worker
# serving-ladder + fleet-membership families and owns the /metrics merge
# collector; deviceprof registers the device program ledger + HBM
# residency families and the /admin/profile capture — imported here so
# the tested observability catalog renders them in every server process
from nornicdb_tpu.telemetry import budget as _budget
# the cost-model module registers the nornicdb_cost_model_* / SLO-burn /
# build-info families and answers GET /admin/capacity — imported here so
# the tested observability catalog renders them in every server process
from nornicdb_tpu.telemetry import costmodel as _costmodel
from nornicdb_tpu.telemetry import deviceprof as _deviceprof
from nornicdb_tpu.telemetry import federation as _federation
from nornicdb_tpu.telemetry.metrics import (
    REGISTRY as _TELEMETRY_REGISTRY,
    Registry as _Registry,
)
from nornicdb_tpu.telemetry.slowlog import slow_log as _slow_log
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)


def _jsonable(v: Any) -> Any:
    if isinstance(v, Node):
        return {
            "id": v.id,
            "labels": list(v.labels),
            "properties": _jsonable(v.properties),
        }
    if isinstance(v, Edge):
        return {
            "id": v.id,
            "type": v.type,
            "startNode": v.start_node,
            "endNode": v.end_node,
            "properties": _jsonable(v.properties),
        }
    if isinstance(v, dict):
        if v.get("__path__"):
            return {
                "nodes": [_jsonable(n) for n in v.get("nodes", [])],
                "relationships": [_jsonable(e) for e in v.get("relationships", [])],
            }
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


class RateLimiter:
    """Token-bucket per client (ref: pkg/security/middleware.go rate limiting)."""

    def __init__(self, rate: float = 100.0, burst: int = 200):
        self.rate = rate
        self.burst = burst
        self._buckets: dict[str, tuple[float, float]] = {}  # ip -> (tokens, ts)
        self._lock = threading.Lock()

    MAX_BUCKETS = 10_000

    def allow(self, client: str) -> bool:
        now = time.monotonic()
        with self._lock:
            if len(self._buckets) > self.MAX_BUCKETS:
                # prune clients whose buckets have refilled (idle long enough)
                self._buckets = {
                    ip: (t, ts)
                    for ip, (t, ts) in self._buckets.items()
                    if t + (now - ts) * self.rate < self.burst
                }
            tokens, ts = self._buckets.get(client, (float(self.burst), now))
            tokens = min(self.burst, tokens + (now - ts) * self.rate)
            if tokens < 1.0:
                self._buckets[client] = (tokens, now)
                return False
            self._buckets[client] = (tokens - 1.0, now)
            return True


class HttpServer:
    """(ref: server.New pkg/server/server.go)"""

    def __init__(
        self,
        db,
        host: str = "127.0.0.1",
        port: int = 7474,
        authenticator=None,
        auth_required: bool = False,
        rate_limit: float = 0.0,  # requests/sec per client; 0 = unlimited
        serve_ui: bool = True,  # False = headless (ref: -tags noui)
        cookie_secure: Optional[bool] = None,  # None = NORNICDB_COOKIE_SECURE
    ):
        self.db = db
        self.serve_ui = serve_ui
        if cookie_secure is None:
            cookie_secure = os.environ.get(
                "NORNICDB_COOKIE_SECURE", ""
            ).lower() in ("1", "true", "yes")
        self.cookie_secure = cookie_secure
        self.host = host
        self.port = port
        self.authenticator = authenticator
        self.auth_required = auth_required
        self.started_at = time.monotonic()
        self.requests = 0
        self.errors = 0
        self.slow_queries = 0
        self.slow_threshold = 1.0
        self._oauth_codes: dict[str, float] = {}
        self.rate_limiter = (
            RateLimiter(rate_limit, burst=max(int(rate_limit * 2), 1))
            if rate_limit > 0
            else None
        )
        self._qdrant = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # per-server child registry: instrumentation-site families from the
        # process-global REGISTRY render first, then this server's
        # db-specific callbacks — so several servers in one process (tests)
        # never fight over one namespace
        self.registry = _Registry(parent=_TELEMETRY_REGISTRY)
        self._http_hist = self.registry.histogram(
            "nornicdb_http_request_seconds",
            "HTTP request latency by method and route family",
            labels=("method", "route"),
        )
        self._http_by_code = self.registry.counter(
            "nornicdb_http_requests_by_code_total",
            "HTTP requests by method and status code",
            labels=("method", "code"),
        )
        self._register_db_metrics()

    @staticmethod
    def _parse_body(raw: bytes) -> dict:
        if not raw:
            return {}
        try:
            return json.loads(raw or b"{}")
        except json.JSONDecodeError:
            raise NornicError("invalid JSON body")

    # -- hot-path response cache (shared policy: server/respcache.py) -----
    @property
    def response_cache(self):
        if getattr(self, "_resp_cache", None) is None:
            from nornicdb_tpu.server.respcache import ResponseCache

            self._resp_cache = ResponseCache(
                lambda: self.db.search._generation
            )
        return self._resp_cache

    def _retention(self):
        if getattr(self, "_retention_mgr", None) is None:
            from nornicdb_tpu.retention import RetentionManager

            self._retention_mgr = RetentionManager(self.db.storage)
        return self._retention_mgr

    @property
    def qdrant(self):
        if self._qdrant is None:
            # prefer the db facade's SHARED registry — the device broker
            # serves worker-side Qdrant searches from it, and a private
            # per-server registry would double the collection corpora and
            # miss broker-visible upserts
            shared = getattr(self.db, "qdrant_registry", None)
            if callable(shared):
                self._qdrant = shared()
            else:  # bare-engine test doubles without the facade
                from nornicdb_tpu.server.qdrant import QdrantCollections

                self._qdrant = QdrantCollections(
                    self.db.storage,
                    vectorspaces=getattr(self.db, 'vectorspaces', None),
                )
        return self._qdrant

    # -- request handling ----------------------------------------------------
    def _make_handler(server_self):  # noqa: N805
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + delayed-ACK costs ~40ms/request on keep-alive
            # connections (this attribute lives on the HANDLER, per
            # socketserver.StreamRequestHandler)
            disable_nagle_algorithm = True

            def log_message(self, *args):  # quiet
                pass

            def _send(
                self,
                code: int,
                body: Any,
                content_type="application/json",
                extra_headers: Optional[dict[str, str]] = None,
            ):
                data = (
                    json.dumps(body).encode()
                    if content_type == "application/json"
                    else body.encode()
                )
                self._send_raw(code, data, content_type, extra_headers)

            def _send_raw(
                self,
                code: int,
                data: bytes,
                content_type="application/json",
                extra_headers: Optional[dict[str, str]] = None,
            ) -> None:
                """Pre-encoded body with the standard header set."""
                self._status = code
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                root = getattr(self, "_trace_root", None)
                if root is not None and root.trace_id is not None:
                    # propagate the (possibly ingested) trace id back to the
                    # caller (W3C trace-context response propagation)
                    self.send_header("traceparent", root.traceparent())
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Access-Control-Allow-Origin", "*")
                # security headers (ref: pkg/security/middleware.go)
                self.send_header("X-Content-Type-Options", "nosniff")
                self.send_header("X-Frame-Options", "DENY")
                self.send_header("Referrer-Policy", "no-referrer")
                self.end_headers()
                self.wfile.write(data)

            def _raw_body(self) -> bytes:
                length = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(length) if length else b""

            def _body(self) -> dict:
                return server_self._parse_body(self._raw_body())

            def _auth(self, permission: str = "read") -> Optional[dict]:
                if not server_self.auth_required or server_self.authenticator is None:
                    return {"sub": "anonymous", "role": "admin"}
                hdr = self.headers.get("Authorization", "")
                auth = server_self.authenticator
                if hdr.startswith("Bearer "):
                    return auth.authorize(hdr[7:], permission)
                if hdr.startswith("Basic "):
                    try:
                        user, pw = (
                            base64.b64decode(hdr[6:]).decode().split(":", 1)
                        )
                    except Exception:
                        raise AuthError("malformed Basic auth")
                    token = auth.authenticate(user, pw)
                    return auth.authorize(token, permission)
                # browser sessions authenticate via the HttpOnly cookie set
                # by POST /auth/token (ref: server_auth.go handleToken's
                # SetCookie("nornicdb_token", ...))
                token = self._cookie_token()
                if token:
                    return auth.authorize(token, permission)
                raise AuthError("authentication required")

            def _cookie_token(self) -> str:
                for part in (self.headers.get("Cookie") or "").split(";"):
                    k, _, v = part.strip().partition("=")
                    if k == "nornicdb_token":
                        return v
                return ""

            def do_OPTIONS(self):  # CORS preflight
                self.send_response(204)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Methods", "GET, POST, DELETE, OPTIONS"
                )
                self.send_header(
                    "Access-Control-Allow-Headers", "Authorization, Content-Type"
                )
                self.send_header("Content-Length", "0")
                self.end_headers()

            def _client_ip(self) -> str:
                # Worker-pool proxies (server/workers.py) connect from
                # loopback and carry the real peer in X-Forwarded-For.
                # Trust the header ONLY for loopback peers — an external
                # client must not be able to spoof its rate-limit bucket.
                peer = self.client_address[0]
                if peer in ("127.0.0.1", "::1"):
                    fwd = (self.headers.get("X-Forwarded-For") or "").strip()
                    if fwd:
                        # rightmost entry = the hop our trusted loopback
                        # worker appended; earlier entries are client-supplied
                        # and spoofable
                        return fwd.split(",")[-1].strip()
                return peer

            def _limited(self) -> bool:
                rl = server_self.rate_limiter
                if rl is not None and not rl.allow(self._client_ip()):
                    self._send(429, {"error": "rate limit exceeded"})
                    return True
                return False

            def _dispatch(self, method: str):
                server_self.requests += 1
                if self._limited():
                    return
                path = self.path.split("?")[0]
                route = server_self._route_label(path)
                self._status = 200
                t0 = time.perf_counter()
                # ingress tracing: ingest W3C traceparent, open the root
                # span every downstream span (executor, storage, device
                # sync) hangs off; the id is echoed on the response by
                # _send_raw
                with _tracer.start_trace(
                    f"http.{method}", traceparent=self.headers.get("traceparent")
                ) as root:
                    if root.trace_id is not None:
                        root.set_attr("path", path)
                        root.set_attr("route", route)
                    self._trace_root = root
                    try:
                        if path.startswith("/collections"):
                            server_self._route_qdrant(self, method, path)
                            return
                        if method == "GET":
                            server_self._route_get(self)
                        elif method == "POST":
                            server_self._route_post(self)
                        elif path.startswith("/auth/users/"):
                            server_self._route_user_by_name(self, method, path)
                        else:
                            self._send(405, {"error": f"{method} not allowed on {path}"})
                    except AuthError as e:
                        self._send(401, {"error": str(e)})
                    except ResourceExhausted as e:
                        # serving admission control shed this request
                        # (embed/search queue full or deadline passed):
                        # backpressure, not failure — clients back off
                        self._send(
                            429,
                            {"error": str(e), "reason": e.reason},
                            extra_headers={"Retry-After": "1"},
                        )
                    except DurabilityError as e:
                        # the write was NOT acked and the WAL tail was
                        # repaired: transient storage unavailability, not
                        # a client error — 503 mirrors Bolt's
                        # Neo.TransientError.General.DatabaseUnavailable
                        # mapping (statement-level durability failures
                        # are already reported in-body by the tx API;
                        # this catches the ones raised outside a
                        # statement, e.g. lazy system-DB writes)
                        self._send(
                            503,
                            {"error": str(e), "kind": e.kind},
                            extra_headers={"Retry-After": "1"},
                        )
                    except Exception as e:
                        server_self.errors += 1
                        self._send(400 if method != "GET" else 500, {"error": str(e)})
                    finally:
                        # a keep-alive connection reuses this handler:
                        # responses sent before the NEXT request's trace
                        # opens (e.g. the rate limiter's 429) must not echo
                        # this request's traceparent
                        self._trace_root = None
                        elapsed = time.perf_counter() - t0
                        server_self._http_hist.labels(method, route).observe(
                            elapsed
                        )
                        server_self._http_by_code.labels(
                            method, str(self._status)
                        ).inc()

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        return Handler

    def _route_qdrant(self, h, method: str, path: str) -> None:
        """Qdrant-compatible vector API (ref: pkg/qdrantgrpc, REST shapes)."""
        from nornicdb_tpu.server.qdrant import handle_qdrant

        h._auth("read" if method == "GET" else "write")
        body = h._body() if method in ("POST", "PUT", "DELETE") else {}
        routed = handle_qdrant(self.qdrant, method, path, body)
        if routed is None:
            h._send(404, {"error": f"not found: {path}"})
            return
        code, payload = routed
        h._send(code, _jsonable(payload))

    # -- GET routes --------------------------------------------------------------
    def _route_get(self, h) -> None:
        path = h.path.split("?")[0]
        if path in ("/", "/ui", "/browser", "/login", "/security", "/admin"):
            # embedded console (ref: ui/embed.go — SPA at the root, with
            # deep links /login and /security served by the same handler,
            # server_router.go:59-64; set serve_ui=False for the
            # reference's -tags noui equivalent)
            if not self.serve_ui:
                h._send(404, {"error": "ui disabled"})
                return
            from nornicdb_tpu.server.ui import UI_HTML

            h._send(200, UI_HTML, content_type="text/html; charset=utf-8")
            return
        if path in ("/openapi.json", "/openapi.yaml", "/docs"):
            # machine-readable API description + embedded explorer
            # (ref: docs/api-reference/openapi.yaml + cmd/swagger-ui).
            # Behind serve_ui: the reference ships swagger-ui as a separate
            # binary, so a headless build exposes no docs/HTML surface —
            # and the spec enumerates every endpoint, which a locked-down
            # deployment may not want served unauthenticated.
            if not self.serve_ui:
                h._send(404, {"error": "ui disabled"})
                return
            from nornicdb_tpu.server import openapi

            if path == "/docs":
                h._send(200, openapi.DOCS_HTML,
                        content_type="text/html; charset=utf-8")
            elif path == "/openapi.yaml":
                h._send(200, openapi.spec_yaml(),
                        content_type="application/yaml; charset=utf-8")
            else:
                h._send(200, openapi.build_spec())
            return
        if path.startswith("/auth/oauth/authorize"):
            # OAuth2 authorization-code flow, resource-owner-credential
            # variant (ref: pkg/auth/oauth.go + cmd/oauth-provider): GET
            # with response_type=code&redirect_uri=... returns a 302 carrying
            # a short-lived code; exchange at /auth/oauth/token with
            # grant_type=authorization_code (credentials passed via the
            # basic-auth header on the exchange).
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(h.path).query)
            redirect = (q.get("redirect_uri") or [""])[0]
            state = (q.get("state") or [""])[0]
            if not redirect or (q.get("response_type") or [""])[0] != "code":
                h._send(400, {"error": "response_type=code and redirect_uri required"})
                return
            import secrets as _secrets

            code = _secrets.token_urlsafe(24)
            self._oauth_codes[code] = time.time() + 120.0
            sep = "&" if "?" in redirect else "?"
            target = f"{redirect}{sep}code={code}"
            if state:
                target += f"&state={state}"
            h.send_response(302)
            h.send_header("Location", target)
            h.send_header("Content-Length", "0")
            h.end_headers()
            return
        if path == "/health":
            h._send(200, {"status": "ok"})
            return
        if path == "/status":
            wal = self.db.wal_stats()
            degraded = bool(wal and wal.get("degraded"))
            body = {
                "status": "degraded" if degraded else "running",
                "uptime_seconds": round(time.monotonic() - self.started_at, 1),
                "nodes": self.db.storage.node_count(),
                "edges": self.db.storage.edge_count(),
                "version": "1.0.0",
            }
            if degraded:
                body["wal_corruption"] = wal.get("corruption_info", "")
            h._send(200, body)
            return
        if path == "/metrics":
            # fleet federation: with registered worker segments the body
            # is the structural merge of every live worker's exposition
            # under a proc label; with none it is byte-identical to the
            # single-process exposition (telemetry/federation.py)
            h._send(200, _federation.FLEET.merged_exposition(
                self.registry.render_prometheus),
                    content_type="text/plain; version=0.0.4")
            return
        if path == "/auth/config":
            # UI bootstrap: is auth on, which OAuth providers exist
            # (ref: server_auth.go:215 handleAuthConfig)
            providers = []
            if os.environ.get("NORNICDB_AUTH_PROVIDER") == "oauth":
                providers.append(
                    {
                        "name": "oauth",
                        "url": "/auth/oauth/authorize",
                        "displayName": "OAuth",
                    }
                )
            h._send(
                200,
                {
                    "devLoginEnabled": True,
                    "securityEnabled": bool(
                        self.auth_required and self.authenticator is not None
                    ),
                    "oauthProviders": providers,
                },
            )
            return
        if path == "/auth/me":
            # current user for the UI session (ref: server_auth.go:368)
            if not self.auth_required or self.authenticator is None:
                h._send(
                    200,
                    {
                        "id": "anonymous",
                        "username": "anonymous",
                        "roles": ["admin"],
                        "enabled": True,
                    },
                )
                return
            payload = h._auth("read")
            try:
                user = self.authenticator.get_user(payload["sub"])
                body = {
                    "id": f"user-{user.username}",
                    "username": user.username,
                    "roles": [user.role],
                    "created_at": user.created_at,
                    "disabled": user.disabled,
                }
            except AuthError:
                # token subject without a stored user (e.g. API token)
                body = {
                    "id": payload["sub"],
                    "username": payload["sub"],
                    "roles": [payload.get("role", "none")],
                    "disabled": False,
                }
            h._send(200, body)
            return
        if path == "/auth/users":
            # admin user list (ref: server_auth.go:549 handleUsers GET)
            h._auth("user_manage")
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            h._send(
                200,
                [
                    {
                        "username": u.username,
                        "roles": [u.role],
                        "created_at": u.created_at,
                        "disabled": u.disabled,
                    }
                    for u in self.authenticator.list_users()
                ],
            )
            return
        if path.startswith("/auth/users/"):
            h._auth("user_manage")
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            from urllib.parse import unquote

            name = unquote(path[len("/auth/users/"):])
            try:
                u = self.authenticator.get_user(name)
            except AuthError:
                h._send(404, {"error": f"user {name} not found"})
                return
            h._send(
                200,
                {
                    "username": u.username,
                    "roles": [u.role],
                    "created_at": u.created_at,
                    "disabled": u.disabled,
                },
            )
            return
        if path == "/api/bifrost/status":
            # assistant status: metrics, models, plugins
            # (ref: server_router.go:211 -> heimdall handler status)
            h._auth("read")
            mgr = self.db.heimdall
            body = {
                "status": "ok",
                "metrics": vars(mgr.metrics),
                "named_metrics": mgr.metrics_registry.snapshot(),
                "models": [m.as_dict() for m in mgr.models.list()],
                "events": {
                    "delivered": mgr.events.delivered,
                    "dropped": mgr.events.dropped,
                },
            }
            host = getattr(mgr, "plugin_host", None)
            if host is not None:
                body["plugins"] = [vars(p) for p in host.plugins()]
            h._send(200, body)
            return
        if path == "/v1/models":
            # OpenAI-compatible model listing from the registry
            h._auth("read")
            h._send(200, {
                "object": "list",
                "data": [
                    {"id": m.name, "object": "model", "owned_by": "nornicdb",
                     "type": m.type, "loaded": m.loaded}
                    for m in self.db.heimdall.models.list()
                ],
            })
            return
        if path == "/api/bifrost/events":
            # SSE notification bus (ref: server_router.go:219 -> bifrost.go)
            h._auth("read")
            import queue as _queue

            bus = self.db.heimdall.bifrost
            q = bus.subscribe()
            h.send_response(200)
            h.send_header("Content-Type", "text/event-stream")
            h.send_header("Cache-Control", "no-cache")
            h.send_header("Connection", "close")
            h.end_headers()
            try:
                while True:
                    try:
                        event = q.get(timeout=15.0)
                    except _queue.Empty:
                        h.wfile.write(b": keepalive\n\n")
                        h.wfile.flush()
                        continue
                    h.wfile.write(
                        f"event: {event['event']}\n".encode()
                        + b"data: " + json.dumps(
                            event["data"], default=str
                        ).encode() + b"\n\n"
                    )
                    h.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass
            finally:
                bus.unsubscribe(q)
            h.close_connection = True
            return
        if path == "/admin/traces":
            # recent completed traces, newest first (tentpole pillar 2)
            h._auth("admin")
            h._send(200, {"traces": _tracer.traces()})
            return
        if path.startswith("/admin/traces/"):
            h._auth("admin")
            trace_id = path[len("/admin/traces/"):]
            tree = _tracer.trace(trace_id)
            if tree is None:
                h._send(404, {"error": f"trace {trace_id} not found"})
            else:
                # deadline-budget attribution: predicted vs actual per
                # named stage, when admission opened a budget for this
                # trace (satellite: budget breakdown on trace detail)
                budget = _budget.breakdown_for(trace_id,
                                               tree.get("spans", []))
                if budget is not None:
                    tree["budget"] = budget
                h._send(200, tree)
            return
        if path == "/admin/capacity":
            # cost-model table + headroom (max sustainable qps per
            # workload class) + SLO window state — the closed-loop
            # capacity surface the predictive admission decides from
            h._auth("admin")
            h._send(200, _costmodel.capacity_snapshot())
            return
        if path == "/admin/slow-queries":
            # over-threshold statements with redacted text, plan summary,
            # span breakdown and counter deltas (tentpole pillar 3);
            # worker-side entries (vector searches with served-path
            # attribution, federated via the fleet segments) merge in
            # tagged with their proc
            h._auth("admin")
            entries = [dict(e, proc="primary")
                       for e in _slow_log.snapshot()]
            entries.extend(_federation.FLEET.slow_queries())
            entries.sort(key=lambda e: e.get("timestamp", 0.0),
                         reverse=True)
            h._send(200, {
                "threshold_ms": _slow_log.threshold_s * 1e3,
                "recorded": _slow_log.recorded,
                "slow_queries": entries,
            })
            return
        if path == "/admin/stats":
            h._auth("admin")
            stats = {
                "requests": self.requests,
                "errors": self.errors,
                "slow_queries": self.slow_queries,
                "telemetry": {
                    "traces_buffered": _tracer.count(),
                    "slow_queries_recorded": _slow_log.recorded,
                },
                "nodes": self.db.storage.node_count(),
                "edges": self.db.storage.edge_count(),
                "pending_embeddings": len(self.db.storage.pending_embed_ids()),
                "databases": self.db.database_manager.storage_stats(),
            }
            if self.db._embed_worker is not None:
                stats["embed_worker"] = vars(self.db._embed_worker.stats)
            engine = self.db.serving_engine()
            if engine is not None:
                # continuous batching engine health: pack efficiency,
                # sheds, staging overlap (docs/operations.md "Embed
                # serving tuning" reads these)
                stats["serving"] = engine.stats_snapshot()
            gen_engine = self.db.genserve_engine()
            if gen_engine is not None:
                # paged-KV generation engine health: queue depth, page
                # pool pressure, evictions, sheds by reason
                # (docs/generation.md reads these)
                stats["genserve"] = gen_engine.stats_snapshot()
            search = getattr(self.db, "search", None)
            if search is not None and hasattr(search, "stats_snapshot"):
                # index/search counters + device-sync patching + query
                # batcher sizes (tune batch_window / uploader cadence here)
                stats["search"] = search.stats_snapshot()
            wal = self.db.wal_stats()
            if wal is not None:
                stats["wal"] = wal
            adjacency = self.db.adjacency_stats()
            if adjacency is not None:
                # CSR topology snapshot health: builds / delta merges /
                # epoch retries / resident bytes (tune merge_threshold here)
                stats["adjacency"] = adjacency
            cypher_stats = self.db.cypher_stats()
            if cypher_stats is not None:
                # columnar Cypher engine: plan-cache hits/misses/
                # invalidations + full/fallback/unsupported outcomes
                # (docs/operations.md "Columnar Cypher execution")
                stats["cypher"] = cypher_stats
            from nornicdb_tpu import backend as _backend_mod

            backend_stats = _backend_mod.manager_stats()
            if backend_stats is not None:
                # device lifecycle: state machine position, fallback /
                # recovery counters, probe latency, recent transitions
                # (docs/backend.md failure playbook reads from here)
                stats["backend"] = backend_stats
            brokers = _broker_mod.active_broker_stats()
            if brokers:
                # cross-process device broker: worker connections, request
                # outcomes (ok/shed/degraded), queries fused downstream
                # (docs/operations.md "Multi-process serving" reads these)
                stats["broker"] = brokers[0] if len(brokers) == 1 else brokers
            from nornicdb_tpu.server import readplane as _readplane_mod

            publishers = _readplane_mod.active_publisher_stats()
            if publishers:
                # shared-memory read plane: per-segment generation /
                # publish counts / payload bytes
                stats["shm"] = (publishers[0] if len(publishers) == 1
                                else publishers)
            pools = _worker_pool_stats()
            if pools:
                # prefork worker pool: live workers, respawns, ports
                stats["workers"] = pools[0] if len(pools) == 1 else pools
            if pools or _federation.FLEET.members():
                # fleet telemetry plane: per-worker exposition freshness
                # (federation half) + per-worker liveness/respawn state
                # (pool half) — the one place an operator reads "which
                # workers are alive and reporting"
                from nornicdb_tpu.server import workers as _workers_mod

                fleet = _federation.FLEET.stats()
                fleet["pools"] = _workers_mod.active_pool_fleet_states()
                stats["fleet"] = fleet
            # device-time & HBM profiler: program ledger by
            # (subsystem, kind, shape) + residency by component
            # (docs/observability.md "Device-time & HBM profiler")
            stats["deviceprof"] = _deviceprof.snapshot()
            h._send(200, stats)
            return
        if path == "/admin/config":
            # (ref: handleAdminConfig server_admin.go:64 — running config
            # view + runtime feature flags)
            h._auth("admin")
            from nornicdb_tpu.config import flags

            # secret material never leaves the process, even for admins:
            # the response flows through proxies and ends up in logs
            secret = ("passphrase", "password", "secret", "token", "api_key")
            cfg = {
                k: ("<redacted>" if v and any(s in k for s in secret) else v)
                for k, v in vars(self.db.config).items()
                # feature_flags on Config is an inert seed field; the live
                # registry is the top-level feature_flags key below
                if not k.startswith("_") and k != "feature_flags"
            }
            h._send(200, {"config": cfg, "feature_flags": flags.all()})
            return
        if path == "/admin/tpu/status":
            # the reference's /admin/gpu/status analogue: accelerator
            # availability WITHOUT forcing backend init (a down relay
            # would hang the admin surface for minutes)
            h._auth("admin")
            h._send(200, self._tpu_status())
            return
        h._send(404, {"error": f"not found: {path}"})

    def _tpu_status(self) -> dict:
        """(ref: server_gpu.go:14 handleGPUStatus). Reports from already-
        initialised JAX state only — probing an uninitialised backend can
        block for minutes when the device relay is down."""
        import jax

        out = {"framework": "jax", "backend_initialized": False,
               "devices": [], "platform": None}
        from nornicdb_tpu import backend as _backend_mod

        lifecycle = _backend_mod.manager_stats()
        if lifecycle is not None:
            # lifecycle-manager view: state machine position + counters
            # (reported even pre-init — the manager probes on its own
            # worker thread, so this never blocks the admin surface)
            out["lifecycle"] = lifecycle
        try:
            # backends are registered only after first real device use
            from jax._src import xla_bridge

            if hasattr(xla_bridge, "backends_are_initialized"):
                initialized = xla_bridge.backends_are_initialized()
            else:  # older/newer jax without the public check
                initialized = bool(getattr(xla_bridge, "_backends", {}))
        except Exception:  # nornlint: disable=NL-ERR02
            initialized = False  # private-API drift: report uninitialised
        if not initialized:
            out["note"] = ("backend not initialised yet; first search or "
                           "embed will initialise it")
            return out
        try:
            devs = jax.devices()
            out["backend_initialized"] = True
            out["platform"] = devs[0].platform if devs else None
            out["devices"] = [str(d) for d in devs]
            out["device_count"] = len(devs)
        except Exception as e:  # relay flapped mid-call
            out["error"] = str(e)[:200]
        return out

    # -- telemetry wiring (ref: server_public.go:141-200, now rendered
    # entirely by the telemetry registry instead of a hand-built string) ----
    def _register_db_metrics(self) -> None:
        """Register this server's db-level providers as render-time
        callbacks.  Subsystem stats() dicts plug in via stats_callback
        (numeric leaves flattened to gauges) with exact-name renames for
        the documented/asserted metric names."""
        reg = self.registry
        reg.gauge_callback(
            "nornicdb_uptime_seconds", "Server uptime in seconds",
            lambda: time.monotonic() - self.started_at,
        )
        reg.counter_callback(
            "nornicdb_requests_total", "HTTP requests served",
            lambda: self.requests,
        )
        reg.counter_callback(
            "nornicdb_errors_total", "HTTP requests that raised",
            lambda: self.errors,
        )
        reg.counter_callback(
            "nornicdb_slow_queries_total",
            "Statements captured by the slow-query log",
            lambda: _slow_log.recorded,
        )
        reg.gauge_callback(
            "nornicdb_nodes", "Nodes in the default database view",
            lambda: self.db.storage.node_count(),
        )
        reg.gauge_callback(
            "nornicdb_edges", "Edges in the default database view",
            lambda: self.db.storage.edge_count(),
        )
        reg.gauge_callback(
            "nornicdb_pending_embeddings", "Nodes awaiting embedding",
            lambda: len(self.db.storage.pending_embed_ids()),
        )

        def _embed_stats() -> Optional[dict]:
            w = self.db._embed_worker
            return None if w is None else vars(w.stats)

        reg.stats_callback(
            "nornicdb_embed", _embed_stats,
            help_="Embed-worker counters",
            rename={
                "nornicdb_embed_processed":
                    "nornicdb_embeddings_processed_total",
                "nornicdb_embed_failed": "nornicdb_embeddings_failed_total",
            },
            counters={"nornicdb_embed_processed", "nornicdb_embed_failed"},
        )

        def _search_stats() -> Optional[dict]:
            # the LAZY slot, never the property: /metrics must not force
            # search-service construction (and a full index build)
            search = self.db._search
            if search is None or not hasattr(search, "stats_snapshot"):
                return None
            return search.stats_snapshot()

        reg.stats_callback(
            "nornicdb_search", _search_stats,
            help_="Search service / device-sync / query-batcher counters",
            rename={
                "nornicdb_search_corpus_sync_bytes_uploaded":
                    "nornicdb_device_sync_bytes_total",
                "nornicdb_search_corpus_sync_patches":
                    "nornicdb_device_sync_patches_total",
                "nornicdb_search_corpus_sync_full_uploads":
                    "nornicdb_device_sync_full_uploads_total",
                "nornicdb_search_corpus_sync_query_stall_s":
                    "nornicdb_device_sync_query_stall_seconds_total",
                "nornicdb_search_batcher_queries":
                    "nornicdb_batched_queries_total",
                "nornicdb_search_batcher_batches":
                    "nornicdb_query_batches_total",
                "nornicdb_search_batcher_max_batch":
                    "nornicdb_query_batch_max",
            },
            counters={
                "nornicdb_search_corpus_sync_bytes_uploaded",
                "nornicdb_search_corpus_sync_patches",
                "nornicdb_search_corpus_sync_full_uploads",
                "nornicdb_search_corpus_sync_query_stall_s",
                "nornicdb_search_batcher_queries",
                "nornicdb_search_batcher_batches",
                "nornicdb_search_searches",
                "nornicdb_search_indexed",
                "nornicdb_search_removed",
                "nornicdb_search_vector_candidates",
                "nornicdb_search_fulltext_candidates",
                # mesh-sharded serving (ShardedCorpus.shard_stats)
                "nornicdb_search_corpus_shard_dispatches",
                "nornicdb_search_corpus_shard_ivf_dispatches",
                "nornicdb_search_corpus_shard_rebalances",
                "nornicdb_search_corpus_shard_local_k_overflows",
                "nornicdb_search_corpus_shard_promotions",
            },
        )
        reg.stats_callback(
            "nornicdb_wal", lambda: self.db.wal_stats(),
            help_="Write-ahead-log health counters",
            counters={
                "nornicdb_wal_entries", "nornicdb_wal_bytes_written",
                "nornicdb_wal_snapshots", "nornicdb_wal_recovered_entries",
                "nornicdb_wal_truncated_tail_records",
            },
        )
        reg.stats_callback(
            "nornicdb_adjacency", lambda: self.db.adjacency_stats(),
            help_="CSR adjacency snapshot counters",
            rename={
                "nornicdb_adjacency_builds": "nornicdb_adjacency_builds_total",
                "nornicdb_adjacency_delta_merges":
                    "nornicdb_adjacency_delta_merges_total",
                "nornicdb_adjacency_merged_edges":
                    "nornicdb_adjacency_merged_edges_total",
                "nornicdb_adjacency_epoch_retries":
                    "nornicdb_adjacency_epoch_retries_total",
            },
            counters={
                "nornicdb_adjacency_builds",
                "nornicdb_adjacency_delta_merges",
                "nornicdb_adjacency_merged_edges",
                "nornicdb_adjacency_epoch_retries",
            },
        )

        def _heimdall_families() -> list:
            # heimdall named metrics when the assistant has been used
            # (ref: pkg/heimdall/metrics.go Prometheus rendering)
            mgr = self.db._heimdall
            if mgr is None:
                return []
            return mgr.metrics_registry.prometheus_families()

        reg.families_callback("heimdall", _heimdall_families)

    ROUTE_FAMILIES = (
        ("/db/", "tx_commit"),
        ("/nornicdb/", "nornicdb"),
        ("/admin/", "admin"),
        ("/auth/", "auth"),
        ("/collections", "qdrant"),
        ("/api/bifrost", "bifrost"),
        ("/v1/", "openai"),
        ("/gdpr/", "gdpr"),
    )

    @classmethod
    def _route_label(cls, path: str) -> str:
        """Bounded-cardinality route family for metric labels."""
        if path in ("/metrics", "/health", "/status", "/mcp", "/graphql"):
            return path.lstrip("/")
        for prefix, label in cls.ROUTE_FAMILIES:
            if path.startswith(prefix):
                return label
        return "other"

    # -- POST routes ---------------------------------------------------------------
    def _route_post(self, h) -> None:
        path = h.path.split("?")[0]
        m = re.fullmatch(r"/db/([^/]+)/tx/commit", path)
        if m:
            body = h._body()
            # permission is per-statement: read-only queries work for viewers
            perm = "read"
            for stmt in body.get("statements", []):
                if classify_query_text(stmt.get("statement", "")) == "write":
                    perm = "write"
                    break
            h._auth(perm)
            self._tx_commit(h, m.group(1), body)
            return
        if path == "/nornicdb/search":
            h._auth("read")
            raw = h._raw_body()
            # hot-path response byte cache: generation-invalidated (any
            # index mutation kills it) + short TTL so decay/access-count
            # drift is bounded to TTL seconds (the rank layer underneath
            # already caches for 30s; ref: pkg/cache LRU+TTL query cache)
            cache = self.response_cache
            cached = cache.get((path, raw))
            if cached is not None:
                h._send_raw(200, cached)
                return
            # snapshot BEFORE searching: a mutation racing the search
            # must make this entry dead on arrival
            gen_before = cache.generation()
            body = self._parse_body(raw)
            vector = body.get("vector")
            if vector:
                # raw-vector search (the gRPC SearchRequest.vector shape on
                # the REST surface): the worker-servable hot path — prefork
                # workers answer it through the device broker and fall back
                # to the shared-memory host scan, bit-identical ids/scores
                # to this in-process path
                from nornicdb_tpu.errors import NotFoundError

                hits = self.db.search.vector_candidates(
                    np.asarray(vector, np.float32),
                    k=int(body.get("limit", 10)),
                    min_similarity=float(body.get("min_score", -1.0)),
                )
                # include_content=false skips the per-hit node fetch —
                # the knob high-qps clients use when ids/scores suffice
                enrich = bool(body.get("include_content", True))
                out = []
                for nid, score in hits:
                    content = ""
                    if enrich:
                        try:
                            node = self.db.storage.get_node(nid)
                            content = node.properties.get("content", "")
                        except NotFoundError:
                            pass  # hit evicted between search and fetch
                    out.append(
                        {"id": nid, "score": score, "content": content}
                    )
                payload = json.dumps({"results": out}).encode()
                cache.put((path, raw), payload, gen_before)
                h._send_raw(200, payload)
                return
            results = self.db.search.search(
                body.get("query", ""), limit=int(body.get("limit", 10))
            )
            payload = json.dumps(
                {
                    "results": [
                        {
                            "id": r["id"],
                            "score": r["score"],
                            "content": r["content"],
                            "labels": r["labels"],
                            "properties": _jsonable(r["node"].properties),
                        }
                        for r in results
                    ]
                }
            ).encode()
            cache.put((path, raw), payload, gen_before)
            h._send_raw(200, payload)
            return
        if path == "/nornicdb/similar":
            h._auth("read")
            body = h._body()
            node = self.db.storage.get_node(body["id"])
            if node.embedding is None:
                h._send(200, {"results": []})
                return
            hits = self.db.search.vector_candidates(
                node.embedding, k=int(body.get("limit", 10)) + 1
            )
            h._send(
                200,
                {
                    "results": [
                        {"id": i, "score": s}
                        for i, s in hits
                        if i != node.id
                    ][: int(body.get("limit", 10))]
                },
            )
            return
        if path == "/nornicdb/embed":
            h._auth("write")
            body = h._body()
            if self.db.embedder is None:
                h._send(503, {"error": "no embedder configured"})
                return
            vec = self.db.embedder.embed(body.get("text", ""))
            h._send(200, {"embedding": _jsonable(vec), "dimensions": len(vec)})
            return
        if path == "/nornicdb/rag/answer":
            # GraphRAG: graph-context retrieval -> packed prompt ->
            # generation through the genserve engine (docs/generation.md).
            # A shed generation surfaces as 429 via the ResourceExhausted
            # handler in _dispatch, like every serving admission edge.
            h._auth("read")
            body = h._body()
            question = str(body.get("question", body.get("query", "")))
            if not question.strip():
                h._send(400, {"error": "question required"})
                return
            svc = self.db.graphrag()
            result = svc.answer(
                question,
                limit=body.get("limit"),
                max_new_tokens=body.get("max_tokens"),
                deadline_ms=body.get("deadline_ms"),
            )
            h._send(200, result)
            return
        if path == "/nornicdb/search/rebuild":
            h._auth("admin")
            n = self.db.search.build_indexes()
            h._send(200, {"indexed": n})
            return
        if path == "/admin/profile":
            # on-demand device profiler: single-flight jax.profiler
            # capture over ?seconds=N, returned as a downloadable
            # .tar.gz artifact (telemetry/deviceprof.py; playbook in
            # docs/observability.md "Device-time & HBM profiler")
            h._auth("admin")
            from urllib.parse import parse_qs, urlparse

            import nornicdb_tpu.telemetry as _telemetry

            qs = parse_qs(urlparse(h.path).query)
            try:
                seconds = float((qs.get("seconds") or ["1.0"])[0])
            except ValueError:
                h._send(400, {"error": "seconds must be a number"})
                return
            try:
                artifact = _deviceprof.capture_profile(
                    seconds, max_seconds=_telemetry.profile_max_s)
            except _deviceprof.ProfileBusy as e:
                h._send(409, {"error": str(e)})
                return
            except Exception as e:
                log.exception("profile capture failed")
                h._send(503, {"error": f"profile capture failed: {e}"})
                return
            h._send_raw(
                200, artifact, content_type="application/gzip",
                extra_headers={
                    "Content-Disposition":
                        'attachment; filename="nornicdb-profile.tar.gz"',
                },
            )
            return
        if path == "/admin/backup":
            # (ref: server_router.go /admin/backup -> badger_backup.go)
            h._auth("admin")
            body = h._body()
            dest = self.db.backup(body.get("path") or None)
            h._send(200, {"file": dest})
            return
        if path == "/admin/restore":
            h._auth("admin")
            body = h._body()
            src = body.get("path", "")
            if not src or not os.path.exists(src):
                h._send(400, {"error": f"backup file not found: {src!r}"})
                return
            counts = self.db.restore(src)
            h._send(200, counts)
            return
        if path == "/auth/login":
            body = h._body()
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            token = self.authenticator.authenticate(
                body.get("username", ""), body.get("password", "")
            )
            h._send(200, {"token": token})
            return
        if path == "/auth/token":
            # browser login: JWT in body + HttpOnly session cookie
            # (ref: server_auth.go:19 handleToken)
            body = h._body()
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            grant = body.get("grant_type", "")
            if grant and grant != "password":
                h._send(400, {"error": "unsupported grant_type"})
                return
            token = self.authenticator.authenticate(
                body.get("username", ""), body.get("password", "")
            )
            h._send(
                200,
                {
                    "access_token": token,
                    "token_type": "Bearer",
                    "expires_in": int(self.authenticator.config.token_ttl),
                },
                extra_headers={
                    # Max-Age tracks the JWT TTL (a longer-lived cookie would
                    # just carry an expired bearer token); Secure when the
                    # deployment terminates TLS (NORNICDB_COOKIE_SECURE=1 or
                    # cookie_secure=True)
                    "Set-Cookie": (
                        f"nornicdb_token={token}; Path=/; HttpOnly; "
                        f"SameSite=Lax; "
                        f"Max-Age={int(self.authenticator.config.token_ttl)}"
                        + ("; Secure" if self.cookie_secure else "")
                    )
                },
            )
            return
        if path == "/auth/password":
            # change own password, old password re-verified
            # (ref: server_auth.go handleChangePassword, PermRead-gated)
            payload = h._auth("read")
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            body = h._body()
            username = payload["sub"]
            if not self.authenticator.verify_current_password(
                username, body.get("old_password", "")
            ):
                h._send(401, {"error": "current password incorrect"})
                return
            new = body.get("new_password", "")
            if len(new) < 4:
                h._send(400, {"error": "new password too short"})
                return
            self.authenticator.set_password(username, new)
            h._send(200, {"status": "password changed"})
            return
        if path == "/auth/api-token":
            # admin-only stateless API token with a subject label, for MCP
            # servers etc. (ref: server_auth.go handleGenerateAPIToken)
            payload = h._auth("admin")
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            body = h._body()
            subject = body.get("subject") or "api-token"
            ttl = float(body.get("expires_in") or 365 * 86400)
            token = self.authenticator.issue_token(
                subject, payload.get("role", "admin"), ttl=ttl
            )
            h._send(
                200,
                {
                    "token": token,
                    "subject": subject,
                    "expires_in": int(ttl),
                    "token_type": "Bearer",
                },
            )
            return
        if path == "/auth/users":
            # create user (ref: server_auth.go:549 handleUsers POST)
            h._auth("user_manage")
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            body = h._body()
            roles = body.get("roles") or [body.get("role", "viewer")]
            try:
                u = self.authenticator.create_user(
                    body.get("username", ""), body.get("password", ""), roles[0]
                )
            except AuthError as e:
                h._send(400, {"error": str(e)})
                return
            h._send(
                201,
                {
                    "username": u.username,
                    "roles": [u.role],
                    "created_at": u.created_at,
                },
            )
            return
        if path == "/gdpr/export":
            # GDPR data export (ref: server_router.go /gdpr/export)
            h._auth("read")
            body = h._body()
            subject = body.get("subject", "")
            if not subject:
                h._send(400, {"error": "subject required"})
                return
            h._send(200, {"subject": subject,
                          "records": _jsonable(self._retention().export_subject(subject))})
            return
        if path == "/gdpr/delete":
            # GDPR erasure: request -> approve -> execute in one call when
            # confirm=true (ref: /gdpr/delete + pkg/retention workflow)
            h._auth("delete")
            body = h._body()
            subject = body.get("subject", "")
            if not subject:
                h._send(400, {"error": "subject required"})
                return
            mgr = self._retention()
            req = mgr.request_erasure(subject)
            if not body.get("confirm", False):
                h._send(202, {"request_id": req.id, "status": req.status,
                              "note": "re-POST with confirm=true to execute"})
                return
            mgr.approve_erasure(req.id)
            done = mgr.execute_erasure(req.id)
            h._send(200, {"request_id": done.id, "status": done.status,
                          "erased": done.erased_count})
            return
        if path == "/auth/oauth/token":
            # OAuth2 token endpoint (ref: pkg/auth/oauth.go; cmd/oauth-provider):
            # password and client_credentials grants map onto the JWT issuer
            body = h._body()
            if self.authenticator is None:
                h._send(503, {"error": "auth not configured"})
                return
            grant = body.get("grant_type", "")
            if grant == "authorization_code":
                code = body.get("code", "")
                expiry = self._oauth_codes.pop(code, 0.0)
                if expiry < time.time():
                    h._send(400, {"error": "invalid_grant"})
                    return
                token = self.authenticator.authenticate(
                    body.get("username", body.get("client_id", "")),
                    body.get("password", body.get("client_secret", "")),
                )
            elif grant == "password":
                token = self.authenticator.authenticate(
                    body.get("username", ""), body.get("password", "")
                )
            elif grant == "client_credentials":
                token = self.authenticator.authenticate(
                    body.get("client_id", ""), body.get("client_secret", "")
                )
            else:
                h._send(400, {"error": "unsupported_grant_type"})
                return
            h._send(
                200,
                {
                    "access_token": token,
                    "token_type": "Bearer",
                    "expires_in": int(self.authenticator.config.token_ttl),
                },
            )
            return
        if path == "/auth/logout":
            body = h._body()
            if self.authenticator is not None:
                token = body.get("token", "") or h._cookie_token()
                self.authenticator.logout(token)
            # clear the browser session cookie (ref: handleLogout MaxAge=-1)
            h._send(
                200,
                {"ok": True},
                extra_headers={
                    "Set-Cookie": "nornicdb_token=; Path=/; HttpOnly; Max-Age=0"
                },
            )
            return
        if path == "/mcp":
            h._auth("write")
            h._send(200, self._mcp(h._body()))
            return
        if path == "/graphql":
            # (ref: pkg/graphql mounted at /graphql, handler.go)
            h._auth("read")  # gate before touching the body
            body = h._body()
            q = body.get("query", "")
            from nornicdb_tpu.server.graphql import GraphQLExecutor, parse_operation

            if parse_operation(q) == "mutation":
                h._auth("write")
            h._send(200, _jsonable(
                GraphQLExecutor(self.db).execute(q, body.get("variables"))
            ))
            return
        if path in ("/api/bifrost/chat/completions", "/v1/chat/completions"):
            # (ref: server_router.go:215 -> heimdall handler.go:207)
            h._auth("read")
            body = h._body()
            messages = body.get("messages", [])
            max_tokens = int(body.get("max_tokens", 128))
            model = body.get("model") or None
            if body.get("stream"):
                # SSE streaming (ref: handler.go:561 streaming responses)
                h.send_response(200)
                h.send_header("Content-Type", "text/event-stream")
                h.send_header("Cache-Control", "no-cache")
                h.send_header("Connection", "close")
                h.end_headers()
                try:
                    for chunk in self.db.heimdall.chat_stream(
                        messages, max_tokens, model=model
                    ):
                        h.wfile.write(
                            b"data: " + json.dumps(chunk).encode() + b"\n\n"
                        )
                    h.wfile.write(b"data: [DONE]\n\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass
                h.close_connection = True
                return
            result = self.db.heimdall.chat(messages, max_tokens, model=model)
            # OpenAI-compatible: invalid_request_error -> 404/400 status
            h._send(404 if "error" in result else 200, result)
            return
        if path == "/admin/config":
            # runtime feature-flag updates (ref: handleAdminConfig POST —
            # the reference's runtime flag registry); static config stays
            # immutable at runtime
            h._auth("admin")
            from nornicdb_tpu.config import flags

            body = h._body()
            # only absent/null means "no updates": `or {}` would let falsy
            # non-dicts ([], false, 0) skip the shape check below
            updates = body.get("feature_flags")
            if updates is None:
                updates = {}
            if not isinstance(updates, dict):
                h._send(400, {"error": "feature_flags must be an object"})
                return
            unknown = [k for k in updates if k not in flags.all()]
            if unknown:
                h._send(400, {"error": f"unknown feature flags: {unknown}",
                              "valid": sorted(flags.all())})
                return
            # strict booleans only: bool("false") is True, so coercing
            # would silently ENABLE a flag the client meant to disable
            bad = [k for k, v in updates.items() if not isinstance(v, bool)]
            if bad:
                h._send(400, {"error":
                              f"feature flag values must be booleans: {bad}"})
                return
            for k, v in updates.items():
                flags.set(k, v)
            h._send(200, {"feature_flags": flags.all()})
            return
        h._send(404, {"error": f"not found: {path}"})

    def _route_user_by_name(self, h, method: str, path: str) -> None:
        """PUT (roles/disabled) and DELETE for /auth/users/{name}
        (ref: server_auth.go handleUserByID)."""
        h._auth("user_manage")
        if self.authenticator is None:
            h._send(503, {"error": "auth not configured"})
            return
        from urllib.parse import unquote

        from nornicdb_tpu.auth.auth import ROLE_PERMISSIONS

        name = unquote(path[len("/auth/users/"):])
        auth = self.authenticator
        if method == "DELETE":
            try:
                auth.delete_user(name)
            except AuthError:
                h._send(404, {"error": f"user {name} not found"})
                return
            h._send(200, {"status": "deleted"})
            return
        if method == "PUT":
            body = h._body()
            roles = body.get("roles") or (
                [body["role"]] if body.get("role") else []
            )
            # validation errors are 400; a missing user is 404
            if roles and roles[0] not in ROLE_PERMISSIONS:
                h._send(400, {"error": f"unknown role {roles[0]}"})
                return
            try:
                auth.get_user(name)  # existence check up front, atomically-ish
                if roles:
                    auth.set_role(name, roles[0])
                if body.get("disabled") is not None:
                    auth.set_disabled(name, bool(body["disabled"]))
            except AuthError as e:
                h._send(404, {"error": str(e)})
                return
            h._send(200, {"status": "updated"})
            return
        h._send(405, {"error": f"{method} not allowed on {path}"})

    def _tx_commit(self, h, database: str, body: dict) -> None:
        """Neo4j HTTP transaction API (ref: server_db.go).

        The whole statement batch is ONE implicit transaction (Neo4j
        semantics): a failing statement rolls back every earlier statement's
        writes. Single-statement bodies run on the shared per-database
        executor WITHOUT tx framing — statement-level undo already makes one
        statement atomic, and the framing measured ~3.5x request cost. For
        multi-statement bodies a FRESH session executor scopes the tx to
        this request; opening a BEGIN frame on the shared executor would
        entangle tx state across handler threads."""
        out_results = []
        errors = []
        statements = body.get("statements", [])
        if len(statements) <= 1:
            # single statement: statement-level atomicity (undo frames)
            # already gives the one-transaction semantics — skip the
            # session + BEGIN/COMMIT framing (measured ~3.5x request cost)
            self._tx_run_statements(
                self.db.executor_for(database), body, out_results, errors)
            h._send(200, {"results": out_results, "errors": errors})
            return
        ex = self.db.session_executor(database)
        ex.execute("BEGIN", {})
        finished = False
        try:
            self._tx_run_statements(ex, body, out_results, errors)
            finished = True
        finally:
            if not finished:
                # an unexpected exception escaped the statement loop (e.g.
                # a non-dict statements entry): the tx must not be left
                # half-applied with its undo log garbage-collected
                try:
                    ex.execute("ROLLBACK", {})
                except Exception:
                    log.warning("post-failure rollback failed", exc_info=True)
        try:
            ex.execute("ROLLBACK" if errors else "COMMIT", {})
        except Exception as e:  # a failed commit voids the batch's results
            errors.append({
                "code": "Neo.DatabaseError.Transaction.TransactionCommitFailed",
                "message": str(e),
            })
            out_results = []
        h._send(200, {"results": out_results, "errors": errors})

    def _tx_run_statements(self, ex, body: dict, out_results: list,
                           errors: list) -> None:
        for stmt in body.get("statements", []):
            if not isinstance(stmt, dict):
                errors.append({
                    "code": "Neo.ClientError.Request.InvalidFormat",
                    "message": "each statements entry must be an object",
                })
                return
            query = stmt.get("statement", "")
            params = stmt.get("parameters", {})
            # User-issued tx control is still rejected: the batch already
            # runs in a transaction, and a client COMMIT would detach the
            # rollback-on-error contract. Gate on the parsed AST, not
            # string prefixes ("BEGIN;", "/* c */ BEGIN" must not slip
            # through; parse() is memoized so this stays a cache hit).
            try:
                if isinstance(cypher_parse(query), cypher_ast.TxCommand):
                    errors.append({
                        "code": "Neo.ClientError.Transaction.Invalid",
                        "message": "explicit transaction control is not "
                                   "available on the stateless tx endpoint",
                    })
                    return
            except Exception:  # nornlint: disable=NL-ERR02
                pass  # unparseable: fall through, execute() reports it
            t0 = time.perf_counter()
            try:
                result = ex.execute(query, params)
            except Exception as e:
                errors.append(
                    {"code": "Neo.ClientError.Statement.SyntaxError", "message": str(e)}
                )
                return
            if time.perf_counter() - t0 > self.slow_threshold:
                self.slow_queries += 1
            out_results.append(
                {
                    "columns": result.columns,
                    "data": [
                        {"row": [_jsonable(v) for v in row], "meta": []}
                        for row in result.rows
                    ],
                    "stats": result.stats.as_dict(),
                }
            )

    # -- MCP (ref: pkg/mcp/tools.go:63-332 — 6 tools) -----------------------------
    MCP_TOOLS = [
        {
            "name": "store",
            "description": "Store a memory in the knowledge graph",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "content": {"type": "string"},
                    "labels": {"type": "array", "items": {"type": "string"}},
                },
                "required": ["content"],
            },
        },
        {
            "name": "recall",
            "description": "Search memories by meaning",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "query": {"type": "string"},
                    "limit": {"type": "integer"},
                },
                "required": ["query"],
            },
        },
        {
            "name": "discover",
            "description": "Find related memories via graph neighborhood",
            "inputSchema": {
                "type": "object",
                "properties": {"id": {"type": "string"}, "depth": {"type": "integer"}},
                "required": ["id"],
            },
        },
        {
            "name": "link",
            "description": "Create a relationship between two memories",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "from": {"type": "string"},
                    "to": {"type": "string"},
                    "type": {"type": "string"},
                },
                "required": ["from", "to"],
            },
        },
        {
            "name": "task",
            "description": "Create a task node",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "title": {"type": "string"},
                    "status": {"type": "string"},
                },
                "required": ["title"],
            },
        },
        {
            "name": "tasks",
            "description": "List task nodes",
            "inputSchema": {
                "type": "object",
                "properties": {"status": {"type": "string"}},
            },
        },
    ]

    def _mcp(self, req: dict) -> dict:
        """JSON-RPC 2.0 dispatcher (ref: pkg/mcp/server.go)."""
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params", {}) or {}

        def ok(result):
            return {"jsonrpc": "2.0", "id": rid, "result": result}

        def err(code, msg):
            return {"jsonrpc": "2.0", "id": rid, "error": {"code": code, "message": msg}}

        if method == "initialize":
            return ok(
                {
                    "protocolVersion": "2024-11-05",
                    "serverInfo": {"name": "nornicdb-tpu", "version": "1.0.0"},
                    "capabilities": {"tools": {}},
                }
            )
        if method == "tools/list":
            return ok({"tools": self.MCP_TOOLS})
        if method == "tools/call":
            name = params.get("name", "")
            args = params.get("arguments", {}) or {}
            try:
                result = self._mcp_tool(name, args)
            except Exception as e:
                return err(-32000, str(e))
            return ok(
                {"content": [{"type": "text", "text": json.dumps(_jsonable(result))}]}
            )
        return err(-32601, f"unknown method {method}")

    def _mcp_tool(self, name: str, args: dict) -> Any:
        db = self.db
        if name == "store":
            node = db.store(args["content"], labels=args.get("labels"))
            return {"id": node.id}
        if name == "recall":
            results = db.recall(args["query"], limit=int(args.get("limit", 5)))
            return [
                {"id": r["id"], "content": r["content"], "score": r["score"]}
                for r in results
            ]
        if name == "discover":
            nodes = db.neighbors(args["id"], depth=int(args.get("depth", 1)))
            return [
                {"id": n.id, "content": n.properties.get("content", "")}
                for n in nodes
            ]
        if name == "link":
            edge = db.link(args["from"], args["to"], args.get("type", "RELATED_TO"))
            return {"id": edge.id, "type": edge.type}
        if name == "task":
            node = db.store(
                args["title"],
                labels=["Task"],
                properties={
                    "title": args["title"],
                    "status": args.get("status", "open"),
                },
            )
            return {"id": node.id}
        if name == "tasks":
            status = args.get("status")
            tasks = db.storage.get_nodes_by_label("Task")
            return [
                {
                    "id": t.id,
                    "title": t.properties.get("title", ""),
                    "status": t.properties.get("status", ""),
                }
                for t in tasks
                if status is None or t.properties.get("status") == status
            ]
        raise NornicError(f"unknown tool {name}")

    # -- lifecycle --------------------------------------------------------------------
    def start(self) -> None:
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), self._make_handler()
        )
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="http-server"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
