"""Bolt protocol server (Neo4j drivers connect here).

Behavioral reference: /root/reference/pkg/bolt/server.go —
handshake magic 0x6060B017 (:874), version negotiation 4.0-4.4 (:139-144),
messages HELLO/GOODBYE/RESET/RUN/DISCARD/PULL/BEGIN/COMMIT/ROLLBACK/ROUTE
(:148-165), per-session state machine with result streaming (:745-815),
chunked message framing, injected QueryExecutor (:249), auth adapter.

Implementation: asyncio TCP server; each session holds buffered results
streamed on PULL (qid-less, single-query-at-a-time like Bolt 4 autocommit).
"""

from __future__ import annotations

import asyncio
import logging
import struct
import threading
import time
from typing import Any, Optional

from nornicdb_tpu.cypher.executor import classify_query_text
from nornicdb_tpu.errors import AuthError
from nornicdb_tpu.server.packstream import Structure, pack, to_wire, unpack
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

_BOLT_HIST = _REGISTRY.histogram(
    "nornicdb_bolt_request_seconds",
    "Bolt RUN latency (query execution, excluding PULL streaming)",
)

MAGIC = b"\x60\x60\xb0\x17"

# message tags (ref: server.go:148-165)
MSG_HELLO = 0x01
MSG_GOODBYE = 0x02
MSG_RESET = 0x0F
MSG_RUN = 0x10
MSG_BEGIN = 0x11
MSG_COMMIT = 0x12
MSG_ROLLBACK = 0x13
MSG_DISCARD = 0x2F
MSG_PULL = 0x3F
MSG_ROUTE = 0x66
MSG_LOGON = 0x6A
MSG_LOGOFF = 0x6B
MSG_TELEMETRY = 0x54
MSG_SUCCESS = 0x70
MSG_RECORD = 0x71
MSG_IGNORED = 0x7E
MSG_FAILURE = 0x7F

# Bolt 5.x first (modern drivers; LOGON flow + element-id structs),
# 4.x fallback (ref: server.go:139-144 negotiates 4.0-4.4)
SUPPORTED_VERSIONS = [
    (5, 4), (5, 3), (5, 2), (5, 1), (5, 0),
    (4, 4), (4, 3), (4, 2), (4, 1),
]


def _is_tx_keyword(query: str) -> bool:
    return query.strip().upper() in ("BEGIN", "COMMIT", "ROLLBACK")


class BoltSession:
    """Per-connection state machine (ref: Session server.go:815)."""

    def __init__(self, server: "BoltServer", conn_no: int = 0):
        self.server = server
        self.conn_no = conn_no
        self.authenticated = not server.auth_required
        # RBAC: role resolved at HELLO/LOGON, enforced per-RUN with the same
        # AST-based write classification as the HTTP tx endpoint (ref: Bolt
        # auth adapter auth_adapter.go + permission model auth.go:171-176).
        # No authenticator / auth disabled => full access.
        self.role = "admin" if self.authenticated else "none"
        self.ready = False
        self.streaming: Optional[dict] = None  # {columns, rows, pos, stats}
        self.in_tx = False
        self.failed = False
        self.database: Optional[str] = None
        # explicit transactions are session-scoped: two connections doing
        # BEGIN must not share one executor's tx state
        self._session_executor = None

    def handle(self, tag: int, fields: list[Any]) -> list[tuple[int, Any]]:
        """Process one message, return response messages [(tag, metadata)]."""
        try:
            if tag == MSG_HELLO:
                return self._hello(fields)
            if tag == MSG_LOGON:
                return self._logon(fields)
            if tag == MSG_LOGOFF:
                self.authenticated = not self.server.auth_required
                self.role = "admin" if self.authenticated else "none"
                return [(MSG_SUCCESS, {})]
            if tag == MSG_TELEMETRY:
                return [(MSG_SUCCESS, {})]  # 5.4 drivers emit api telemetry
            if tag == MSG_RESET:
                self.abort_tx()  # RESET mid-tx must ROLLBACK, not leak it
                self.streaming = None
                self.failed = False
                return [(MSG_SUCCESS, {})]
            if tag == MSG_GOODBYE:
                return []
            if self.failed and tag not in (MSG_RESET,):
                return [(MSG_IGNORED, {})]
            if not self.authenticated:
                self.failed = True
                return [
                    (
                        MSG_FAILURE,
                        {
                            "code": "Neo.ClientError.Security.Unauthorized",
                            "message": "authentication required",
                        },
                    )
                ]
            if tag == MSG_RUN:
                return self._run(fields)
            if tag == MSG_PULL:
                return self._pull(fields)
            if tag == MSG_DISCARD:
                self.streaming = None
                return [(MSG_SUCCESS, {"has_more": False})]
            if tag == MSG_BEGIN:
                self._execute("BEGIN", {})
                self.in_tx = True
                return [(MSG_SUCCESS, {})]
            if tag == MSG_COMMIT:
                self._execute("COMMIT", {})
                self.in_tx = False
                return [(MSG_SUCCESS, {})]
            if tag == MSG_ROLLBACK:
                self._execute("ROLLBACK", {})
                self.in_tx = False
                return [(MSG_SUCCESS, {})]
            if tag == MSG_ROUTE:
                return self._route(fields)
            self.failed = True
            return [
                (
                    MSG_FAILURE,
                    {
                        "code": "Neo.ClientError.Request.Invalid",
                        "message": f"unknown message 0x{tag:02X}",
                    },
                )
            ]
        except Exception as e:  # surface executor errors as FAILURE
            self.failed = True
            code = "Neo.ClientError.Statement.SyntaxError"
            name = type(e).__name__
            if "NotFound" in name:
                code = "Neo.ClientError.Statement.EntityNotFound"
            elif "Constraint" in name:
                code = "Neo.ClientError.Schema.ConstraintValidationFailed"
            elif "Auth" in name:
                code = "Neo.ClientError.Security.Unauthorized"
            elif "Durability" in name:
                # a WAL append failed durability (disk error / ENOSPC /
                # injected storage fault): nothing was acked; transient so
                # drivers back off and retry once the disk recovers
                code = "Neo.TransientError.General.DatabaseUnavailable"
            elif "ResourceExhausted" in name:
                # serving admission control shed work under this statement
                # (embed/search queue full or deadline): a TRANSIENT code,
                # so neo4j drivers retry with backoff instead of failing
                # the transaction permanently
                code = "Neo.TransientError.Request.ResourceExhausted"
            return [(MSG_FAILURE, {"code": code, "message": str(e)})]

    def _hello(self, fields: list[Any]) -> list[tuple[int, Any]]:
        meta = fields[0] if fields else {}
        if self.server.auth_required:
            self._try_auth(meta)
        else:
            self.authenticated = True
        if not self.authenticated and "credentials" in (meta or {}):
            self.failed = True
            return [
                (
                    MSG_FAILURE,
                    {
                        "code": "Neo.ClientError.Security.Unauthorized",
                        "message": "invalid credentials",
                    },
                )
            ]
        self.ready = True
        return [
            (
                MSG_SUCCESS,
                {
                    "server": f"NornicDB-TPU/{self.server.version}",
                    # monotonic accept counter, not id() and not the
                    # active-connection gauge (which decrements and would
                    # reuse ids): deterministic for the transcribed wire
                    # fixtures and collision-free for log correlation
                    "connection_id": f"bolt-{self.conn_no}",
                },
            )
        ]

    def _logon(self, fields: list[Any]) -> list[tuple[int, Any]]:
        meta = fields[0] if fields else {}
        self._try_auth(meta)
        if not self.authenticated:
            self.failed = True
            return [
                (
                    MSG_FAILURE,
                    {
                        "code": "Neo.ClientError.Security.Unauthorized",
                        "message": "invalid credentials",
                    },
                )
            ]
        return [(MSG_SUCCESS, {})]

    def _try_auth(self, meta: dict) -> None:
        if self.server.authenticator is None:
            self.authenticated = True
            self.role = "admin"
            return
        scheme = (meta or {}).get("scheme", "none")
        if scheme == "basic":
            user = meta.get("principal", "")
            pw = meta.get("credentials", "")
            self.authenticated = self.server.authenticator.check_password(user, pw)
            if self.authenticated:
                try:
                    self.role = self.server.authenticator.get_user(user).role
                except AuthError:
                    self.role = "none"
        elif scheme == "bearer":
            token = meta.get("credentials", "")
            payload = self.server.authenticator.validate_token(token)
            self.authenticated = payload is not None
            if payload is not None:
                self.role = payload.get("role", "none")
        else:
            self.authenticated = not self.server.auth_required
            self.role = "admin" if self.authenticated else "none"
        if not self.authenticated:
            self.role = "none"

    def abort_tx(self) -> None:
        """Roll back an open explicit transaction (RESET / disconnect).

        Without this, a client that BEGINs and vanishes leaves the engine's
        tx id set forever — which, among other things, permanently defers
        WAL auto-compaction (wal.py compact() skips while a tx is open)."""
        if not self.in_tx:
            return
        self.in_tx = False
        try:
            self._execute("ROLLBACK", {})
        except Exception:
            log.warning("implicit rollback failed", exc_info=True)

    def _execute(self, query: str, params: dict):
        factory = self.server.session_executor_factory
        if factory is not None and (self.in_tx or _is_tx_keyword(query)):
            # route tx-scoped statements through this session's own executor
            if self._session_executor is None:
                self._session_executor = factory(self.database)
            return self._session_executor.execute(query, params)
        return self.server.executor_fn(query, params, self.database)

    def _run(self, fields: list[Any]) -> list[tuple[int, Any]]:
        query = fields[0] if fields else ""
        params = fields[1] if len(fields) > 1 else {}
        extra = fields[2] if len(fields) > 2 else {}
        if isinstance(extra, dict) and extra.get("db"):
            self.database = extra["db"]
        if self.server.authenticator is not None and not _is_tx_keyword(query):
            perm = classify_query_text(query)
            if not self.server.authenticator.has_permission(self.role, perm):
                raise AuthError(
                    f"permission {perm} denied for role {self.role}"
                )
        # Bolt ingress root span: drivers may hand a W3C traceparent via
        # the RUN extra's tx_metadata (no header channel on Bolt); the
        # executor / storage / device spans below nest under this root
        meta = extra.get("tx_metadata") if isinstance(extra, dict) else None
        traceparent = (
            meta.get("traceparent") if isinstance(meta, dict) else None
        )
        t0 = time.perf_counter()
        with _tracer.start_trace("bolt.run", traceparent=traceparent) as root:
            if root.trace_id is not None:
                root.set_attr("db", self.database or "neo4j")
            result = self._execute(query, params or {})
        _BOLT_HIST.observe(time.perf_counter() - t0)
        self.streaming = {
            "columns": result.columns,
            "rows": result.rows,
            "pos": 0,
            "stats": result.stats.as_dict(),
        }
        return [(MSG_SUCCESS, {"fields": result.columns, "t_first": 0})]

    def _pull(self, fields: list[Any]) -> list[tuple[int, Any]]:
        meta = fields[0] if fields else {}
        n = int(meta.get("n", -1)) if isinstance(meta, dict) else -1
        out: list[tuple[int, Any]] = []
        if self.streaming is None:
            return [(MSG_SUCCESS, {"has_more": False})]
        rows = self.streaming["rows"]
        pos = self.streaming["pos"]
        end = len(rows) if n < 0 else min(pos + n, len(rows))
        for i in range(pos, end):
            out.append((MSG_RECORD, [to_wire(v) for v in rows[i]]))
        self.streaming["pos"] = end
        if end >= len(rows):
            summary = {
                "type": "rw",
                "t_last": 0,
                "db": self.database or "neo4j",
            }
            stats = self.streaming["stats"]
            if stats:
                summary["stats"] = stats
            self.streaming = None
            out.append((MSG_SUCCESS, summary))
        else:
            out.append((MSG_SUCCESS, {"has_more": True}))
        return out

    def _route(self, fields: list[Any]) -> list[tuple[int, Any]]:
        # single-instance routing table (ref: handleRoute)
        host = f"{self.server.host}:{self.server.port}"
        table = {
            "rt": {
                "ttl": 300,
                "db": self.database or "neo4j",
                "servers": [
                    {"addresses": [host], "role": role}
                    for role in ("WRITE", "READ", "ROUTE")
                ],
            }
        }
        return [(MSG_SUCCESS, table)]


class BoltServer:
    """(ref: bolt.Server server.go:191)"""

    version = "1.0.0"

    def __init__(
        self,
        executor_fn,
        host: str = "127.0.0.1",
        port: int = 7687,
        authenticator=None,
        auth_required: bool = False,
        session_executor_factory=None,
    ):
        """executor_fn(query, params, database) -> cypher Result
        (ref: QueryExecutor interface server.go:249).
        session_executor_factory(database) -> executor, used to give each
        connection its own transaction scope (BEGIN/COMMIT isolation)."""
        self.executor_fn = executor_fn
        self.session_executor_factory = session_executor_factory
        self.host = host
        self.port = port
        self.authenticator = authenticator
        self.auth_required = auth_required
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.connections = 0  # active-connection gauge (dec on close)
        self._conn_seq = 0    # monotonic accept counter (never reused)

    # -- wire helpers --------------------------------------------------------
    @staticmethod
    def _chunk(payload: bytes) -> bytes:
        """Chunked framing: [len u16][data]... [0x0000]."""
        out = bytearray()
        for i in range(0, len(payload), 0xFFFF):
            part = payload[i : i + 0xFFFF]
            out += struct.pack(">H", len(part))
            out += part
        out += b"\x00\x00"
        return bytes(out)

    async def _read_message(self, reader: asyncio.StreamReader) -> Optional[bytes]:
        chunks = bytearray()
        while True:
            header = await reader.readexactly(2)
            (size,) = struct.unpack(">H", header)
            if size == 0:
                if chunks:
                    return bytes(chunks)
                continue  # NOOP keepalive chunk
            chunks += await reader.readexactly(size)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self.connections += 1
        session = None
        try:
            # handshake (ref: server.go:867-898)
            magic = await reader.readexactly(4)
            if magic != MAGIC:
                writer.close()
                return
            proposals = await reader.readexactly(16)
            chosen = (0, 0)
            for i in range(4):
                # proposal bytes: [00, range, minor, major] — the client
                # supports (major, minor-range) .. (major, minor)
                rng = proposals[i * 4 + 1]
                minor, major = proposals[i * 4 + 2], proposals[i * 4 + 3]
                for v in SUPPORTED_VERSIONS:  # ordered best-first
                    if v[0] == major and (minor - rng) <= v[1] <= minor:
                        chosen = v  # always a version WE support
                        break
                if chosen != (0, 0):
                    break
            writer.write(bytes([0, 0, chosen[1], chosen[0]]))
            await writer.drain()
            if chosen == (0, 0):
                writer.close()
                return
            self._conn_seq += 1  # single-threaded: the server's event loop
            session = BoltSession(self, conn_no=self._conn_seq)
            while True:
                try:
                    raw = await self._read_message(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                if raw is None:
                    break
                msg = unpack(raw)
                if not isinstance(msg, Structure):
                    break
                responses = session.handle(msg.tag, msg.fields)
                if msg.tag == MSG_GOODBYE:
                    break
                # one transport write for the whole response stream: a
                # per-RECORD write costs a syscall + event-loop hop each
                # (profiled at ~40% of request wall time on a 19-record
                # stream; ref: the Go server's buffered writer batches the
                # same way, bolt/server.go WriteRecordNoFlush)
                buf = bytearray()
                for tag, meta in responses:
                    buf += self._chunk(pack(Structure(tag, [meta])))
                if buf:
                    # transports accept bytearray; buf is rebound next
                    # iteration, never mutated after the write
                    writer.write(buf)
                # drain() only matters for flow control; awaiting it per
                # message costs an event-loop round-trip per op (measured
                # ~2x op latency at RETURN-1 scale). Await only when the
                # transport's buffer actually backs up.
                if writer.transport.get_write_buffer_size() > 65536:
                    await writer.drain()
        except Exception:
            # client gone mid-conversation is routine; anything else in the
            # message loop should leave a trace before we drop the session
            log.debug("bolt session ended abnormally", exc_info=True)
        finally:
            self.connections -= 1
            if session is not None:
                try:
                    session.abort_tx()  # dropped connection mid-tx: roll back
                except Exception:
                    log.warning("abort_tx on dropped connection failed",
                                exc_info=True)
            try:
                writer.close()
            except OSError:
                pass  # socket already torn down

    # -- lifecycle ---------------------------------------------------------------
    async def _serve(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self._started.set()
        async with self._server:
            await self._server.serve_forever()

    def start(self) -> None:
        """Run the server on a background thread (blocking variant: serve())."""

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(self._serve())
            except asyncio.CancelledError:
                pass

        self._thread = threading.Thread(target=run, daemon=True, name="bolt-server")
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is not None:

            def _shutdown():
                if self._server is not None:
                    self._server.close()
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()

            self._loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(timeout=5)
