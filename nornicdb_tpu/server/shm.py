"""Generation-stamped shared-memory segments: the cross-process read plane.

N prefork workers (server/workers.py) must serve reads from ONE copy of the
primary's flat read-mostly state — the CSR adjacency snapshot and the search
corpus mirror — instead of N private rebuilds. This module is the transport:
a writer (the primary) publishes named numpy arrays plus a JSON meta block
as an mmap'd payload file per generation, and readers (worker subprocesses)
map the current payload read-only and remap when the generation moves.

Layout
------
``<prefix>.hdr`` — fixed 64-byte seqlock header, single writer:

    [0:8)   sequence (u64 LE; odd while a publish is in flight)
    [8:16)  generation (u64)
    [16:24) payload byte length (u64)
    [24:64) reserved

``<prefix>.g<generation>`` — the payload: ``magic | u32 json_len | json
directory | pad to 64 | raw array bytes``. The directory lists each array's
name/dtype/shape/offset plus the writer's ``meta`` dict. Payload files are
immutable once published: the writer creates ``.tmp`` then renames, updates
the header under the seqlock, and unlinks the PREVIOUS generation's file.
A reader that loses the race (header read → file already unlinked) simply
retries the header; a reader that already mapped an old generation keeps
its views alive through the open mapping (POSIX unlink semantics) until it
drops the snapshot — remapping is the reader's choice of WHEN, never a
correctness hazard mid-read.

The seqlock discipline is the same as workers.GenerationFile: mmap slice
assignment is a plain memcpy with no atomicity guarantee, so readers retry
while the sequence is odd or moved across the read. The bounded fallback
(writer died mid-publish) returns the last even snapshot seen or fails the
map — a worker then falls back to proxying, never serves torn state.
"""

from __future__ import annotations

import json
import logging
import mmap
import os
import struct
import threading
from typing import Any, Optional

import numpy as np

from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY

log = logging.getLogger(__name__)

_MAGIC = b"NSHM"
_HDR_SIZE = 64
_PAYLOAD_ALIGN = 64

# -- metrics (eager cells for the two shipped segments so the tested
#    observability catalog renders before first publish) --------------------
_PUBLISHES = _REGISTRY.counter(
    "nornicdb_shm_publishes_total",
    "Shared-memory segment generations published by the primary",
    labels=("segment",),
)
_REMAPS = _REGISTRY.counter(
    "nornicdb_shm_remaps_total",
    "Reader remaps onto a newer shared-segment generation",
    labels=("segment",),
)
_BYTES = _REGISTRY.gauge(
    "nornicdb_shm_bytes",
    "Payload bytes of the current shared-segment generation",
    labels=("segment",),
)
_GENERATION = _REGISTRY.gauge(
    "nornicdb_shm_generation",
    "Current published generation per shared segment",
    labels=("segment",),
)
for _seg in ("corpus", "adjacency"):
    _PUBLISHES.labels(_seg)
    _REMAPS.labels(_seg)
    _BYTES.labels(_seg)
    _GENERATION.labels(_seg)


class SegmentUnavailable(RuntimeError):
    """No published generation could be mapped (writer absent, mid-crash,
    or the prefix never existed). Readers fall back to their proxy path."""


def _encode_payload(arrays: dict[str, np.ndarray], meta: dict) -> bytes:
    """``magic | u32 json_len | u64 data_origin | json | pad | arrays``.
    Array offsets in the directory are relative to ``data_origin`` so the
    directory's own length never feeds back into the offsets."""
    directory = {"arrays": [], "meta": meta}
    blobs: list[bytes] = []
    rel = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        raw = a.tobytes()
        directory["arrays"].append({
            "name": name,
            "dtype": a.dtype.str,
            "shape": list(a.shape),
            "offset": rel,
            "nbytes": len(raw),
        })
        blobs.append(raw)
        rel = (rel + len(raw) + 7) & ~7  # 8-byte align every array
    dir_json = json.dumps(directory, separators=(",", ":")).encode()
    head_len = len(_MAGIC) + 4 + 8 + len(dir_json)
    origin = (head_len + _PAYLOAD_ALIGN - 1) // _PAYLOAD_ALIGN \
        * _PAYLOAD_ALIGN
    out = bytearray(
        _MAGIC + struct.pack("<IQ", len(dir_json), origin) + dir_json
    )
    out += b"\x00" * (origin - len(out))
    for entry, raw in zip(directory["arrays"], blobs):
        at = origin + entry["offset"]
        if len(out) < at:
            out += b"\x00" * (at - len(out))
        out += raw
    return bytes(out)


class SegmentSnapshot:
    """One mapped generation: read-only numpy views over the mmap plus the
    writer's meta dict. Holding the snapshot keeps the mapping (and thus
    every view) valid even after the writer publishes — and unlinks — newer
    generations."""

    __slots__ = ("generation", "arrays", "meta", "_mm", "_f")

    def __init__(self, generation: int, arrays: dict[str, np.ndarray],
                 meta: dict, mm: mmap.mmap, f):
        self.generation = generation
        self.arrays = arrays
        self.meta = meta
        self._mm = mm
        self._f = f

    def close(self) -> None:
        self.arrays = {}
        try:
            self._mm.close()
            self._f.close()
        except (OSError, ValueError):
            pass  # already closed


class SegmentWriter:
    """Single-writer publisher for one named segment."""

    def __init__(self, prefix: str, segment: str = "corpus"):
        self.prefix = prefix
        self.segment = segment
        self.generation = 0
        self._lock = threading.Lock()
        self._hdr_path = prefix + ".hdr"
        os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
        with open(self._hdr_path, "wb") as f:
            f.write(b"\x00" * _HDR_SIZE)
        self._hdr_f = open(self._hdr_path, "r+b")
        self._hdr = mmap.mmap(self._hdr_f.fileno(), _HDR_SIZE)
        self._seq = 0
        self._prev_path: Optional[str] = None
        self.publishes = 0
        self.payload_bytes = 0

    def _payload_path(self, gen: int) -> str:
        return f"{self.prefix}.g{gen}"

    def publish(self, arrays: dict[str, np.ndarray],
                meta: Optional[dict] = None) -> int:
        """Write a new generation and swing the header to it. Returns the
        published generation."""
        payload = _encode_payload(arrays, meta or {})
        with self._lock:
            gen = self.generation + 1
            path = self._payload_path(gen)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
            os.rename(tmp, path)
            self._seq += 1  # odd: publish in flight
            self._hdr[0:8] = struct.pack("<Q", self._seq & (2**64 - 1))
            self._hdr[8:16] = struct.pack("<Q", gen)
            self._hdr[16:24] = struct.pack("<Q", len(payload))
            self._seq += 1  # even: stable
            self._hdr[0:8] = struct.pack("<Q", self._seq & (2**64 - 1))
            self.generation = gen
            prev, self._prev_path = self._prev_path, path
            self.publishes += 1
            self.payload_bytes = len(payload)
        if prev is not None:
            try:
                os.unlink(prev)
            except OSError:
                log.debug("stale segment payload unlink failed: %s", prev,
                          exc_info=True)
        _PUBLISHES.labels(self.segment).inc()
        _BYTES.labels(self.segment).set(float(len(payload)))
        _GENERATION.labels(self.segment).set(float(gen))
        return gen

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "segment": self.segment,
                "generation": self.generation,
                "publishes": self.publishes,
                "payload_bytes": self.payload_bytes,
            }

    def close(self, unlink: bool = True) -> None:
        with self._lock:
            try:
                self._hdr.close()
                self._hdr_f.close()
            except (OSError, ValueError):
                pass  # already closed
            paths = [self._hdr_path]
            if self._prev_path is not None:
                paths.append(self._prev_path)
            if unlink:
                for p in paths:
                    try:
                        os.unlink(p)
                    except OSError:
                        pass  # best-effort cleanup of our own temp files


class SegmentReader:
    """Maps the writer's current generation; remaps on generation bump.

    ``snapshot()`` is cheap when the generation hasn't moved (one seqlock
    header read). Thread-safe: concurrent callers share one cached
    SegmentSnapshot per generation."""

    def __init__(self, prefix: str, segment: str = "corpus"):
        self.prefix = prefix
        self.segment = segment
        self._lock = threading.Lock()
        self._hdr: Optional[mmap.mmap] = None
        self._hdr_f = None
        self._snap: Optional[SegmentSnapshot] = None
        self.remaps = 0

    def _ensure_header(self) -> mmap.mmap:
        if self._hdr is None:
            try:
                self._hdr_f = open(self.prefix + ".hdr", "rb")
                self._hdr = mmap.mmap(self._hdr_f.fileno(), _HDR_SIZE,
                                      prot=mmap.PROT_READ)
            except (OSError, ValueError) as e:
                raise SegmentUnavailable(
                    f"segment header missing: {self.prefix}.hdr ({e})"
                )
        return self._hdr

    def _read_header(self) -> tuple[int, int]:
        """(generation, payload_len) via bounded seqlock retry."""
        hdr = self._ensure_header()
        for _ in range(1000):
            s1 = struct.unpack_from("<Q", hdr, 0)[0]
            if s1 & 1:
                continue
            gen = struct.unpack_from("<Q", hdr, 8)[0]
            ln = struct.unpack_from("<Q", hdr, 16)[0]
            s2 = struct.unpack_from("<Q", hdr, 0)[0]
            if s1 == s2:
                return gen, ln
        raise SegmentUnavailable(
            f"segment header unstable (writer died mid-publish?): "
            f"{self.prefix}"
        )

    def _map(self, gen: int, ln: int) -> SegmentSnapshot:
        path = f"{self.prefix}.g{gen}"
        f = open(path, "rb")
        try:
            mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
        except (OSError, ValueError):
            f.close()
            raise
        try:
            if mm[:4] != _MAGIC:
                raise SegmentUnavailable(f"bad segment magic in {path}")
            dir_len, origin = struct.unpack_from("<IQ", mm, 4)
            directory = json.loads(mm[16:16 + dir_len].decode())
            buf = memoryview(mm)
            arrays: dict[str, np.ndarray] = {}
            for entry in directory["arrays"]:
                dt = np.dtype(entry["dtype"])
                count = int(np.prod(entry["shape"])) if entry["shape"] else 1
                a = np.frombuffer(
                    buf, dtype=dt, count=count,
                    offset=origin + entry["offset"],
                ).reshape(entry["shape"])
                a.flags.writeable = False
                arrays[entry["name"]] = a
            return SegmentSnapshot(gen, arrays, directory.get("meta", {}),
                                   mm, f)
        except SegmentUnavailable:
            mm.close()
            f.close()
            raise
        except Exception:
            mm.close()
            f.close()
            raise

    def snapshot(self) -> SegmentSnapshot:
        """The current generation's arrays+meta; remaps if the writer
        published since the last call. Raises SegmentUnavailable when no
        generation can be mapped."""
        with self._lock:
            for _ in range(8):
                gen, ln = self._read_header()
                if gen == 0:
                    raise SegmentUnavailable(
                        f"no generation published yet: {self.prefix}"
                    )
                if self._snap is not None and self._snap.generation == gen:
                    return self._snap
                try:
                    snap = self._map(gen, ln)
                except FileNotFoundError:
                    # writer raced ahead and unlinked this generation
                    # between our header read and the open — retry
                    continue
                old, self._snap = self._snap, snap
                if old is not None:
                    # the OLD snapshot object stays valid for anyone still
                    # holding it (its mapping is open); we only drop OUR
                    # cached reference
                    self.remaps += 1
                    _REMAPS.labels(self.segment).inc()
                return snap
            raise SegmentUnavailable(
                f"could not map a stable generation: {self.prefix}"
            )

    def close(self) -> None:
        with self._lock:
            if self._snap is not None:
                self._snap.close()
                self._snap = None
            try:
                if self._hdr is not None:
                    self._hdr.close()
                if self._hdr_f is not None:
                    self._hdr_f.close()
            except (OSError, ValueError):
                pass  # already closed
            self._hdr = None
            self._hdr_f = None
