"""Shared-memory read plane: one copy of the hot read state for N workers.

The prefork workers (server/workers.py) are protocol frontends with no DB.
Before this module, every read they could not answer from the response
cache crossed back into the primary — so "add workers" only scaled cache
hits. The read plane exports the primary's two flat, read-mostly indexes
through generation-stamped shared-memory segments (server/shm.py):

* **corpus** — the search corpus host mirror: f32 rows + validity + slot→id
  map, plus the int8 serving mirror (per-row symmetric codes + scales, the
  same quantization the device kernels use). Workers serve exact host
  search from the f32 block — bit-identical to the primary's DEGRADED_CPU
  path because both run the same ``host_topk`` + ``format_topk_results``
  routines over the same slot layout.
* **adjacency** — the merged CSR topology snapshot (storage/adjacency.py):
  offsets/neighbors/edge-rows per direction + vocab. Workers expand
  traversals through the same ``_gather_csr`` gather the in-process
  snapshot uses, so expansions are bit-identical too.

The :class:`ReadPlanePublisher` republishes a segment when its source
generation moves; readers remap lazily on their next access (seqlock
header check — the mid-read case is safe because an already-mapped
snapshot stays valid until dropped).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Any, Callable, Optional

import numpy as np

from nornicdb_tpu.ops.host_search import (
    format_topk_results,
    host_topk,
    quantize_rows_np,
)
from nornicdb_tpu.server.shm import (
    SegmentReader,
    SegmentUnavailable,
    SegmentWriter,
)
from nornicdb_tpu.storage.adjacency import _gather_csr

log = logging.getLogger(__name__)

CORPUS_SEGMENT = "corpus"
ADJACENCY_SEGMENT = "adjacency"


# -- string-table packing ----------------------------------------------------
def pack_strings(strs: list) -> tuple[np.ndarray, np.ndarray]:
    """Pack a list of strings (None → empty) into (u8 bytes, u32 offsets);
    offsets has len(strs)+1 entries."""
    blobs = [(s or "").encode() for s in strs]
    off = np.zeros(len(blobs) + 1, np.uint32)
    if blobs:
        off[1:] = np.cumsum([len(b) for b in blobs], dtype=np.uint64).astype(
            np.uint32
        )
    data = np.frombuffer(b"".join(blobs), np.uint8).copy() if blobs else \
        np.zeros(0, np.uint8)
    return data, off


def unpack_strings(data: np.ndarray, off: np.ndarray) -> list[str]:
    raw = data.tobytes()
    o = off.tolist()
    return [raw[o[i]:o[i + 1]].decode() for i in range(len(o) - 1)]


# -- exporters ---------------------------------------------------------------
def export_corpus_segment(corpus) -> tuple[dict, dict]:
    """Corpus host state → (arrays, meta) for SegmentWriter.publish."""
    state = corpus.export_host_state()
    rows = state["rows"]
    # int8 serving mirror: ops.host_search.quantize_rows_np — the ONE
    # definition of the per-row symmetric quantization (shared with the
    # compressed-residency upload path, so an int8-resident corpus's
    # exported codes are bit-identical to what its device HBM holds; vs
    # the device-side ops.pallas_kernels.quantize_rows the codes are
    # identical and the scales within a float ulp)
    codes, scale = quantize_rows_np(rows)
    id_bytes, id_off = pack_strings(state["ids"])
    arrays = {
        "rows": rows,
        "valid": state["valid"],
        "rows_i8": codes,
        "scales_i8": scale,
        "id_bytes": id_bytes,
        "id_off": id_off,
    }
    meta = {
        "epoch": state["epoch"],
        "count": state["count"],
        "dims": state["dims"],
        # residency of the SOURCE corpus's device plane: consumers sizing
        # against HBM (or asserting the int8 mirror contract) read this
        "int8_residency": bool(getattr(corpus, "quantized", False)),
    }
    return arrays, meta


def export_adjacency_segment(snap) -> Optional[tuple[dict, dict]]:
    """AdjacencySnapshot → (arrays, meta); None while unbuilt."""
    exported = snap.export_arrays()
    if exported is None:
        return None
    arrays, vocab = exported
    for name in ("ids", "row_ids", "type_names"):
        data, off = pack_strings(vocab[name])
        arrays[f"{name}_bytes"] = data
        arrays[f"{name}_off"] = off
    meta = {
        "source_generation": vocab["generation"],
        "n_csr": vocab["n_csr"],
    }
    return arrays, meta


# -- shared readers ----------------------------------------------------------
class SharedCorpusReader:
    """Worker-side exact host search over the shared corpus segment.

    ``search`` mirrors ``HostCorpus._search_host`` — same query
    normalization, same ``host_topk`` selection (including its tie rule),
    same ``format_topk_results`` epilogue — over the one shared copy, so
    results are bit-identical to the primary's host path at the same
    generation."""

    def __init__(self, prefix: str):
        self._reader = SegmentReader(prefix, CORPUS_SEGMENT)
        self._ids_cache: tuple[int, list[str]] = (-1, [])
        self._lock = threading.Lock()

    def generation(self) -> int:
        return self._reader.snapshot().generation

    def _ids_for(self, snap) -> list[str]:
        with self._lock:
            gen, ids = self._ids_cache
            if gen == snap.generation:
                return ids
        ids = unpack_strings(snap.arrays["id_bytes"], snap.arrays["id_off"])
        with self._lock:
            self._ids_cache = (snap.generation, ids)
        return ids

    def search(
        self, queries: np.ndarray, k: int, min_similarity: float = -1.0,
        precision: str = "f32",
    ) -> list[list[tuple[str, float]]]:
        snap = self._reader.snapshot()  # remaps on generation bump
        q = np.atleast_2d(np.asarray(queries, np.float32))
        norms = np.linalg.norm(q, axis=1, keepdims=True)
        qn = q / np.maximum(norms, 1e-12)
        valid = snap.arrays["valid"]
        ids = self._ids_for(snap)
        if precision == "int8":
            # compact block: int8 codes scored in int32, de-scaled per row.
            # Approximate (quantization error), for memory-lean consumers;
            # serving fallback uses the exact f32 block below.
            codes = snap.arrays["rows_i8"]
            scales = snap.arrays["scales_i8"]
            approx = codes.astype(np.float32) / np.maximum(scales, 1e-9)[
                :, None
            ]
            vals, idx = host_topk(qn, approx, valid,
                                  min(k, codes.shape[0]))
        else:
            rows = snap.arrays["rows"]
            vals, idx = host_topk(qn, rows, valid, min(k, rows.shape[0]))
        return format_topk_results(
            vals, idx, q.shape[0], k, min_similarity, ids
        )

    def stats(self) -> dict[str, Any]:
        return {"remaps": self._reader.remaps}

    def close(self) -> None:
        self._reader.close()


class _AdjView:
    """Decoded per-generation adjacency state (vocab maps + array refs)."""

    __slots__ = ("snap", "ids", "idx", "alive", "row_ids", "type_code",
                 "n_csr")

    def __init__(self, snap):
        self.snap = snap
        a = snap.arrays
        self.ids = unpack_strings(a["ids_bytes"], a["ids_off"])
        self.idx = {id_: i for i, id_ in enumerate(self.ids)}
        self.alive = a["node_alive"]
        self.row_ids = unpack_strings(a["row_ids_bytes"], a["row_ids_off"])
        names = unpack_strings(a["type_names_bytes"], a["type_names_off"])
        self.type_code = {n: c for c, n in enumerate(names)}
        self.n_csr = int(snap.meta["n_csr"])


class SharedAdjacencyReader:
    """Worker-side CSR traversal over the shared adjacency segment.

    Expansion runs the same ``_gather_csr`` gather as the in-process
    AdjacencySnapshot (the exported CSR is pre-merged, so no delta overlay
    is needed) and sorts pairs by edge id exactly like
    ``expand_frontier`` — bit-identical expansions at the same source
    generation."""

    def __init__(self, prefix: str):
        self._reader = SegmentReader(prefix, ADJACENCY_SEGMENT)
        self._view: Optional[_AdjView] = None
        self._lock = threading.Lock()

    def _current(self) -> _AdjView:
        snap = self._reader.snapshot()
        with self._lock:
            if self._view is not None and self._view.snap is snap:
                return self._view
        view = _AdjView(snap)
        with self._lock:
            self._view = view
        return view

    def generation(self) -> int:
        """The SOURCE snapshot generation this view was exported from."""
        return int(self._current().snap.meta["source_generation"])

    def index_of(self, node_id: str) -> Optional[int]:
        v = self._current()
        i = v.idx.get(node_id)
        if i is None or not v.alive[i]:
            return None
        return i

    def ids_of(self, idxs) -> list[str]:
        v = self._current()
        return [v.ids[i] for i in idxs]

    def type_codes(self, types) -> Optional[list[int]]:
        if not types:
            return None
        v = self._current()
        return [c for t in types
                if (c := v.type_code.get(t)) is not None]

    def expand_frontier(
        self, idxs: list[int], direction: str,
        codes: Optional[list[int]] = None,
    ) -> dict[int, list[tuple[str, int]]]:
        v = self._current()
        a = v.snap.arrays
        dirs = (("out",) if direction == "out"
                else ("in",) if direction == "in" else ("out", "in"))
        out: dict[int, list[tuple[str, int]]] = {i: [] for i in idxs}
        arr_all = np.fromiter(idxs, np.int64, len(idxs))
        for d in dirs:
            heads, r, nb = _gather_csr(
                a[f"{d}_off"], a[f"{d}_nbr"], a[f"{d}_rows"],
                a["row_alive"], a["erow_type"], v.n_csr, arr_all, codes,
            )
            for j in range(heads.size):
                out[int(heads[j])].append((v.row_ids[int(r[j])],
                                           int(nb[j])))
        for lst in out.values():
            lst.sort()
        return out

    def expand_pairs(self, node_id: str, direction: str,
                     types=None) -> Optional[list[tuple[str, str]]]:
        """(edge_id, other_node_id) pairs, sorted — the AdjacencySnapshot
        ``expand_pairs`` contract over the shared segment."""
        idx = self.index_of(node_id)
        if idx is None:
            return None
        codes = self.type_codes(types)
        if types and not codes:
            return []
        adj = self.expand_frontier([idx], direction, codes)
        v = self._current()
        out = [(eid, v.ids[o]) for eid, o in adj.get(idx, ())]
        out.sort()
        return out

    def stats(self) -> dict[str, Any]:
        return {"remaps": self._reader.remaps}

    def close(self) -> None:
        self._reader.close()


# -- the publisher -----------------------------------------------------------
_ACTIVE: "list[weakref.ref]" = []
_ACTIVE_LOCK = threading.Lock()


def active_publisher_stats() -> list[dict]:
    """Stats for every live publisher (the /admin/stats "shm" section)."""
    out = []
    with _ACTIVE_LOCK:
        refs = list(_ACTIVE)
    for ref in refs:
        pub = ref()
        if pub is not None:
            out.append(pub.stats())
    return out


class ReadPlanePublisher:
    """Primary-side background publisher for the corpus + adjacency
    segments. Republishes a segment when its source generation/epoch moves
    (checked every ``interval`` seconds — cheap integer reads), so worker
    reads are at most one interval stale, the exact staleness contract of
    the workers' generation-stamped response cache."""

    def __init__(
        self,
        directory: str,
        corpus_fn: Callable[[], Any],
        adjacency_fn: Optional[Callable[[], Any]] = None,
        interval: float = 0.05,
    ):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.corpus_fn = corpus_fn
        self.adjacency_fn = adjacency_fn
        self.interval = interval
        self.paths = {
            CORPUS_SEGMENT: os.path.join(directory, "corpus.seg"),
            ADJACENCY_SEGMENT: os.path.join(directory, "adjacency.seg"),
        }
        self._corpus_writer = SegmentWriter(self.paths[CORPUS_SEGMENT],
                                            CORPUS_SEGMENT)
        self._adj_writer = SegmentWriter(self.paths[ADJACENCY_SEGMENT],
                                         ADJACENCY_SEGMENT)
        # weakref, not id(): a promoted-away corpus can be freed and its
        # address reused by the replacement — an id match plus an equal
        # epoch would then silently skip republishing the new corpus
        self._last_corpus_ref: Optional["weakref.ref"] = None
        self._last_corpus_epoch = -1
        self._last_adj = -1
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.errors = 0
        with _ACTIVE_LOCK:
            _ACTIVE[:] = [r for r in _ACTIVE if r() is not None]
            _ACTIVE.append(weakref.ref(self))

    # -- publish decisions --------------------------------------------------
    def publish_now(self) -> dict[str, int]:
        """Export + publish any segment whose source moved; returns the
        generations published this call (empty when nothing moved)."""
        published: dict[str, int] = {}
        corpus = self.corpus_fn()
        if corpus is not None:
            # unlocked epoch read is a benign race: a publish decision one
            # tick late is within the staleness contract, and the export
            # itself snapshots under the corpus sync lock
            last = (self._last_corpus_ref()
                    if self._last_corpus_ref is not None else None)
            if last is not corpus or \
                    corpus._epoch != self._last_corpus_epoch:
                arrays, meta = export_corpus_segment(corpus)
                gen = self._corpus_writer.publish(arrays, meta)
                self._last_corpus_ref = weakref.ref(corpus)
                self._last_corpus_epoch = meta["epoch"]
                published[CORPUS_SEGMENT] = gen
        snap = self.adjacency_fn() if self.adjacency_fn is not None else None
        if snap is not None and snap.ready():
            src_gen = snap.generation()
            if src_gen != self._last_adj:
                exported = export_adjacency_segment(snap)
                if exported is not None:
                    gen = self._adj_writer.publish(*exported)
                    self._last_adj = src_gen
                    published[ADJACENCY_SEGMENT] = gen
        return published

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.publish_now()
            except Exception:
                self.errors += 1
                log.exception("read-plane publish failed")

    def start(self) -> "ReadPlanePublisher":
        if self._thread is None:
            try:
                self.publish_now()
            except Exception:
                self.errors += 1
                log.exception("initial read-plane publish failed")
            self._thread = threading.Thread(
                target=self._loop, name="nornicdb-readplane", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
        self._corpus_writer.close()
        self._adj_writer.close()

    def stats(self) -> dict[str, Any]:
        return {
            "directory": self.directory,
            "interval_s": self.interval,
            "errors": self.errors,
            "segments": {
                CORPUS_SEGMENT: self._corpus_writer.stats(),
                ADJACENCY_SEGMENT: self._adj_writer.stats(),
            },
        }
