"""Qdrant-compatible gRPC services (Collections / Points / Snapshots / root).

Behavioral reference: /root/reference/pkg/qdrantgrpc/ — server.go:207
(NewServer wiring, keepalive, default-deny method RBAC :353-475),
collections_service.go, points_service.go, snapshots_service.go,
registry.go (points live as graph nodes, label "QdrantPoint"), tested
upstream with the official client (qdrant_official_e2e_test.go).

Wire format: the upstream Qdrant protobuf contract (package `qdrant`,
v1.16 field numbers, documented per-message below). No generated stubs —
messages are hand-encoded/decoded over grpc's GenericRpcHandler, the same
pattern as grpc_search.py. The official qdrant-client is not in this image,
so tests speak hand-built frames; the field numbers follow the public
qdrant protos (collections.proto / points.proto / json_with_int.proto /
snapshots_service.proto / qdrant.proto).

State is shared with the REST surface: both wrap one QdrantCollections
registry, so a point upserted over gRPC is visible to /collections/* REST
and to the unified search service (ref: server.go "single unified vector
index").
"""

from __future__ import annotations

import base64
import gzip
import json
import os
import struct
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from nornicdb_tpu.errors import AuthError, NornicError, NotFoundError
from nornicdb_tpu.server.qdrant import POINT_LABEL, QdrantCollections

SERVICE_COLLECTIONS = "qdrant.Collections"
SERVICE_POINTS = "qdrant.Points"
SERVICE_SNAPSHOTS = "qdrant.Snapshots"
SERVICE_ROOT = "qdrant.Qdrant"

# Distance enum (collections.proto): UnknownDistance=0 Cosine=1 Euclid=2
# Dot=3 Manhattan=4
_DISTANCE_TO_NUM = {"Cosine": 1, "Euclid": 2, "Dot": 3, "Manhattan": 4}
_NUM_TO_DISTANCE = {v: k for k, v in _DISTANCE_TO_NUM.items()}

_U64 = (1 << 64)
_I64_MAX = (1 << 63) - 1

import string as _string

_SAFE_NAME_CHARS = frozenset(_string.ascii_letters + _string.digits + "._-")


# ------------------------------------------------------------- wire helpers
def _varint(v: int) -> bytes:
    v &= _U64 - 1
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise NornicError("malformed varint")


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, data: bytes) -> bytes:
    """Length-delimited field."""
    return _tag(field, 2) + _varint(len(data)) + data


def _vi(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def _f32(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _f64(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _packed_f32(field: int, vals) -> bytes:
    return _ld(field, struct.pack(f"<{len(vals)}f", *vals))


def _s(field: int, text: str) -> bytes:
    return _ld(field, text.encode("utf-8"))


def _parse(buf: bytes) -> dict[int, list[tuple[int, Any]]]:
    """Generic TLV sweep: field -> [(wire_type, raw_value)].

    wire 0 -> int, wire 1 -> 8 raw bytes, wire 5 -> 4 raw bytes,
    wire 2 -> bytes. Unknown groups are rejected (proto3 never emits them).
    """
    out: dict[int, list[tuple[int, Any]]] = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
        elif wire == 1:
            if pos + 8 > n:
                raise NornicError("truncated fixed64 field")
            v = buf[pos : pos + 8]
            pos += 8
        elif wire == 5:
            if pos + 4 > n:
                raise NornicError("truncated fixed32 field")
            v = buf[pos : pos + 4]
            pos += 4
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > n:
                raise NornicError("truncated length-delimited field")
            v = buf[pos : pos + ln]
            pos += ln
        else:
            raise NornicError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append((wire, v))
    return out


def _first(fields: dict, num: int, default=None):
    vals = fields.get(num)
    return vals[0][1] if vals else default


def _i64(v: int) -> int:
    return v - _U64 if v > _I64_MAX else v


def _floats(raw: bytes) -> list[float]:
    return list(struct.unpack(f"<{len(raw) // 4}f", raw[: len(raw) // 4 * 4]))


def _varint_list(entries: list[tuple[int, Any]]) -> list[int]:
    """repeated int64 values: canonical proto3 encoders PACK them (one
    length-delimited blob of varints, wiretype 2) while lenient encoders may
    emit one varint field per element — accept both, like protobuf does."""
    out: list[int] = []
    for wire, v in entries:
        if isinstance(v, bytes):
            pos = 0
            while pos < len(v):
                val, pos = _read_varint(v, pos)
                out.append(_i64(val))
        else:
            out.append(_i64(v))
    return out


# ----------------------------------------------------- qdrant.Value codec
# json_with_int.proto: Value oneof kind { NullValue null_value=1;
# double double_value=2; int64 integer_value=3; string string_value=4;
# bool bool_value=5; Struct struct_value=6; ListValue list_value=7 }
# Struct: map<string, Value> fields=1.  ListValue: repeated Value values=1.
def enc_value(v: Any) -> bytes:
    if v is None:
        return _vi(1, 0)
    if isinstance(v, bool):
        return _vi(5, 1 if v else 0)
    if isinstance(v, int):
        return _vi(3, v)
    if isinstance(v, float):
        return _f64(2, v)
    if isinstance(v, str):
        return _s(4, v)
    if isinstance(v, dict):
        body = b"".join(
            _ld(1, _s(1, str(k)) + _ld(2, enc_value(x))) for k, x in v.items()
        )
        return _ld(6, body)
    if isinstance(v, (list, tuple)):
        return _ld(7, b"".join(_ld(1, enc_value(x)) for x in v))
    if isinstance(v, np.ndarray):
        return enc_value(v.tolist())
    return _s(4, str(v))


def dec_value(raw: bytes) -> Any:
    f = _parse(raw)
    if 1 in f:
        return None
    if 5 in f:
        return bool(f[5][0][1])
    if 3 in f:
        return _i64(f[3][0][1])
    if 2 in f:
        return struct.unpack("<d", f[2][0][1])[0]
    if 4 in f:
        return f[4][0][1].decode("utf-8")
    if 6 in f:
        sf = _parse(f[6][0][1])  # Struct: map<string, Value> fields=1
        out = {}
        for _, entry in sf.get(1, []):
            ef = _parse(entry)
            k = _first(ef, 1, b"").decode("utf-8")
            out[k] = dec_value(_first(ef, 2, b""))
        return out
    if 7 in f:
        lf = _parse(f[7][0][1])
        return [dec_value(r) for _, r in lf.get(1, [])]
    return None


def enc_payload_map(field: int, payload: dict[str, Any]) -> bytes:
    """map<string, Value>: entries key=1, value=2."""
    return b"".join(
        _ld(field, _s(1, str(k)) + _ld(2, enc_value(v)))
        for k, v in payload.items()
    )


def dec_payload_map(entries: list[tuple[int, Any]]) -> dict[str, Any]:
    out = {}
    for _, raw in entries:
        f = _parse(raw)
        k = _first(f, 1, b"").decode("utf-8")
        out[k] = dec_value(_first(f, 2, b""))
    return out


# --------------------------------------------------------------- Filter
# points.proto Filter: should=1, must=2, must_not=3 (repeated Condition).
# Condition oneof: FieldCondition field=1, IsEmptyCondition is_empty=2,
# HasIdCondition has_id=3, Filter filter=4, IsNullCondition is_null=5.
# FieldCondition: key=1, Match match=2, Range range=3.
# Match oneof: keyword=1, integer=2, boolean=3, text=4,
#   RepeatedStrings keywords=5 {strings=1}, RepeatedIntegers integers=6
#   {integers=1}, except_integers=7, except_keywords=8.
# Range: lt=1, gt=2, gte=3, lte=4 (doubles).
# Decodes to the JSON-dict form evaluated by qdrant.eval_filter, so both
# transports share one evaluator (ref: pkg/qdrantgrpc points_service.go).
def _dec_match(raw: bytes) -> dict:
    f = _parse(raw)
    if 1 in f:
        return {"keyword": f[1][0][1].decode("utf-8")}
    if 2 in f:
        return {"integer": _i64(f[2][0][1])}
    if 3 in f:
        return {"boolean": bool(f[3][0][1])}
    if 4 in f:
        return {"text": f[4][0][1].decode("utf-8")}
    if 5 in f:
        rs = _parse(f[5][0][1])
        return {"any": [r.decode("utf-8") for _, r in rs.get(1, [])]}
    if 6 in f:
        ri = _parse(f[6][0][1])
        return {"any": _varint_list(ri.get(1, []))}
    if 7 in f:
        ri = _parse(f[7][0][1])
        return {"except": _varint_list(ri.get(1, []))}
    if 8 in f:
        rs = _parse(f[8][0][1])
        return {"except": [r.decode("utf-8") for _, r in rs.get(1, [])]}
    raise NornicError("empty match clause")


def _dec_condition(raw: bytes) -> dict:
    f = _parse(raw)
    if 1 in f:  # FieldCondition
        ff = _parse(f[1][0][1])
        cond: dict = {"key": _first(ff, 1, b"").decode("utf-8")}
        if 2 in ff:
            cond["match"] = _dec_match(ff[2][0][1])
        elif 3 in ff:
            rf = _parse(ff[3][0][1])
            rng = {}
            for num, name in ((1, "lt"), (2, "gt"), (3, "gte"), (4, "lte")):
                if num in rf:
                    rng[name] = struct.unpack("<d", rf[num][0][1])[0]
            cond["range"] = rng
        else:
            raise NornicError(
                f"unsupported field condition on {cond['key']!r} "
                "(match and range are supported)"
            )
        return cond
    if 2 in f:
        ef = _parse(f[2][0][1])
        return {"is_empty": {"key": _first(ef, 1, b"").decode("utf-8")}}
    if 3 in f:
        hf = _parse(f[3][0][1])
        return {"has_id": [dec_point_id(r) for _, r in hf.get(1, [])]}
    if 4 in f:
        return {"filter": dec_filter(f[4][0][1])}
    if 5 in f:
        nf = _parse(f[5][0][1])
        return {"is_null": {"key": _first(nf, 1, b"").decode("utf-8")}}
    raise NornicError("unsupported filter condition")


def dec_filter(raw: bytes) -> dict:
    f = _parse(raw)
    out: dict = {}
    for num, name in ((1, "should"), (2, "must"), (3, "must_not")):
        if num in f:
            out[name] = [_dec_condition(r) for _, r in f[num]]
    return out


# ------------------------------------------------------- PointId / Vectors
# points.proto PointId: oneof { uint64 num=1; string uuid=2 }
def enc_point_id(pid: Any) -> bytes:
    if isinstance(pid, int):
        return _vi(1, pid)
    return _s(2, str(pid))


def dec_point_id(raw: bytes) -> Any:
    f = _parse(raw)
    if 1 in f:
        return f[1][0][1]
    if 2 in f:
        return f[2][0][1].decode("utf-8")
    return None


# Vector: repeated float data=1 (packed).
# Vectors: oneof { Vector vector=1; NamedVectors vectors=2 };
# NamedVectors: map<string, Vector> vectors=1.
def enc_vectors(vector: Any) -> bytes:
    if isinstance(vector, dict):
        entries = b"".join(
            _ld(1, _s(1, name) + _ld(2, _packed_f32(1, vals)))
            for name, vals in vector.items()
        )
        return _ld(2, entries)
    return _ld(1, _packed_f32(1, list(vector)))


def dec_vectors(raw: bytes) -> Any:
    f = _parse(raw)
    if 1 in f:
        vf = _parse(f[1][0][1])
        return _floats(_first(vf, 1, b""))
    if 2 in f:
        out = {}
        nf = _parse(f[2][0][1])
        for _, entry in nf.get(1, []):
            ef = _parse(entry)
            name = _first(ef, 1, b"").decode("utf-8")
            vf = _parse(_first(ef, 2, b""))
            out[name] = _floats(_first(vf, 1, b""))
        return out
    return None


# ------------------------------------------------------- response shells
def _op_response(ok: bool, t0: float) -> bytes:
    """CollectionOperationResponse / result=1 bool, time=2 double."""
    return _vi(1, 1 if ok else 0) + _f64(2, time.perf_counter() - t0)


def _update_result_response(t0: float, status: int = 2) -> bytes:
    """PointsOperationResponse: result=1 UpdateResult{operation_id=1,
    status=2 (Completed=2)}, time=2."""
    return _ld(1, _vi(1, 0) + _vi(2, status)) + _f64(
        2, time.perf_counter() - t0
    )


def _scored_point(pid: Any, score: float, payload: Optional[dict],
                  vectors: Any = None) -> bytes:
    """ScoredPoint: id=1, payload=2 map, score=3 float, version=5,
    vectors=6."""
    body = _ld(1, enc_point_id(pid))
    if payload:
        body += enc_payload_map(2, payload)
    body += _f32(3, float(score)) + _vi(5, 0)
    if vectors is not None:
        body += _ld(6, enc_vectors(vectors))
    return body


def _retrieved_point(pid: Any, payload: Optional[dict],
                     vectors: Any = None) -> bytes:
    """RetrievedPoint: id=1, payload=2 map, vectors=4."""
    body = _ld(1, enc_point_id(pid))
    if payload:
        body += enc_payload_map(2, payload)
    if vectors is not None:
        body += _ld(4, enc_vectors(vectors))
    return body


# ----------------------------------------------------------------- server
class QdrantGrpcServer:
    """Qdrant v1.16-wire gRPC server on :6334 (ref: NewServer server.go:207).

    Auth mirrors the reference's interceptors (server.go:374-475):
    metadata `authorization: Bearer <jwt>` / `Basic <user:pass>` or
    `api-key: <jwt>`; per-method RBAC is default-deny — a method absent
    from the permission table is refused.
    """

    # ref: authorizeMethod server.go:353 — default-deny table
    METHOD_PERMISSIONS = {
        f"/{SERVICE_ROOT}/HealthCheck": None,  # open, like upstream qdrant
        f"/{SERVICE_COLLECTIONS}/List": "read",
        f"/{SERVICE_COLLECTIONS}/Get": "read",
        f"/{SERVICE_COLLECTIONS}/CollectionExists": "read",
        f"/{SERVICE_COLLECTIONS}/Create": "write",
        f"/{SERVICE_COLLECTIONS}/Update": "write",
        f"/{SERVICE_COLLECTIONS}/Delete": "write",
        f"/{SERVICE_POINTS}/Search": "read",
        f"/{SERVICE_POINTS}/Get": "read",
        f"/{SERVICE_POINTS}/Count": "read",
        f"/{SERVICE_POINTS}/Scroll": "read",
        f"/{SERVICE_POINTS}/Upsert": "write",
        f"/{SERVICE_POINTS}/Delete": "write",
        f"/{SERVICE_POINTS}/SetPayload": "write",
        f"/{SERVICE_POINTS}/OverwritePayload": "write",
        f"/{SERVICE_POINTS}/DeletePayload": "write",
        f"/{SERVICE_POINTS}/ClearPayload": "write",
        f"/{SERVICE_SNAPSHOTS}/List": "read",
        f"/{SERVICE_SNAPSHOTS}/Create": "write",
        f"/{SERVICE_SNAPSHOTS}/Delete": "write",
    }

    def __init__(
        self,
        registry: QdrantCollections,
        host: str = "127.0.0.1",
        port: int = 0,
        authenticator=None,
        allow_vector_mutations: bool = True,
        snapshot_dir: Optional[str] = None,
        max_workers: int = 4,
        version: str = "1.16.0",
    ):
        import grpc
        from concurrent import futures

        self.registry = registry
        self.authenticator = authenticator
        self.allow_vector_mutations = allow_vector_mutations
        self.snapshot_dir = snapshot_dir
        self.version = version
        self._grpc = grpc
        self._snap_lock = threading.Lock()
        outer = self

        methods: dict[str, Callable] = {
            f"/{SERVICE_ROOT}/HealthCheck": self._health,
            f"/{SERVICE_COLLECTIONS}/Create": self._coll_create,
            f"/{SERVICE_COLLECTIONS}/Delete": self._coll_delete,
            f"/{SERVICE_COLLECTIONS}/List": self._coll_list,
            f"/{SERVICE_COLLECTIONS}/Get": self._coll_get,
            f"/{SERVICE_COLLECTIONS}/Update": self._coll_update,
            f"/{SERVICE_COLLECTIONS}/CollectionExists": self._coll_exists,
            f"/{SERVICE_POINTS}/Upsert": self._points_upsert,
            f"/{SERVICE_POINTS}/Get": self._points_get,
            f"/{SERVICE_POINTS}/Delete": self._points_delete,
            f"/{SERVICE_POINTS}/Search": self._points_search,
            f"/{SERVICE_POINTS}/Count": self._points_count,
            f"/{SERVICE_POINTS}/Scroll": self._points_scroll,
            f"/{SERVICE_POINTS}/SetPayload": self._points_set_payload,
            f"/{SERVICE_POINTS}/OverwritePayload": self._points_overwrite_payload,
            f"/{SERVICE_POINTS}/DeletePayload": self._points_delete_payload,
            f"/{SERVICE_POINTS}/ClearPayload": self._points_clear_payload,
            f"/{SERVICE_SNAPSHOTS}/Create": self._snap_create,
            f"/{SERVICE_SNAPSHOTS}/List": self._snap_list,
            f"/{SERVICE_SNAPSHOTS}/Delete": self._snap_delete,
        }

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                fn = methods.get(handler_call_details.method)
                if fn is None:
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    outer._wrap(handler_call_details.method, fn),
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            # grpc signals bind failure by returning port 0 — surface it
            # like BoltServer/HttpServer do instead of serving nowhere
            raise NornicError(f"qdrant grpc failed to bind {host}:{port}")
        self.host = host

    # -- auth (ref: unaryAuthInterceptor server.go:374, basic :475) --------
    def _wrap(self, method: str, fn: Callable) -> Callable:
        grpc = self._grpc

        def call(request: bytes, context) -> bytes:
            if self.authenticator is not None:
                perm = self.METHOD_PERMISSIONS.get(method, "__deny__")
                if perm == "__deny__":
                    context.abort(grpc.StatusCode.PERMISSION_DENIED,
                                  f"method {method} not permitted")
                if perm is not None:
                    payload = self._authenticate(dict(
                        context.invocation_metadata()))
                    if payload is None:
                        context.abort(grpc.StatusCode.UNAUTHENTICATED,
                                      "authentication required")
                    role = payload.get("role", "none")
                    if not self.authenticator.has_permission(role, perm):
                        context.abort(grpc.StatusCode.PERMISSION_DENIED,
                                      f"permission {perm} denied")
            try:
                return fn(request, context)
            except NotFoundError as e:
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            except (NornicError, IndexError, struct.error,
                    UnicodeDecodeError) as e:
                # truncated varints / short fixed fields / bad UTF-8 from a
                # malformed frame must map to INVALID_ARGUMENT, not leak a
                # traceback as UNKNOWN
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"malformed request: {e}")

        return call

    def _authenticate(self, md: dict) -> Optional[dict]:
        auth = self.authenticator
        header = md.get("authorization", "")
        if header.startswith("Bearer "):
            return auth.validate_token(header[7:])
        if header.startswith("Basic "):
            try:
                user, pw = base64.b64decode(header[6:]).decode().split(":", 1)
            except (ValueError, UnicodeDecodeError):
                return None  # malformed basic-auth header
            if auth.check_password(user, pw):
                try:
                    return {"sub": user, "role": auth.get_user(user).role}
                except AuthError:
                    return None  # user deleted between check and fetch
            return None
        api_key = md.get("api-key", "")
        if api_key:
            return auth.validate_token(api_key)
        return None

    # -- root --------------------------------------------------------------
    def _health(self, request: bytes, context) -> bytes:
        """HealthCheckReply: title=1, version=2 (qdrant.proto)."""
        return _s(1, "nornicdb-tpu qdrant compat") + _s(2, self.version)

    # -- collections -------------------------------------------------------
    @staticmethod
    def _dec_vector_params(raw: bytes) -> dict:
        """VectorParams: size=1 uint64, distance=2 enum."""
        f = _parse(raw)
        return {
            "size": int(_first(f, 1, 0)),
            "distance": _NUM_TO_DISTANCE.get(int(_first(f, 2, 1)), "Cosine"),
        }

    def _dec_vectors_config(self, raw: bytes) -> tuple[int, str, dict]:
        """VectorsConfig: oneof { VectorParams params=1;
        VectorParamsMap params_map=2 }. Returns (size, distance, named)."""
        f = _parse(raw)
        if 1 in f:
            p = self._dec_vector_params(f[1][0][1])
            return p["size"], p["distance"], {}
        named = {}
        if 2 in f:
            mf = _parse(f[2][0][1])  # VectorParamsMap: map=1
            for _, entry in mf.get(1, []):
                ef = _parse(entry)
                name = _first(ef, 1, b"").decode("utf-8")
                named[name] = self._dec_vector_params(_first(ef, 2, b""))
        return 0, "Cosine", named

    def _coll_create(self, request: bytes, context) -> bytes:
        t0 = time.perf_counter()
        f = _parse(request)
        name = _first(f, 1, b"").decode("utf-8")
        size, distance, named = 0, "Cosine", {}
        if 10 in f:  # CreateCollection.vectors_config=10
            size, distance, named = self._dec_vectors_config(f[10][0][1])
        self.registry.create(name, size=size, distance=distance, named=named)
        return _op_response(True, t0)

    def _coll_delete(self, request: bytes, context) -> bytes:
        t0 = time.perf_counter()
        name = _first(_parse(request), 1, b"").decode("utf-8")
        return _op_response(self.registry.drop(name), t0)

    def _coll_update(self, request: bytes, context) -> bytes:
        # optimizer/HNSW retuning has no analogue here; acknowledge
        return _op_response(True, time.perf_counter())

    def _coll_list(self, request: bytes, context) -> bytes:
        """ListCollectionsResponse: collections=1 rep CollectionDescription
        {name=1}, time=2."""
        t0 = time.perf_counter()
        body = b"".join(
            _ld(1, _s(1, c["name"])) for c in self.registry.list()
        )
        return body + _f64(2, time.perf_counter() - t0)

    def _coll_exists(self, request: bytes, context) -> bytes:
        """CollectionExistsResponse: result=1 {exists=1 bool}, time=2."""
        t0 = time.perf_counter()
        name = _first(_parse(request), 1, b"").decode("utf-8")
        exists = self.registry.info(name) is not None
        # proto3 canonical form: default (false) is omitted
        return _ld(1, _vi(1, 1) if exists else b"") + _f64(
            2, time.perf_counter() - t0
        )

    def _coll_get(self, request: bytes, context) -> bytes:
        """GetCollectionInfoResponse: result=1 CollectionInfo{status=1,
        vectors_count=3, config=7 CollectionConfig{params=1
        CollectionParams{vectors_config=5}}, points_count=9}, time=2."""
        t0 = time.perf_counter()
        name = _first(_parse(request), 1, b"").decode("utf-8")
        info = self.registry.info(name)
        if info is None:
            raise NotFoundError(f"collection {name} not found")
        meta = self.registry.params(name) or {}
        vec_params = _vi(1, int(meta.get("size", 0))) + _vi(
            2, _DISTANCE_TO_NUM.get(meta.get("distance", "Cosine"), 1)
        )
        named = meta.get("named") or {}
        if named:
            entries = b"".join(
                _ld(1, _s(1, vn) + _ld(2, _vi(1, int(spec.get("size", 0)))
                                       + _vi(2, _DISTANCE_TO_NUM.get(
                                           spec.get("distance", "Cosine"), 1))))
                for vn, spec in named.items()
            )
            vectors_config = _ld(2, _ld(1, entries))
        else:
            vectors_config = _ld(1, vec_params)
        params = _ld(5, vectors_config)  # CollectionParams.vectors_config=5
        config = _ld(1, params)  # CollectionConfig.params=1
        count = info["points_count"]
        collection_info = (
            _vi(1, 1)  # status=Green
            + _vi(3, count)
            + _ld(7, config)
            + _vi(9, count)
        )
        return _ld(1, collection_info) + _f64(2, time.perf_counter() - t0)

    # -- points ------------------------------------------------------------
    def _points_upsert(self, request: bytes, context) -> bytes:
        """UpsertPoints: collection_name=1, wait=2, points=3 rep PointStruct
        {id=1, payload=3 map, vectors=4}."""
        t0 = time.perf_counter()
        if not self.allow_vector_mutations:
            # ref: AllowVectorMutations=false -> FailedPrecondition
            context.abort(
                self._grpc.StatusCode.FAILED_PRECONDITION,
                "vector mutations are managed by nornicdb embeddings",
            )
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        points = []
        for _, raw in f.get(3, []):
            pf = _parse(raw)
            pid = dec_point_id(_first(pf, 1, b""))
            payload = dec_payload_map(pf.get(3, []))
            vectors = dec_vectors(_first(pf, 4, b"")) if 4 in pf else None
            points.append(
                {"id": pid, "vector": vectors, "payload": payload}
            )
        self.registry.upsert(coll, points)
        return _update_result_response(t0)

    def _points_get(self, request: bytes, context) -> bytes:
        """GetPoints: collection_name=1, ids=2 rep PointId ->
        GetResponse: result=1 rep RetrievedPoint, time=2."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        ids = [dec_point_id(raw) for _, raw in f.get(2, [])]
        body = b""
        for item in self.registry.retrieve(coll, ids):
            body += _ld(1, _retrieved_point(
                item["id"], item.get("payload"), item.get("vector")))
        return body + _f64(2, time.perf_counter() - t0)

    def _selector_ids(self, coll: str, f: dict, field: int, context) -> list:
        """Decode PointsSelector at `field`: oneof { PointsIdsList points=1;
        Filter filter=2 }. Filter selectors resolve to the matching point
        ids via the shared evaluator."""
        if field not in f:
            return []
        sf = _parse(f[field][0][1])
        if 2 in sf:
            return self.registry.matching_ids(coll, dec_filter(sf[2][0][1]))
        if 1 in sf:
            lf = _parse(sf[1][0][1])
            return [dec_point_id(raw) for _, raw in lf.get(1, [])]
        return []

    def _points_delete(self, request: bytes, context) -> bytes:
        """DeletePoints: collection_name=1, points=3 PointsSelector
        {points=1 PointsIdsList{ids=1} | filter=2}."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        ids = self._selector_ids(coll, f, 3, context)
        self.registry.delete_points(coll, ids)
        return _update_result_response(t0)

    def _points_search(self, request: bytes, context) -> bytes:
        """SearchPoints: collection_name=1, vector=2 packed floats, filter=3,
        limit=4, with_payload=6 WithPayloadSelector{enable=1},
        score_threshold=8, vector_name=10, with_vectors=11 ->
        SearchResponse: result=1 rep ScoredPoint, time=2."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        vector = _floats(_first(f, 2, b""))
        flt = dec_filter(f[3][0][1]) if 3 in f else None
        limit = int(_first(f, 4, 10))
        with_payload = True
        if 6 in f:
            wf = _parse(f[6][0][1])
            if 1 in wf:
                with_payload = bool(wf[1][0][1])
        threshold = -1.0
        if 8 in f:
            threshold = struct.unpack("<f", f[8][0][1])[0]
        vec_name = _first(f, 10, b"").decode("utf-8") if 10 in f else ""
        with_vectors = False
        if 11 in f:
            wv = _parse(f[11][0][1])
            if 1 in wv:
                with_vectors = bool(wv[1][0][1])
        query: Any = vector
        if vec_name:
            query = {"name": vec_name, "vector": vector}
        hits = self.registry.search(
            coll, query, limit=limit, score_threshold=threshold,
            with_payload=with_payload, query_filter=flt,
        )
        body = b""
        vec_by_id = {}
        if with_vectors:
            for item in self.registry.retrieve(coll, [h["id"] for h in hits]):
                vec_by_id[item["id"]] = item.get("vector")
        for h in hits:
            body += _ld(1, _scored_point(
                h["id"], h["score"], h.get("payload"),
                vec_by_id.get(h["id"]) if with_vectors else None,
            ))
        return body + _f64(2, time.perf_counter() - t0)

    def _points_count(self, request: bytes, context) -> bytes:
        """CountPoints: collection_name=1, filter=2 -> CountResponse:
        result=1 {count=1}, time=2."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        flt = dec_filter(f[2][0][1]) if 2 in f else None
        count = self.registry.count(coll, flt)
        return _ld(1, _vi(1, count)) + _f64(2, time.perf_counter() - t0)

    def _points_scroll(self, request: bytes, context) -> bytes:
        """ScrollPoints: collection_name=1, filter=2, offset=3 PointId,
        limit=4 -> ScrollResponse: next_page_offset=1, result=2 rep
        RetrievedPoint, time=3. Points are ordered by point id (stringified)
        for a stable scroll, matching the reference's deterministic paging."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        flt = dec_filter(f[2][0][1]) if 2 in f else None
        offset = dec_point_id(_first(f, 3, b"")) if 3 in f else None
        limit = int(_first(f, 4, 10))
        page, nxt = self.registry.scroll(
            coll, offset=offset, limit=limit, query_filter=flt
        )
        body = b""
        for item in self.registry.retrieve(coll, page):
            body += _ld(2, _retrieved_point(
                item["id"], item.get("payload"), item.get("vector")))
        out = b""
        if nxt is not None:
            out += _ld(1, enc_point_id(nxt))
        return out + body + _f64(3, time.perf_counter() - t0)

    # -- payload ops (ref: points_service.go payload ops) -------------------
    def _payload_targets(self, f: dict, context,
                         selector_field: int = 5) -> tuple[str, list]:
        """Set/DeletePayload carry the selector at field 5 (field 3 is the
        payload map / key list — never a selector); ClearPayload carries it
        at field 3."""
        coll = _first(f, 1, b"").decode("utf-8")
        return coll, self._selector_ids(coll, f, selector_field, context)

    def _mutate_payload(self, coll: str, ids: list, fn) -> None:
        if self.registry.info(coll) is None:
            raise NotFoundError(f"collection {coll} not found")
        for pid in ids:
            nid = self.registry._node_id(coll, pid)
            try:
                node = self.registry.storage.get_node(nid)
            except NotFoundError:
                continue
            fn(node)
            self.registry.storage.update_node(node)

    def _points_set_payload(self, request: bytes, context) -> bytes:
        """SetPayloadPoints: collection_name=1, payload=3 map,
        points_selector=5."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll, ids = self._payload_targets(f, context)
        # underscore keys are internal (_collection, _point_id) — a client
        # payload must not clobber them (DeletePayload/Clear guard likewise)
        payload = {k: v for k, v in dec_payload_map(f.get(3, [])).items()
                   if not k.startswith("_")}
        self._mutate_payload(
            coll, ids, lambda n: n.properties.update(payload)
        )
        return _update_result_response(t0)

    def _points_overwrite_payload(self, request: bytes, context) -> bytes:
        t0 = time.perf_counter()
        f = _parse(request)
        coll, ids = self._payload_targets(f, context)
        payload = {k: v for k, v in dec_payload_map(f.get(3, [])).items()
                   if not k.startswith("_")}

        def overwrite(n):
            keep = {k: v for k, v in n.properties.items()
                    if k.startswith("_")}
            n.properties = {**keep, **payload}

        self._mutate_payload(coll, ids, overwrite)
        return _update_result_response(t0)

    def _points_delete_payload(self, request: bytes, context) -> bytes:
        """DeletePayloadPoints: collection_name=1, keys=3 rep string,
        points_selector=5."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll, ids = self._payload_targets(f, context)
        keys = [raw.decode("utf-8") for _, raw in f.get(3, [])]

        def drop(n):
            for k in keys:
                if not k.startswith("_"):
                    n.properties.pop(k, None)

        self._mutate_payload(coll, ids, drop)
        return _update_result_response(t0)

    def _points_clear_payload(self, request: bytes, context) -> bytes:
        """ClearPayloadPoints: collection_name=1, points=3 selector."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll, ids = self._payload_targets(f, context, selector_field=3)

        def clear(n):
            n.properties = {k: v for k, v in n.properties.items()
                            if k.startswith("_")}

        self._mutate_payload(coll, ids, clear)
        return _update_result_response(t0)

    # -- snapshots (ref: snapshots_service.go; on-disk archives) ------------
    @staticmethod
    def _safe_component(name: str) -> str:
        """Snapshot paths are built from client-supplied names; anything
        outside [A-Za-z0-9._-] (or starting with a dot) would let a crafted
        collection/snapshot name escape snapshot_dir."""
        if (
            not name
            or name.startswith(".")
            or any(c not in _SAFE_NAME_CHARS for c in name)
        ):
            raise NornicError(f"invalid name {name!r}")
        return name

    def _snap_path(self, coll: str, name: str) -> str:
        return os.path.join(
            self.snapshot_dir,
            self._safe_component(coll),
            self._safe_component(name),
        )

    def _snap_create(self, request: bytes, context) -> bytes:
        """CreateSnapshotResponse: snapshot_description=1
        {name=1, creation_time=2 Timestamp{seconds=1}, size=3}, time=2."""
        t0 = time.perf_counter()
        if self.snapshot_dir is None:
            context.abort(self._grpc.StatusCode.FAILED_PRECONDITION,
                          "snapshot_dir not configured")
        coll = self._safe_component(
            _first(_parse(request), 1, b"").decode("utf-8"))
        if self.registry.info(coll) is None:
            raise NotFoundError(f"collection {coll} not found")
        points = []
        for n in self.registry.storage.get_nodes_by_label(POINT_LABEL):
            if n.properties.get("_collection") != coll:
                continue
            points.append({
                "id": n.properties.get("_point_id"),
                "payload": {k: v for k, v in n.properties.items()
                            if not k.startswith("_")},
                "vector": (
                    {k: v.tolist() for k, v in n.named_embeddings.items()}
                    if n.named_embeddings
                    else (n.embedding.tolist()
                          if n.embedding is not None else None)
                ),
            })
        ts = int(time.time())
        name = f"{coll}-{ts}.snapshot"
        with self._snap_lock:
            os.makedirs(os.path.join(self.snapshot_dir, coll), exist_ok=True)
            blob = gzip.compress(json.dumps(
                {"collection": coll, "points": points}).encode())
            with open(self._snap_path(coll, name), "wb") as fh:
                fh.write(blob)
        desc = _s(1, name) + _ld(2, _vi(1, ts)) + _vi(3, len(blob))
        return _ld(1, desc) + _f64(2, time.perf_counter() - t0)

    def _snap_list(self, request: bytes, context) -> bytes:
        """ListSnapshotsResponse: snapshot_descriptions=1 rep, time=2."""
        t0 = time.perf_counter()
        coll = self._safe_component(
            _first(_parse(request), 1, b"").decode("utf-8"))
        body = b""
        d = os.path.join(self.snapshot_dir or "", coll)
        if self.snapshot_dir and os.path.isdir(d):
            for fname in sorted(os.listdir(d)):
                path = os.path.join(d, fname)
                body += _ld(1, _s(1, fname)
                            + _ld(2, _vi(1, int(os.path.getmtime(path))))
                            + _vi(3, os.path.getsize(path)))
        return body + _f64(2, time.perf_counter() - t0)

    def _snap_delete(self, request: bytes, context) -> bytes:
        """DeleteSnapshotResponse: time=1."""
        t0 = time.perf_counter()
        f = _parse(request)
        coll = _first(f, 1, b"").decode("utf-8")
        name = _first(f, 2, b"").decode("utf-8")
        if not self.snapshot_dir:
            raise NotFoundError("snapshots not configured")
        path = self._snap_path(coll, name)
        if not os.path.exists(path):
            raise NotFoundError(f"snapshot {name} not found")
        os.remove(path)
        return _f64(1, time.perf_counter() - t0)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=1)
