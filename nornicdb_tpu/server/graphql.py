"""GraphQL API: hand-rolled executor for the reference's GraphQL surface.

Behavioral reference: /root/reference/pkg/graphql/ — gqlgen-based schema with
node/edge CRUD, search, Cypher pass-through and traversals (handler.go,
schema/, resolvers/). graphql-core is not in this image, so this module
implements a small GraphQL subset natively: query/mutation operations,
field arguments (literals + $variables with defaults), nested selection
sets (projected onto results), aliases, named + inline fragments,
@include/@skip directives, __typename, and enough of the introspection
schema (__schema/__type) for clients that probe capabilities.

Root fields:
  query:    node(id) nodes(label, limit) relationships(type, limit)
            search(query, limit) similar(id, limit) cypher(statement,
            parameters) neighbors(id, depth) stats
  mutation: createNode(labels, properties) updateNode(id, properties)
            deleteNode(id) createRelationship(from, to, type, properties)
            deleteRelationship(id)
"""

from __future__ import annotations

import json
import re
import threading
from typing import Any, Optional

from nornicdb_tpu.errors import CypherSyntaxError, NornicError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Node

_TOKEN = re.compile(
    r"""(?P<ws>[\s,]+|\#[^\n]*)|(?P<name>[_A-Za-z][_0-9A-Za-z]*)"""
    r"""|(?P<string>"(?:\\.|[^"\\])*")|(?P<float>-?\d+\.\d+)"""
    r"""|(?P<int>-?\d+)|(?P<punct>[{}()\[\]:$=!@])|(?P<spread>\.\.\.)"""
)


class _Parser:
    def __init__(self, src: str):
        self.tokens = []
        last_end = 0
        for m in _TOKEN.finditer(src):
            if m.start() != last_end:
                raise CypherSyntaxError(
                    f"GraphQL: unexpected character {src[last_end]!r}"
                )
            last_end = m.end()
            kind = m.lastgroup
            if kind != "ws":
                self.tokens.append((kind, m.group(0)))
        if last_end != len(src):
            raise CypherSyntaxError(
                f"GraphQL: unexpected character {src[last_end]!r}"
            )
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else ("eof", "")

    def next(self):
        t = self.peek()
        if t[0] == "eof":
            raise CypherSyntaxError("GraphQL: unexpected end of query")
        self.pos += 1
        return t

    def expect(self, value: str):
        kind, v = self.next()
        if v != value:
            raise CypherSyntaxError(f"GraphQL: expected {value!r}, got {v!r}")

    def parse_document(self) -> dict:
        """Full document: one operation + any number of named fragments."""
        operation = None
        fragments: dict[str, dict] = {}
        while self.peek()[0] != "eof":
            kind, v = self.peek()
            if v == "fragment":
                self.next()
                fname = self.next()[1]
                self.expect("on")
                ftype = self.next()[1]
                fragments[fname] = {
                    "type": ftype,
                    "selections": self.parse_selection_set(),
                }
            else:
                op = self.parse_operation_def()
                if operation is not None:
                    raise CypherSyntaxError(
                        "GraphQL: multiple operations in one document"
                    )
                operation = op
        if operation is None:
            raise CypherSyntaxError("GraphQL: no operation in document")
        operation["fragments"] = fragments
        return operation

    def parse_operation_def(self) -> dict:
        kind, v = self.peek()
        op = "query"
        name = None
        var_defaults: dict[str, Any] = {}
        if v in ("query", "mutation"):
            op = v
            self.next()
            if self.peek()[0] == "name":
                name = self.next()[1]
            if self.peek()[1] == "(":
                self.next()
                while self.peek()[1] != ")":
                    self.expect("$")
                    vname = self.next()[1]
                    self.expect(":")
                    # type tokens (Name, [Name!]!, …): consume until the next
                    # variable, a default marker, or the close paren
                    consumed = 0
                    while self.peek()[1] not in ("=", ")", "$"):
                        tk, tv = self.next()
                        if tk != "name" and tv not in ("[", "]", "!"):
                            raise CypherSyntaxError(
                                f"GraphQL: bad variable type near {tv!r}"
                            )
                        consumed += 1
                    if consumed == 0:
                        raise CypherSyntaxError(
                            f"GraphQL: missing type for ${vname}"
                        )
                    if self.peek()[1] == "=":
                        self.next()
                        var_defaults[vname] = self.parse_value()
                self.expect(")")
        selections = self.parse_selection_set()
        return {
            "operation": op,
            "name": name,
            "selections": selections,
            "var_defaults": var_defaults,
        }

    def parse_selection_set(self) -> list[dict]:
        self.expect("{")
        out = []
        while self.peek()[1] != "}":
            out.append(self.parse_field())
        self.expect("}")
        return out

    def parse_field(self) -> dict:
        kind, name = self.next()
        if kind == "spread":
            # ...FragmentName | ... on Type { ... }
            nk, nv = self.peek()
            if nv == "on":
                self.next()
                ftype = self.next()[1]
                directives = self.parse_directives()
                return {"inline": ftype, "directives": directives,
                        "selections": self.parse_selection_set()}
            if nk != "name":
                raise CypherSyntaxError("GraphQL: expected fragment name after '...'")
            fname = self.next()[1]
            return {"spread": fname, "directives": self.parse_directives()}
        if kind != "name":
            raise CypherSyntaxError(f"GraphQL: expected field name, got {name!r}")
        alias = None
        if self.peek()[1] == ":":
            self.next()
            alias, name = name, self.next()[1]
        args = {}
        if self.peek()[1] == "(":
            self.next()
            while self.peek()[1] != ")":
                akind, aname = self.next()
                self.expect(":")
                args[aname] = self.parse_value()
            self.expect(")")
        directives = self.parse_directives()
        sub = None
        if self.peek()[1] == "{":
            sub = self.parse_selection_set()
        return {"name": name, "alias": alias or name, "args": args,
                "directives": directives, "selections": sub}

    def parse_directives(self) -> list[dict]:
        out = []
        while self.peek()[1] == "@":
            self.next()
            dname = self.next()[1]
            dargs = {}
            if self.peek()[1] == "(":
                self.next()
                while self.peek()[1] != ")":
                    ak, an = self.next()
                    self.expect(":")
                    dargs[an] = self.parse_value()
                self.expect(")")
            out.append({"name": dname, "args": dargs})
        return out

    def parse_value(self) -> Any:
        kind, v = self.next()
        if kind == "string":
            return json.loads(v)
        if kind == "int":
            return int(v)
        if kind == "float":
            return float(v)
        if kind == "name":
            if v == "true":
                return True
            if v == "false":
                return False
            if v == "null":
                return None
            return v  # enum-ish
        if v == "$":
            return _Var(self.next()[1])
        if v == "[":
            out = []
            while self.peek()[1] != "]":
                out.append(self.parse_value())
            self.next()
            return out
        if v == "{":
            out = {}
            while self.peek()[1] != "}":
                k = self.next()[1]
                self.expect(":")
                out[k] = self.parse_value()
            self.next()
            return out
        raise CypherSyntaxError(f"GraphQL: unexpected value token {v!r}")


class _Var:
    def __init__(self, name: str):
        self.name = name

    def __eq__(self, other):  # same $var in two selections must merge cleanly
        return isinstance(other, _Var) and other.name == self.name

    def __hash__(self):
        return hash(("_Var", self.name))


# document memo (same rationale as the Cypher AST memo, cypher/parser.py:1127:
# re-parsing identical documents dominated repeat-query time; parsed docs are
# execution-immutable — merging/flattening copies selection dicts before
# mutating). Epoch eviction: clear at cap, zero bookkeeping on hits.
_DOC_CACHE: dict[str, dict] = {}
_DOC_LOCK = threading.Lock()
_DOC_CACHE_MAX = 256


def parse_document_cached(query: str) -> dict:
    with _DOC_LOCK:
        doc = _DOC_CACHE.get(query)
    if doc is not None:
        return doc
    doc = _Parser(query).parse_document()
    with _DOC_LOCK:
        if len(_DOC_CACHE) >= _DOC_CACHE_MAX:
            _DOC_CACHE.clear()
        _DOC_CACHE[query] = doc
    return doc


def parse_operation(query: str) -> str:
    """Operation type of a document ("query"/"mutation"); "query" on parse
    failure (the executor will produce the real error)."""
    try:
        return parse_document_cached(query)["operation"]
    except Exception:  # nornlint: disable=NL-ERR02
        return "query"


def _resolve_args(args: dict, variables: dict) -> dict:
    def res(v):
        if isinstance(v, _Var):
            return variables.get(v.name)
        if isinstance(v, list):
            return [res(x) for x in v]
        if isinstance(v, dict):
            return {k: res(x) for k, x in v.items()}
        return v

    return {k: res(v) for k, v in args.items()}


def _node_obj(n: Node) -> dict:
    return {
        "__typename": "Node",
        "id": n.id,
        "labels": list(n.labels),
        "properties": dict(n.properties),
        "decayScore": n.decay_score,
        "accessCount": n.access_count,
    }


def _edge_obj(e: Edge) -> dict:
    return {
        "__typename": "Relationship",
        "id": e.id,
        "type": e.type,
        "from": e.start_node,
        "to": e.end_node,
        "properties": dict(e.properties),
        "confidence": e.confidence,
        "autoGenerated": e.auto_generated,
    }


def _directive_allows(directives: list[dict], variables: dict) -> bool:
    """Evaluate @include(if:)/@skip(if:) (the two spec-mandated directives).
    A missing `if` or undefined variable is an error, not a silent drop —
    the spec types `if` as Boolean! and undefined variables fail validation."""
    for d in directives or []:
        if d["name"] not in ("include", "skip"):
            continue  # unknown directives are ignored, matching lenient servers
        if "if" not in d["args"]:
            raise CypherSyntaxError(
                f"GraphQL: @{d['name']} requires an 'if' argument"
            )
        cond = d["args"]["if"]
        if isinstance(cond, _Var):
            if cond.name not in variables:
                raise CypherSyntaxError(
                    f"GraphQL: undefined variable ${cond.name} in @{d['name']}"
                )
            cond = variables[cond.name]
        if d["name"] == "include" and not cond:
            return False
        if d["name"] == "skip" and cond:
            return False
    return True


def _flatten_selections(
    selections: list[dict],
    fragments: dict[str, dict],
    variables: dict,
    typename: Optional[str],
    _depth: int = 0,
) -> list[dict]:
    """Expand fragment spreads / inline fragments into plain fields,
    honoring type conditions and @include/@skip."""
    if _depth > 16:
        raise CypherSyntaxError("GraphQL: fragment nesting too deep (cycle?)")
    out: list[dict] = []
    for sel in selections:
        if not _directive_allows(sel.get("directives"), variables):
            continue
        if "spread" in sel:
            frag = fragments.get(sel["spread"])
            if frag is None:
                raise CypherSyntaxError(
                    f"GraphQL: unknown fragment {sel['spread']!r}"
                )
            if typename is None or frag["type"] == typename:
                out.extend(_flatten_selections(
                    frag["selections"], fragments, variables, typename,
                    _depth + 1))
        elif "inline" in sel:
            if typename is None or sel["inline"] == typename:
                out.extend(_flatten_selections(
                    sel["selections"], fragments, variables, typename,
                    _depth + 1))
        else:
            out.append(sel)
    return _merge_fields(out)


def _merge_fields(selections: list[dict]) -> list[dict]:
    """Spec field merging: same response key selected twice (the normal
    composed-fragments pattern) concatenates sub-selections instead of
    last-wins, and the resolver runs once per key."""
    by_alias: dict[str, dict] = {}
    order: list[str] = []
    for sel in selections:
        prev = by_alias.get(sel["alias"])
        if prev is None:
            by_alias[sel["alias"]] = dict(sel)
            order.append(sel["alias"])
        else:
            if prev["name"] != sel["name"] or prev["args"] != sel["args"]:
                # spec: OverlappingFieldsCanBeMerged — same response key
                # with different field/args is a document error, not a
                # silent last-wins
                raise CypherSyntaxError(
                    f"GraphQL: fields for key {sel['alias']!r} conflict "
                    "(different field or arguments)"
                )
            if sel["selections"] and prev["selections"]:
                prev["selections"] = prev["selections"] + sel["selections"]
            elif sel["selections"]:
                prev["selections"] = sel["selections"]
    return [by_alias[a] for a in order]


def _validate_spreads(selections: list[dict], fragments: dict[str, dict]) -> None:
    """Document-level validation: every ...spread must name a known fragment
    (real GraphQL validates before execution, so empty results still error)."""
    for sel in selections:
        if "spread" in sel:
            if sel["spread"] not in fragments:
                raise CypherSyntaxError(
                    f"GraphQL: unknown fragment {sel['spread']!r}"
                )
        elif sel.get("selections"):
            _validate_spreads(sel["selections"], fragments)


def _project(
    value: Any,
    selections: Optional[list[dict]],
    fragments: dict[str, dict],
    variables: dict,
) -> Any:
    """Apply a selection set to a result (GraphQL field projection)."""
    if selections is None or value is None:
        return value
    if isinstance(value, list):
        return [_project(v, selections, fragments, variables) for v in value]
    if not isinstance(value, dict):
        return value
    flat = _flatten_selections(
        selections, fragments, variables, value.get("__typename"))
    out = {}
    for sel in flat:
        if sel["name"] == "__typename":
            out[sel["alias"]] = value.get("__typename")
        else:
            out[sel["alias"]] = _project(
                value.get(sel["name"]), sel["selections"], fragments, variables)
    return out


class GraphQLExecutor:
    """(ref: pkg/graphql/handler.go + resolvers/)"""

    def __init__(self, db):
        self.db = db

    def execute(self, query: str, variables: Optional[dict] = None) -> dict:
        variables = dict(variables or {})
        try:
            doc = parse_document_cached(query)
            for k, v in doc.get("var_defaults", {}).items():
                variables.setdefault(k, v)
            fragments = doc.get("fragments", {})
            _validate_spreads(doc["selections"], fragments)
            for frag in fragments.values():
                _validate_spreads(frag["selections"], fragments)
            root_type = "Query" if doc["operation"] == "query" else "Mutation"
            root = _flatten_selections(
                doc["selections"], fragments, variables, root_type)
        except Exception as e:
            return {"errors": [{"message": f"parse error: {e}"}]}
        data = {}
        errors = []
        for sel in root:
            try:
                if sel["name"] == "__typename":
                    data[sel["alias"]] = root_type
                    continue
                args = _resolve_args(sel["args"], variables)
                value = self._resolve(doc["operation"], sel["name"], args)
                data[sel["alias"]] = _project(
                    value, sel["selections"], fragments, variables)
            except Exception as e:
                errors.append({"message": str(e), "path": [sel["alias"]]})
                data[sel["alias"]] = None
        out: dict[str, Any] = {"data": data}
        if errors:
            out["errors"] = errors
        return out

    # -- resolvers ----------------------------------------------------------
    def _resolve(self, op: str, field: str, args: dict) -> Any:
        db = self.db
        if op == "query":
            if field == "__schema":
                return _introspection_schema()
            if field == "__type":
                want = args.get("name")
                for t in _introspection_schema()["types"]:
                    if t["name"] == want:
                        return t
                return None
            if field == "node":
                return _node_obj(db.storage.get_node(args["id"]))
            if field == "nodes":
                label = args.get("label")
                limit = int(args.get("limit", 100))
                nodes = (
                    db.storage.get_nodes_by_label(label)
                    if label
                    else list(db.storage.all_nodes())
                )
                return [_node_obj(n) for n in sorted(nodes, key=lambda n: n.id)[:limit]]
            if field == "relationships":
                rtype = args.get("type")
                limit = int(args.get("limit", 100))
                edges = (
                    db.storage.get_edges_by_type(rtype)
                    if rtype
                    else list(db.storage.all_edges())
                )
                return [_edge_obj(e) for e in sorted(edges, key=lambda e: e.id)[:limit]]
            if field == "search":
                results = db.search.search(
                    args.get("query", ""), limit=int(args.get("limit", 10))
                )
                return [
                    {
                        "__typename": "SearchResult",
                        "id": r["id"],
                        "score": r["score"],
                        "content": r["content"],
                        "node": _node_obj(r["node"]),
                    }
                    for r in results
                ]
            if field == "similar":
                node = db.storage.get_node(args["id"])
                if node.embedding is None:
                    return []
                hits = db.search.vector_candidates(
                    node.embedding, k=int(args.get("limit", 10)) + 1
                )
                return [
                    {"__typename": "SimilarResult", "id": i, "score": s}
                    for i, s in hits if i != node.id
                ][: int(args.get("limit", 10))]
            if field == "cypher":
                result = db.executor.execute(
                    args.get("statement", ""), args.get("parameters") or {}
                )
                from nornicdb_tpu.server.http import _jsonable

                return {
                    "__typename": "CypherResult",
                    "columns": result.columns,
                    "rows": [[_jsonable(v) for v in row] for row in result.rows],
                    "stats": result.stats.as_dict(),
                }
            if field == "neighbors":
                nodes = db.neighbors(args["id"], depth=int(args.get("depth", 1)))
                return [_node_obj(n) for n in nodes]
            if field == "stats":
                return {
                    "__typename": "Stats",
                    "nodes": db.storage.node_count(),
                    "edges": db.storage.edge_count(),
                    "pendingEmbeddings": len(db.storage.pending_embed_ids()),
                }
            raise NornicError(f"unknown query field {field}")
        if op == "mutation":
            if field == "createNode":
                node = Node(
                    labels=list(args.get("labels") or []),
                    properties=dict(args.get("properties") or {}),
                )
                return _node_obj(db.storage.create_node(node))
            if field == "updateNode":
                node = db.storage.get_node(args["id"])
                node.properties.update(args.get("properties") or {})
                return _node_obj(db.storage.update_node(node))
            if field == "deleteNode":
                db.storage.delete_node(args["id"])
                return True
            if field == "createRelationship":
                edge = Edge(
                    start_node=args["from"],
                    end_node=args["to"],
                    type=args.get("type", "RELATED_TO"),
                    properties=dict(args.get("properties") or {}),
                )
                return _edge_obj(db.storage.create_edge(edge))
            if field == "deleteRelationship":
                db.storage.delete_edge(args["id"])
                return True
            raise NornicError(f"unknown mutation field {field}")
        raise NornicError(f"unknown operation {op}")


# -- introspection (ref: pkg/graphql gqlgen emits the full spec schema;
# this is the minimal subset clients use for capability probing) ------------

def _t(name: str, kind: str = "SCALAR") -> dict:
    return {"__typename": "__Type", "kind": kind, "name": name, "ofType": None}


def _list(inner: dict) -> dict:
    """Spec wrapper type: kind LIST has name=null and ofType=element."""
    return {"__typename": "__Type", "kind": "LIST", "name": None,
            "ofType": inner}


def _f(name: str, type_: dict, args: Optional[list] = None) -> dict:
    return {
        "__typename": "__Field",
        "name": name,
        "args": args or [],
        "type": type_,
        "isDeprecated": False,
        "deprecationReason": None,
    }


def _arg(name: str, type_: dict) -> dict:
    return {"__typename": "__InputValue", "name": name, "type": type_,
            "defaultValue": None}


def _obj(name: str, fields: list[dict]) -> dict:
    return {
        "__typename": "__Type",
        "kind": "OBJECT",
        "name": name,
        "fields": fields,
        "ofType": None,
        "interfaces": [],
        "possibleTypes": None,
        "enumValues": None,
        "inputFields": None,
    }


def _introspection_schema() -> dict:
    STR, INT, BOOL, JSONT, ID = (
        _t("String"), _t("Int"), _t("Boolean"), _t("JSON"), _t("ID"))
    node = _obj("Node", [
        _f("id", ID), _f("labels", _list(_t("String", "SCALAR"))),
        _f("properties", JSONT), _f("decayScore", _t("Float")),
        _f("accessCount", INT),
    ])
    rel = _obj("Relationship", [
        _f("id", ID), _f("type", STR), _f("from", ID), _f("to", ID),
        _f("properties", JSONT), _f("confidence", _t("Float")),
        _f("autoGenerated", BOOL),
    ])
    search_result = _obj("SearchResult", [
        _f("id", ID), _f("score", _t("Float")), _f("content", STR),
        _f("node", _t("Node", "OBJECT")),
    ])
    cypher_result = _obj("CypherResult", [
        _f("columns", _list(_t("String", "SCALAR"))), _f("rows", JSONT),
        _f("stats", JSONT),
    ])
    stats = _obj("Stats", [
        _f("nodes", INT), _f("edges", INT), _f("pendingEmbeddings", INT),
    ])
    query = _obj("Query", [
        _f("node", _t("Node", "OBJECT"), [_arg("id", ID)]),
        _f("nodes", _list(_t("Node", "OBJECT")),
           [_arg("label", STR), _arg("limit", INT)]),
        _f("relationships", _list(_t("Relationship", "OBJECT")),
           [_arg("type", STR), _arg("limit", INT)]),
        _f("search", _list(_t("SearchResult", "OBJECT")),
           [_arg("query", STR), _arg("limit", INT)]),
        _f("similar", JSONT, [_arg("id", ID), _arg("limit", INT)]),
        _f("cypher", _t("CypherResult", "OBJECT"),
           [_arg("statement", STR), _arg("parameters", JSONT)]),
        _f("neighbors", _list(_t("Node", "OBJECT")),
           [_arg("id", ID), _arg("depth", INT)]),
        _f("stats", _t("Stats", "OBJECT")),
    ])
    mutation = _obj("Mutation", [
        _f("createNode", _t("Node", "OBJECT"),
           [_arg("labels", _list(_t("String", "SCALAR"))), _arg("properties", JSONT)]),
        _f("updateNode", _t("Node", "OBJECT"),
           [_arg("id", ID), _arg("properties", JSONT)]),
        _f("deleteNode", BOOL, [_arg("id", ID)]),
        _f("createRelationship", _t("Relationship", "OBJECT"),
           [_arg("from", ID), _arg("to", ID), _arg("type", STR),
            _arg("properties", JSONT)]),
        _f("deleteRelationship", BOOL, [_arg("id", ID)]),
    ])
    return {
        "__typename": "__Schema",
        "queryType": {"__typename": "__Type", "name": "Query"},
        "mutationType": {"__typename": "__Type", "name": "Mutation"},
        "subscriptionType": None,
        "types": [query, mutation, node, rel, search_result, cypher_result,
                  stats, STR, INT, BOOL, _t("Float"), ID, JSONT],
        "directives": [
            {"__typename": "__Directive", "name": "include",
             "locations": ["FIELD"], "args": [_arg("if", BOOL)]},
            {"__typename": "__Directive", "name": "skip",
             "locations": ["FIELD"], "args": [_arg("if", BOOL)]},
        ],
    }
