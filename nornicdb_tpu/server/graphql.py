"""GraphQL API: hand-rolled executor for the reference's GraphQL surface.

Behavioral reference: /root/reference/pkg/graphql/ — gqlgen-based schema with
node/edge CRUD, search, Cypher pass-through and traversals (handler.go,
schema/, resolvers/). graphql-core is not in this image, so this module
implements a small GraphQL subset natively: query/mutation operations,
field arguments (literals + $variables), nested selection sets (projected
onto results), aliases. No fragments/directives yet.

Root fields:
  query:    node(id) nodes(label, limit) relationships(type, limit)
            search(query, limit) similar(id, limit) cypher(statement,
            parameters) neighbors(id, depth) stats
  mutation: createNode(labels, properties) updateNode(id, properties)
            deleteNode(id) createRelationship(from, to, type, properties)
            deleteRelationship(id)
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

from nornicdb_tpu.errors import CypherSyntaxError, NornicError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Node

_TOKEN = re.compile(
    r"""(?P<ws>[\s,]+|\#[^\n]*)|(?P<name>[_A-Za-z][_0-9A-Za-z]*)"""
    r"""|(?P<string>"(?:\\.|[^"\\])*")|(?P<float>-?\d+\.\d+)"""
    r"""|(?P<int>-?\d+)|(?P<punct>[{}()\[\]:$=!@])|(?P<spread>\.\.\.)"""
)


class _Parser:
    def __init__(self, src: str):
        self.tokens = []
        last_end = 0
        for m in _TOKEN.finditer(src):
            if m.start() != last_end:
                raise CypherSyntaxError(
                    f"GraphQL: unexpected character {src[last_end]!r}"
                )
            last_end = m.end()
            kind = m.lastgroup
            if kind != "ws":
                self.tokens.append((kind, m.group(0)))
        if last_end != len(src):
            raise CypherSyntaxError(
                f"GraphQL: unexpected character {src[last_end]!r}"
            )
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else ("eof", "")

    def next(self):
        t = self.peek()
        if t[0] == "eof":
            raise CypherSyntaxError("GraphQL: unexpected end of query")
        self.pos += 1
        return t

    def expect(self, value: str):
        kind, v = self.next()
        if v != value:
            raise CypherSyntaxError(f"GraphQL: expected {value!r}, got {v!r}")

    def parse_document(self) -> dict:
        kind, v = self.peek()
        op = "query"
        name = None
        variables: dict[str, Any] = {}
        if v in ("query", "mutation"):
            op = v
            self.next()
            if self.peek()[0] == "name":
                name = self.next()[1]
            if self.peek()[1] == "(":
                self.next()
                while self.peek()[1] != ")":
                    self.expect("$")
                    self.next()  # var name
                    self.expect(":")
                    while self.peek()[1] not in (")", "$"):
                        self.next()  # skip type tokens incl. ! and defaults
                self.expect(")")
        selections = self.parse_selection_set()
        return {"operation": op, "name": name, "selections": selections}

    def parse_selection_set(self) -> list[dict]:
        self.expect("{")
        out = []
        while self.peek()[1] != "}":
            out.append(self.parse_field())
        self.expect("}")
        return out

    def parse_field(self) -> dict:
        kind, name = self.next()
        if kind != "name":
            raise CypherSyntaxError(f"GraphQL: expected field name, got {name!r}")
        alias = None
        if self.peek()[1] == ":":
            self.next()
            alias, name = name, self.next()[1]
        args = {}
        if self.peek()[1] == "(":
            self.next()
            while self.peek()[1] != ")":
                akind, aname = self.next()
                self.expect(":")
                args[aname] = self.parse_value()
            self.expect(")")
        sub = None
        if self.peek()[1] == "{":
            sub = self.parse_selection_set()
        return {"name": name, "alias": alias or name, "args": args,
                "selections": sub}

    def parse_value(self) -> Any:
        kind, v = self.next()
        if kind == "string":
            return json.loads(v)
        if kind == "int":
            return int(v)
        if kind == "float":
            return float(v)
        if kind == "name":
            if v == "true":
                return True
            if v == "false":
                return False
            if v == "null":
                return None
            return v  # enum-ish
        if v == "$":
            return _Var(self.next()[1])
        if v == "[":
            out = []
            while self.peek()[1] != "]":
                out.append(self.parse_value())
            self.next()
            return out
        if v == "{":
            out = {}
            while self.peek()[1] != "}":
                k = self.next()[1]
                self.expect(":")
                out[k] = self.parse_value()
            self.next()
            return out
        raise CypherSyntaxError(f"GraphQL: unexpected value token {v!r}")


class _Var:
    def __init__(self, name: str):
        self.name = name


def parse_operation(query: str) -> str:
    """Operation type of a document ("query"/"mutation"); "query" on parse
    failure (the executor will produce the real error)."""
    try:
        return _Parser(query).parse_document()["operation"]
    except Exception:
        return "query"


def _resolve_args(args: dict, variables: dict) -> dict:
    def res(v):
        if isinstance(v, _Var):
            return variables.get(v.name)
        if isinstance(v, list):
            return [res(x) for x in v]
        if isinstance(v, dict):
            return {k: res(x) for k, x in v.items()}
        return v

    return {k: res(v) for k, v in args.items()}


def _node_obj(n: Node) -> dict:
    return {
        "id": n.id,
        "labels": list(n.labels),
        "properties": dict(n.properties),
        "decayScore": n.decay_score,
        "accessCount": n.access_count,
    }


def _edge_obj(e: Edge) -> dict:
    return {
        "id": e.id,
        "type": e.type,
        "from": e.start_node,
        "to": e.end_node,
        "properties": dict(e.properties),
        "confidence": e.confidence,
        "autoGenerated": e.auto_generated,
    }


def _project(value: Any, selections: Optional[list[dict]]) -> Any:
    """Apply a selection set to a result (GraphQL field projection)."""
    if selections is None or value is None:
        return value
    if isinstance(value, list):
        return [_project(v, selections) for v in value]
    if not isinstance(value, dict):
        return value
    out = {}
    for sel in selections:
        out[sel["alias"]] = _project(value.get(sel["name"]), sel["selections"])
    return out


class GraphQLExecutor:
    """(ref: pkg/graphql/handler.go + resolvers/)"""

    def __init__(self, db):
        self.db = db

    def execute(self, query: str, variables: Optional[dict] = None) -> dict:
        variables = variables or {}
        try:
            doc = _Parser(query).parse_document()
        except Exception as e:
            return {"errors": [{"message": f"parse error: {e}"}]}
        data = {}
        errors = []
        for sel in doc["selections"]:
            try:
                args = _resolve_args(sel["args"], variables)
                value = self._resolve(doc["operation"], sel["name"], args)
                data[sel["alias"]] = _project(value, sel["selections"])
            except Exception as e:
                errors.append({"message": str(e), "path": [sel["alias"]]})
                data[sel["alias"]] = None
        out: dict[str, Any] = {"data": data}
        if errors:
            out["errors"] = errors
        return out

    # -- resolvers ----------------------------------------------------------
    def _resolve(self, op: str, field: str, args: dict) -> Any:
        db = self.db
        if op == "query":
            if field == "node":
                return _node_obj(db.storage.get_node(args["id"]))
            if field == "nodes":
                label = args.get("label")
                limit = int(args.get("limit", 100))
                nodes = (
                    db.storage.get_nodes_by_label(label)
                    if label
                    else list(db.storage.all_nodes())
                )
                return [_node_obj(n) for n in sorted(nodes, key=lambda n: n.id)[:limit]]
            if field == "relationships":
                rtype = args.get("type")
                limit = int(args.get("limit", 100))
                edges = (
                    db.storage.get_edges_by_type(rtype)
                    if rtype
                    else list(db.storage.all_edges())
                )
                return [_edge_obj(e) for e in sorted(edges, key=lambda e: e.id)[:limit]]
            if field == "search":
                results = db.search.search(
                    args.get("query", ""), limit=int(args.get("limit", 10))
                )
                return [
                    {
                        "id": r["id"],
                        "score": r["score"],
                        "content": r["content"],
                        "node": _node_obj(r["node"]),
                    }
                    for r in results
                ]
            if field == "similar":
                node = db.storage.get_node(args["id"])
                if node.embedding is None:
                    return []
                hits = db.search.vector_candidates(
                    node.embedding, k=int(args.get("limit", 10)) + 1
                )
                return [
                    {"id": i, "score": s} for i, s in hits if i != node.id
                ][: int(args.get("limit", 10))]
            if field == "cypher":
                result = db.executor.execute(
                    args.get("statement", ""), args.get("parameters") or {}
                )
                from nornicdb_tpu.server.http import _jsonable

                return {
                    "columns": result.columns,
                    "rows": [[_jsonable(v) for v in row] for row in result.rows],
                    "stats": result.stats.as_dict(),
                }
            if field == "neighbors":
                nodes = db.neighbors(args["id"], depth=int(args.get("depth", 1)))
                return [_node_obj(n) for n in nodes]
            if field == "stats":
                return {
                    "nodes": db.storage.node_count(),
                    "edges": db.storage.edge_count(),
                    "pendingEmbeddings": len(db.storage.pending_embed_ids()),
                }
            raise NornicError(f"unknown query field {field}")
        if op == "mutation":
            if field == "createNode":
                node = Node(
                    labels=list(args.get("labels") or []),
                    properties=dict(args.get("properties") or {}),
                )
                return _node_obj(db.storage.create_node(node))
            if field == "updateNode":
                node = db.storage.get_node(args["id"])
                node.properties.update(args.get("properties") or {})
                return _node_obj(db.storage.update_node(node))
            if field == "deleteNode":
                db.storage.delete_node(args["id"])
                return True
            if field == "createRelationship":
                edge = Edge(
                    start_node=args["from"],
                    end_node=args["to"],
                    type=args.get("type", "RELATED_TO"),
                    properties=dict(args.get("properties") or {}),
                )
                return _edge_obj(db.storage.create_edge(edge))
            if field == "deleteRelationship":
                db.storage.delete_edge(args["id"])
                return True
            raise NornicError(f"unknown mutation field {field}")
        raise NornicError(f"unknown operation {op}")
