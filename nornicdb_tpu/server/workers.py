"""Prefork protocol workers: multi-core scale-out for the protocol surface.

The reference's Go runtime spreads its protocol handling across cores for
free (goroutines; the testing/e2e/README.md numbers come from a multi-core
box). A CPython server needs worker PROCESSES for the same effect — this
module provides them for the HTTP surface (search REST + GraphQL + the rest
of the REST API) and the native gRPC search service.

Architecture
------------
The primary process owns the DB — and the TPU client: the chip has one
owner, so compute stays centralized while the GIL-bound protocol work
(socket accept, HTTP parse, JSON/protobuf encode) fans out.

N worker processes bind the SAME public port with SO_REUSEPORT; the kernel
load-balances connection accepts across them. Workers are protocol
frontends:

- hot read endpoints (/nornicdb/search, /nornicdb/similar, read-only
  /graphql documents, /metrics, /health, /status) are served from a
  generation-stamped response cache. The generation is a shared-memory
  counter the primary bumps on every storage event, so worker caches die
  the moment anything mutates — the exact contract of the in-process
  ResponseCache (server/respcache.py), stretched across processes.
- everything else (writes, Cypher tx, auth, admin, cache misses) is
  proxied to the primary's loopback listener over per-thread keep-alive
  connections.

Workers never touch JAX: they are plain subprocesses running
`python -m nornicdb_tpu.server.worker_main <json-config>` (no inherited TPU
client state, no fork-unsafety with the primary's background threads, and
— unlike multiprocessing's spawn — no re-import of the parent's __main__,
so the pool works from REPLs and stdin scripts too). The shared generation
counter lives in an mmap'd temp file both sides map.

Device access without device ownership (the worker-scaling hot path):

- **vector search** (REST ``/nornicdb/search`` with a ``vector`` body;
  native gRPC SearchRequest.vector) is served through the primary's
  device broker (server/broker.py): the worker ships a compact binary
  query block over a Unix socket and the broker fuses queries from ALL
  workers into one device program per batch window. A shed comes back as
  429 / RESOURCE_EXHAUSTED (the PR 8 taxonomy, end to end).
- **degraded / broker-down fallback**: when the broker answers DEGRADED
  (backend serving from host arrays) or the socket is gone, the worker
  serves an exact host search from the shared-memory read plane
  (server/readplane.py) — the same one copy of the corpus every worker
  maps — and only proxies to the primary when no segment is published.
- every response says how it was served (``X-Nornic-Served``:
  cache | broker | shm | proxy) so benches and soak invariants can prove
  the intended path actually ran.

The pool also owns worker lifecycle: a monitor thread respawns crashed
workers (same worker id, same config) so a kill -9 during a fault window
costs capacity for under a second, not forever.

Client identity: every proxied request carries X-Forwarded-For with the
real peer address, and the primary prefers that header for loopback peers
when keying its rate limiter (http.py _client_ip). Workers additionally
apply the same token-bucket rate limit BEFORE cache lookup when the pool
is configured with one, so cache hits cannot bypass limiting.
"""

from __future__ import annotations

import json
import logging
import mmap
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from nornicdb_tpu.server.respcache import ResponseCache
from nornicdb_tpu.telemetry.federation import (
    FLEET,
    WORKER_BROKER_RTT,
    WORKER_REQUESTS,
)
from nornicdb_tpu.telemetry.slowlog import slow_log as _slow_log
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

log = logging.getLogger(__name__)

_ACTIVE_POOLS: "list[weakref.ref]" = []
_ACTIVE_POOLS_LOCK = threading.Lock()


def active_pool_stats() -> list[dict]:
    """Stats of every live WorkerPool (the /admin/stats "workers"
    section)."""
    out = []
    with _ACTIVE_POOLS_LOCK:
        refs = list(_ACTIVE_POOLS)
    for ref in refs:
        pool = ref()
        if pool is not None:
            out.append(pool.stats())
    return out


def active_pool_fleet_states() -> list[dict]:
    """Per-worker liveness/respawn state of every live pool — the
    /admin/stats ``fleet`` section's pool half (kept OUT of
    active_pool_stats so the response carries it once)."""
    out = []
    with _ACTIVE_POOLS_LOCK:
        refs = list(_ACTIVE_POOLS)
    for ref in refs:
        pool = ref()
        if pool is not None:
            out.append({
                "kind": pool.kind,
                "n_workers": pool.n_workers,
                "alive": pool.alive(),
                "respawns": pool.respawns,
                "workers": pool.worker_states(),
            })
    return out


class GenerationFile:
    """A cross-process monotonic counter in an mmap'd 16-byte seqlock.

    Single writer (the primary), many readers (workers). mmap slice
    assignment is a memcpy with no atomicity guarantee, so a bare
    double-read can still snapshot a *stable* torn value if the writer is
    descheduled mid-copy. Layout instead is a seqlock:
    bytes [0:4) sequence, [4:12) value. The writer bumps seq to odd,
    writes the value, bumps seq to even; a reader retries while seq is odd
    or changed across the value read — a mid-copy writer can never
    produce a stable-looking torn value."""

    _SIZE = 16  # 4B seq + 8B value + 4B pad

    def __init__(self, path: Optional[str] = None):
        self._own = path is None
        if path is None:
            fd, path = tempfile.mkstemp(prefix="nornic-gen-")
            os.write(fd, b"\x00" * self._SIZE)
            os.close(fd)
        self.path = path
        self._f = open(path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), self._SIZE)
        self._local = 0
        self._seq = 0

    @property
    def value(self) -> int:
        # bounded: if the writer died mid-write (seq stuck odd), return the
        # value anyway — a possibly-torn generation only mis-keys a cache
        # entry, and with the writer gone there will be no more bumps
        for _ in range(1000):
            s1 = int.from_bytes(self._mm[0:4], "little")
            if s1 & 1:
                continue
            v = bytes(self._mm[4:12])
            s2 = int.from_bytes(self._mm[0:4], "little")
            if s1 == s2:
                return int.from_bytes(v, "little")
        return int.from_bytes(self._mm[4:12], "little")

    def bump(self) -> None:
        self._local += 1
        self._seq += 1
        self._mm[0:4] = (self._seq & 0xFFFFFFFF).to_bytes(4, "little")  # odd
        self._mm[4:12] = self._local.to_bytes(8, "little")
        self._seq += 1
        self._mm[0:4] = (self._seq & 0xFFFFFFFF).to_bytes(4, "little")  # even

    def close(self) -> None:
        try:
            self._mm.close()
            self._f.close()
        except (OSError, ValueError):
            pass  # already closed
        if self._own:
            try:
                os.unlink(self.path)
            except OSError:
                pass

class WorkerReadPath:
    """A worker's device-access bundle: the broker client plus the
    shared-memory fallback readers, built lazily from the pool config.

    ``search`` implements the serving ladder: broker (fused device
    dispatch) → shared-memory exact host scan (broker down / backend
    degraded) → raise LookupError (caller proxies to the primary).
    Sheds (ResourceExhausted) propagate — they are backpressure, not
    unavailability, and must surface as 429/RESOURCE_EXHAUSTED."""

    def __init__(self, broker_path: Optional[str],
                 corpus_seg: Optional[str],
                 adjacency_seg: Optional[str] = None,
                 proc: str = "worker"):
        self.broker_path = broker_path
        self.corpus_seg = corpus_seg
        self.adjacency_seg = adjacency_seg
        self.proc = proc
        self._client = None
        self._corpus_reader = None
        self.served = {"broker": 0, "shm": 0}
        # async trace shipment (ship_trace): lazily-started single
        # shipper thread + bounded queue; drops counted, never blocking
        self._ship_queue = None
        self._ship_lock = threading.Lock()
        self.ship_drops = 0

    def _broker(self):
        if self._client is None and self.broker_path:
            from nornicdb_tpu.server.broker import BrokerClient

            self._client = BrokerClient(self.broker_path)
        return self._client

    def _shared_corpus(self):
        if self._corpus_reader is None and self.corpus_seg:
            from nornicdb_tpu.server.readplane import SharedCorpusReader

            self._corpus_reader = SharedCorpusReader(self.corpus_seg)
        return self._corpus_reader

    def qdrant_search(
        self, collection: str, vector, limit: int, score_threshold: float,
        with_payload: bool,
    ) -> tuple[list, str]:
        """Qdrant points/search through the device plane: the broker
        answers from the primary's shared collection registry (fused
        device dispatch, payload enrichment included). Collection corpora
        have no shared-memory mirror yet (ROADMAP 1b residual: only the
        default search corpus rides the shm plane), so the ladder here is
        broker → LookupError (caller proxies). Raises ResourceExhausted
        on a shed, BrokerError for a real error reply (unknown
        collection) — the caller proxies so the primary owns the 404."""
        from nornicdb_tpu.server.broker import (
            BrokerDegraded,
            BrokerUnavailable,
        )

        client = self._broker()
        if client is None:
            raise LookupError("no broker for qdrant search")
        try:
            hits = client.qdrant_search(
                collection, vector, limit=limit,
                score_threshold=score_threshold, with_payload=with_payload,
            )
        except (BrokerDegraded, BrokerUnavailable) as e:
            log.debug("broker unavailable for qdrant search: %s", e)
            raise LookupError("broker down for qdrant search") from e
        self.served["broker"] += 1
        return hits, "broker"

    def search(
        self, vector, k: int, min_score: float, with_content: bool,
    ) -> tuple[list, str]:
        """One query → ([(id, score, content)], served_by). Raises
        ResourceExhausted on a shed, LookupError when neither the broker
        nor a shared segment can answer."""
        import numpy as np

        from nornicdb_tpu.server.broker import (
            BrokerDegraded,
            BrokerUnavailable,
        )

        q = np.asarray(vector, np.float32).reshape(1, -1)
        client = self._broker()
        if client is not None:
            try:

                t0 = time.perf_counter()
                with _tracer.span("worker.broker_call"):
                    rows = client.search(q, k, min_score,
                                         with_content=with_content)
                WORKER_BROKER_RTT.observe(time.perf_counter() - t0)
                self.served["broker"] += 1
                return rows[0], "broker"
            except (BrokerDegraded, BrokerUnavailable) as e:
                log.debug("broker unavailable for search: %s", e)
        reader = self._shared_corpus()
        if reader is not None:
            from nornicdb_tpu.server.shm import SegmentUnavailable

            try:
                with _tracer.span("worker.shm_search"):
                    rows = reader.search(q, k, min_score)
                self.served["shm"] += 1
                return [(i, s, "") for i, s in rows[0]], "shm"
            except SegmentUnavailable as e:
                log.debug("shared corpus segment unavailable: %s", e)
        raise LookupError("no broker and no shared corpus segment")

    def ship_trace(self, trace_id: Optional[str]) -> None:
        """Queue a finished worker trace's spans for shipment to the
        primary, so /admin/traces/<trace_id> renders one tree spanning
        both processes. Shipment runs on a single background thread
        (bounded queue, drop-on-full): the handler thread must never pay
        a broker round trip AFTER the response it already sent, and a
        dropped shipment under burst costs a trace detail, never a
        request. Best-effort end to end."""
        if not trace_id or self.broker_path is None:
            return
        entry = _tracer.trace(trace_id)
        if entry is None:
            return
        import queue as _queue

        q = self._ship_queue
        if q is None:
            with self._ship_lock:
                q = self._ship_queue
                if q is None:
                    q = self._ship_queue = _queue.Queue(maxsize=64)
                    threading.Thread(
                        target=self._ship_loop,
                        name="nornicdb-worker-trace-ship", daemon=True,
                    ).start()
        try:
            q.put_nowait({k: entry.get(k) for k in
                          ("trace_id", "root", "started", "duration_ms",
                           "spans")})
        except _queue.Full:
            self.ship_drops += 1

    def _ship_loop(self) -> None:
        while True:
            payload = self._ship_queue.get()
            client = self._broker()
            if client is None:
                continue
            try:
                client.ship_spans(payload, proc=self.proc)
            except Exception:
                log.debug("worker trace shipment failed", exc_info=True)


_MUTATION_RE = re.compile(r"\bmutation\b")
# worker-servable Qdrant surface: points/search is read-only and takes a
# raw vector — the broker answers it from the primary's shared registry
_QDRANT_SEARCH_RE = re.compile(r"/collections/([^/]+)/points/search")

# endpoints a worker may answer from its generation-stamped cache; every
# other path is proxied to the primary untouched
_CACHEABLE_GET = ("/metrics", "/health", "/status")
_CACHEABLE_POST = ("/nornicdb/search", "/nornicdb/similar")


def _cacheable(method: str, path: str, body: bytes) -> bool:
    p = path.split("?", 1)[0]
    if method == "GET":
        return p in _CACHEABLE_GET
    if method != "POST":
        return False
    if p in _CACHEABLE_POST:
        return True
    if p == "/graphql":
        # conservative: any document mentioning `mutation` goes to the
        # primary, even inside a string literal — correctness over hit rate
        try:
            q = json.loads(body or b"{}").get("query", "")
        except (ValueError, AttributeError, UnicodeDecodeError):
            return False  # unparseable body: route to primary, never cache
        if not isinstance(q, str):
            return False  # e.g. {"query": null}: primary's problem, not ours
        return not _MUTATION_RE.search(q)
    return False


class _ReuseportHTTPServer(ThreadingHTTPServer):
    allow_reuse_address = True
    daemon_threads = True

    def server_bind(self):
        self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        super().server_bind()


class _FrontendHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "NornicDB-TPU-worker"
    # response writes must flush immediately: header block and body go out
    # as separate send()s, and Nagle + the client's delayed ACK turns that
    # into a ~40ms stall per request (same fix as the primary HTTP server)
    disable_nagle_algorithm = True
    _local = threading.local()

    def log_message(self, *a):  # quiet
        pass

    # -- primary connection (per handler thread, keep-alive) -----------
    def _primary(self):
        import http.client

        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                "127.0.0.1", self.server.primary_port, timeout=30
            )
            conn.connect()
            # proxy requests also go out as header+body send() pairs;
            # without NODELAY each proxied call eats the Nagle stall twice
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self._local.conn = conn
        return conn

    # hop-by-hop headers stay ours; everything else from the primary
    # (Set-Cookie for logins, Location for redirects, CORS headers...)
    # relays through untouched
    _SKIP_RESP_HEADERS = frozenset(
        ("connection", "keep-alive", "transfer-encoding", "content-length")
    )
    _IDEMPOTENT = frozenset(("GET", "HEAD", "OPTIONS"))

    def _proxy(
        self, method: str, body: bytes
    ) -> tuple[int, list[tuple[str, str]], bytes]:
        headers = {}
        for h in ("Content-Type", "Authorization", "Cookie", "Accept",
                  "Origin", "Access-Control-Request-Method",
                  "Access-Control-Request-Headers"):
            v = self.headers.get(h)
            if v:
                headers[h] = v
        # the primary keys its rate limiter and audit on the real client,
        # not the worker's loopback socket (http.py _client_ip)
        prior = self.headers.get("X-Forwarded-For")
        peer = self.client_address[0]
        headers["X-Forwarded-For"] = f"{prior}, {peer}" if prior else peer
        # retry a dropped keep-alive connection only for idempotent methods:
        # a POST whose connection died mid-response may already have
        # executed on the primary, and replaying it would run the write twice
        attempts = (0, 1) if method in self._IDEMPOTENT else (1,)
        for attempt in attempts:
            conn = self._primary()
            try:
                conn.request(method, self.path, body or None, headers)
                resp = conn.getresponse()
                data = resp.read()
                out_headers = [
                    (k, v) for k, v in resp.getheaders()
                    if k.lower() not in self._SKIP_RESP_HEADERS
                ]
                return resp.status, out_headers, data
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass
                self._local.conn = None
                if attempt:
                    raise
                log.debug("proxy connection failed; retrying once",
                          exc_info=True)
        raise RuntimeError("unreachable")

    def _respond(self, status: int, headers: list[tuple[str, str]],
                 data: bytes, cache_state: str) -> None:
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(data)))
        self.send_header("X-Nornic-Worker", str(self.server.worker_id))
        self.send_header("X-Nornic-Cache", cache_state)
        self.end_headers()
        self.wfile.write(data)
        # serving-ladder attribution for the federated exposition: every
        # worker response counts exactly once, by HOW it was served
        if cache_state == "hit":
            served = "cache"
        elif cache_state in ("limited", "error"):
            served = cache_state
        else:
            served = next((v for k, v in headers
                           if k == "X-Nornic-Served"), "proxy")
        WORKER_REQUESTS.labels(served).inc()

    def _handle(self, method: str) -> None:
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        # mirror the primary's token bucket BEFORE the cache lookup, so a
        # hot cached endpoint cannot be hammered past the configured limit
        rl = self.server.rate_limiter
        if rl is not None and not rl.allow(self.client_address[0]):
            msg = json.dumps({"error": "rate limit exceeded"}).encode()
            self._respond(429, [("Content-Type", "application/json")],
                          msg, "limited")
            return
        vec_meta = None  # (k, dims, t0): proxy-served vector search
        try:
            if method == "POST" and \
                    self.path.split("?", 1)[0] == "/nornicdb/search":
                parsed = self._sniff_vector(body)
                if parsed is not None:
                    if self._serve_vector(method, body, parsed):
                        return
                    # device plane could not answer — the primary serves
                    # it via the proxy path below; keep the slow-query
                    # attribution complete (served="proxy"). Malformed
                    # limit/vector values proxy WITHOUT capture — the
                    # primary owns their validation error.

                    shape = self._vec_shape(parsed)
                    if shape is not None:
                        vec_meta = (*shape, time.perf_counter())
            if method == "POST":
                qm = _QDRANT_SEARCH_RE.fullmatch(self.path.split("?", 1)[0])
                if qm is not None:
                    parsed = self._sniff_qdrant(body)
                    if parsed is not None and self._serve_qdrant(
                            qm.group(1), method, body, parsed):
                        return
            if _cacheable(method, self.path, body):
                # auth material is part of the key: a cached response must
                # never leak across differently-privileged tokens
                key = (
                    method,
                    self.path,
                    body,
                    self.headers.get("Authorization", ""),
                    self.headers.get("Cookie", ""),
                )
                cached = self.server.cache.get(key)
                if cached is not None:
                    status, headers, data = cached
                    self._respond(status, headers, data, "hit")
                    return
                gen_before = self.server.cache.generation()
                status, headers, data = self._proxy(method, body)
                if status == 200:
                    self.server.cache.put(
                        key, (status, headers, data), gen_before
                    )
                self._respond(status, headers, data, "miss")
                return
            status, headers, data = self._proxy(method, body)
            self._respond(status, headers, data, "proxy")
        except Exception as e:
            msg = json.dumps({"error": f"worker proxy failure: {e}"}).encode()
            try:
                self._respond(
                    502, [("Content-Type", "application/json")], msg, "error"
                )
            except OSError:
                pass  # client hung up before the error could be written
        finally:
            if vec_meta is not None:

                k, dims, t0 = vec_meta
                _slow_log.maybe_record(
                    f"VECTOR SEARCH k={k} dims={dims}", None,
                    time.perf_counter() - t0, served="proxy",
                )

    # -- broker-served vector search -----------------------------------
    @staticmethod
    def _vec_shape(parsed: dict) -> Optional[tuple[int, int]]:
        """(k, dims) of a sniffed vector request, or None when the
        values are malformed — the primary owns the validation error
        shape, so malformed requests must PROXY, never 502 here."""
        try:
            return int(parsed.get("limit", 10)), len(parsed["vector"])
        except (TypeError, ValueError):
            return None

    @staticmethod
    def _sniff_vector(body: bytes) -> Optional[dict]:
        """The worker-servable request shape: a JSON body with a non-empty
        ``vector`` list. Anything else (hybrid text search, malformed
        JSON) returns None and takes the cache/proxy path untouched."""
        try:
            parsed = json.loads(body or b"{}")
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(parsed, dict):
            return None
        v = parsed.get("vector")
        if not isinstance(v, list) or not v:
            return None
        return parsed

    def _serve_vector(self, method: str, body: bytes,
                      parsed: dict) -> bool:
        """Serve a raw-vector search without touching the primary's
        protocol stack: response cache, then the WorkerReadPath ladder
        (broker → shared segment). Returns False when neither source is
        available — the caller falls through to the proxy path.

        Device-plane serves run under a root trace (continuing the
        client's ``traceparent`` when present): the broker frame carries
        it across the process hop, the finished worker spans ship back
        via MSG_SPANS, and slow searches land in the worker's slow-query
        ring with served-path attribution — federated to the primary's
        /admin/slow-queries by the metrics publisher."""

        from nornicdb_tpu.errors import ResourceExhausted

        read_path = self.server.read_path
        if read_path is None:
            return False
        cache = self.server.cache
        key = (
            method, self.path, body,
            self.headers.get("Authorization", ""),
            self.headers.get("Cookie", ""),
        )
        cached = cache.get(key)
        if cached is not None:
            status, headers, data = cached
            self._respond(status, headers, data, "hit")
            return True
        gen_before = cache.generation()
        shape = self._vec_shape(parsed)
        if shape is None:
            return False  # malformed limit/vector: the primary validates
        k, dims = shape
        t0 = time.perf_counter()
        hits = served = shed = None
        root = _tracer.start_trace(
            "worker.search",
            traceparent=self.headers.get("traceparent"),
            attrs={"proc": read_path.proc, "k": k, "dims": dims},
        )
        with root:
            try:
                hits, served = read_path.search(
                    parsed["vector"], k,
                    float(parsed.get("min_score", -1.0)),
                    with_content=bool(
                        parsed.get("include_content", True)),
                )
                root.set_attr("served", served)
            except ResourceExhausted as e:
                shed = e
                root.set_attr("served", "shed")
            except LookupError:
                pass  # no broker, no segment: proxy to the primary
            except Exception:
                log.warning("worker vector search failed; proxying",
                            exc_info=True)
        duration = time.perf_counter() - t0
        trace_id = getattr(root, "trace_id", None)
        if shed is not None:
            msg = json.dumps(
                {"error": str(shed), "reason": shed.reason}
            ).encode()
            self._respond(
                429,
                [("Content-Type", "application/json"),
                 ("Retry-After", "1")],
                msg, "limited",
            )
            return True
        if served is None:
            return False  # ladder empty: proxy to the primary
        payload = json.dumps({
            "results": [
                {"id": i, "score": s, "content": c} for i, s, c in hits
            ]
        }).encode()
        headers = [("Content-Type", "application/json"),
                   ("X-Nornic-Served", served)]
        # the shm fallback serves without content enrichment — still
        # cacheable (generation-stamped, so any index mutation kills it)
        cache.put(key, (200, headers, payload), gen_before)
        self._respond(200, headers, payload, "miss")
        # satellite: worker-side slow-query capture with served-path
        # attribution (the vector text itself never enters the ring)
        _slow_log.maybe_record(
            f"VECTOR SEARCH k={k} dims={dims}", None, duration,
            trace_id=trace_id, served=served,
        )
        read_path.ship_trace(trace_id)
        return True

    # -- broker-served qdrant points/search ----------------------------
    @staticmethod
    def _sniff_qdrant(body: bytes) -> Optional[dict]:
        """The worker-servable Qdrant search shape: a plain (unnamed)
        vector list and NO payload filter. Filters need a payload scan
        over storage and named vectors need the name-resolved corpus —
        both stay with the primary's protocol stack (proxy)."""
        try:
            parsed = json.loads(body or b"{}")
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(parsed, dict) or parsed.get("filter"):
            return None
        v = parsed.get("vector")
        if not isinstance(v, list) or not v:
            return None
        return parsed

    def _serve_qdrant(self, collection: str, method: str, body: bytes,
                      parsed: dict) -> bool:
        """Serve Qdrant points/search through the broker (the primary's
        shared collection registry — fused device dispatch, payloads
        included), response-shaped exactly like handle_qdrant's reply so
        worker and primary answers are body-identical. Returns False to
        fall through to the proxy path (broker down, unknown collection —
        the primary owns the 404 shape)."""
        from nornicdb_tpu.errors import ResourceExhausted
        from nornicdb_tpu.server.broker import BrokerError

        read_path = self.server.read_path
        if read_path is None:
            return False
        cache = self.server.cache
        key = (
            method, self.path, body,
            self.headers.get("Authorization", ""),
            self.headers.get("Cookie", ""),
        )
        cached = cache.get(key)
        if cached is not None:
            status, headers, data = cached
            self._respond(status, headers, data, "hit")
            return True
        gen_before = cache.generation()
        try:
            hits, served = read_path.qdrant_search(
                collection, parsed["vector"],
                int(parsed.get("limit", 10)),
                float(parsed.get("score_threshold", -1.0)),
                bool(parsed.get("with_payload", True)),
            )
        except ResourceExhausted as e:
            msg = json.dumps({"error": str(e), "reason": e.reason}).encode()
            self._respond(
                429,
                [("Content-Type", "application/json"),
                 ("Retry-After", "1")],
                msg, "limited",
            )
            return True
        except LookupError:
            return False  # no broker: proxy to the primary
        except BrokerError:
            return False  # e.g. collection unknown: primary owns the 404
        except Exception:
            log.warning("worker qdrant search failed; proxying",
                        exc_info=True)
            return False
        payload = json.dumps(
            {"result": hits, "status": "ok", "time": 0.0}
        ).encode()
        headers = [("Content-Type", "application/json"),
                   ("X-Nornic-Served", served)]
        cache.put(key, (200, headers, payload), gen_before)
        self._respond(200, headers, payload, "miss")
        return True

    def do_GET(self):
        self._handle("GET")

    def do_POST(self):
        self._handle("POST")

    def do_PUT(self):
        self._handle("PUT")

    def do_DELETE(self):
        self._handle("DELETE")

    def do_PATCH(self):
        self._handle("PATCH")

    def do_OPTIONS(self):  # CORS preflight must reach the primary
        self._handle("OPTIONS")

    def do_HEAD(self):
        self._handle("HEAD")


def _http_worker_main(host: str, public_port: int, primary_port: int,
                      gen: GenerationFile, worker_id: int,
                      rate_limit: Optional[tuple] = None,
                      read_path: Optional[WorkerReadPath] = None) -> None:
    srv = _ReuseportHTTPServer((host, public_port), _FrontendHandler)
    srv.primary_port = primary_port
    srv.cache = ResponseCache(lambda: gen.value)
    srv.worker_id = worker_id
    srv.read_path = read_path
    if rate_limit:
        from nornicdb_tpu.server.http import RateLimiter

        # per-worker bucket: the kernel spreads a client's connections
        # across workers, so the effective limit is ≤ n_workers × rate —
        # a ceiling, not a precise global bucket, which matches the goal
        # (cache hits must not be unlimited)
        srv.rate_limiter = RateLimiter(rate=rate_limit[0],
                                       burst=int(rate_limit[1]))
    else:
        srv.rate_limiter = None
    srv.serve_forever(poll_interval=0.1)


def _grpc_worker_main(host: str, public_port: int, primary_port: int,
                      gen: GenerationFile, worker_id: int,
                      rate_limit: Optional[tuple] = None,
                      read_path: Optional[WorkerReadPath] = None) -> None:
    from concurrent import futures

    import grpc

    from nornicdb_tpu.server.grpc_search import (
        SERVICE_NAME,
        decode_search_request,
        encode_search_response,
    )

    channel = grpc.insecure_channel(f"127.0.0.1:{primary_port}")
    forward = channel.unary_unary(
        f"/{SERVICE_NAME}/Search",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    cache = ResponseCache(lambda: gen.value)
    limiter = None
    if rate_limit:
        from nornicdb_tpu.server.http import RateLimiter

        # same per-worker-bucket caveat as the HTTP worker: effective
        # ceiling is <= n_workers x rate, which is the point (cache hits
        # must not be unlimited)
        limiter = RateLimiter(rate=rate_limit[0], burst=int(rate_limit[1]))

    def _vector_local(request: bytes, context) -> Optional[bytes]:
        """Serve a vector SearchRequest through the broker / shared
        segment without the primary's gRPC stack; None → proxy."""
        if read_path is None:
            return None
        try:
            req = decode_search_request(request)
        except Exception:
            # undecodable: proxy it — the primary owns the error reply
            log.debug("worker could not decode SearchRequest; proxying",
                      exc_info=True)
            return None
        if not len(req["vector"]):
            return None  # text search needs embedder + BM25: proxy
        from nornicdb_tpu.errors import ResourceExhausted

        t0 = time.perf_counter()
        hits = served = shed = None
        root = _tracer.start_trace(
            "worker.search",
            attrs={"proc": read_path.proc, "k": req["limit"],
                   "dims": int(len(req["vector"]))},
        )
        with root:
            try:
                hits, served = read_path.search(
                    req["vector"], req["limit"], req["min_score"],
                    with_content=True,
                )
                root.set_attr("served", served)
            except ResourceExhausted as e:
                shed = e
            except LookupError:
                pass
            except Exception:
                log.warning("worker grpc vector search failed; proxying",
                            exc_info=True)
        duration = time.perf_counter() - t0
        if shed is not None:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(shed))
        if served is None:
            return None
        _slow_log.maybe_record(
            f"VECTOR SEARCH k={req['limit']} dims={len(req['vector'])}",
            None, duration,
            trace_id=getattr(root, "trace_id", None), served=served,
        )
        read_path.ship_trace(getattr(root, "trace_id", None))
        took = int(duration * 1e6)
        return encode_search_response(
            [{"id": i, "score": s, "content": c} for i, s, c in hits],
            took,
        )

    def call(request: bytes, context) -> bytes:
        if limiter is not None:
            peer = (context.peer() or "").rsplit(":", 1)[0]
            if not limiter.allow(peer):
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              "rate limit exceeded")
        # credentials are part of the cache key and travel with the proxied
        # call — GrpcSearchServer has no auth today, but the moment auth
        # metadata appears on this surface, cached responses must not leak
        # across clients and proxied calls must not drop credentials
        meta = tuple(
            (k, v) for k, v in (context.invocation_metadata() or ())
            if k in ("authorization", "cookie", "x-api-key")
        )
        key = (request, meta)
        hit = cache.get(key)
        if hit is not None:
            return hit
        gen_before = cache.generation()
        resp = _vector_local(request, context)
        if resp is None:
            resp = forward(request, metadata=meta or None)
        cache.put(key, resp, gen_before)
        return resp

    class Handler(grpc.GenericRpcHandler):
        def service(self, details):
            if details.method == f"/{SERVICE_NAME}/Search":
                return grpc.unary_unary_rpc_method_handler(
                    call,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )
            return None

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=4),
        options=[("grpc.so_reuseport", 1)],
    )
    server.add_generic_rpc_handlers((Handler(),))
    bound = server.add_insecure_port(f"{host}:{public_port}")
    if bound != public_port:
        raise RuntimeError(
            f"worker {worker_id}: wanted port {public_port}, got {bound}"
        )
    server.start()
    server.wait_for_termination()


_READ_PLANE_LOCK = threading.Lock()


def _ensure_read_plane(db, workdir: str, interval: float = 0.05):
    """One ReadPlanePublisher per db object, refcounted across pools: the
    HTTP and gRPC pools front the SAME primary, and two publishers would
    export the same corpus twice per epoch."""
    from nornicdb_tpu.server.readplane import ReadPlanePublisher

    def _corpus():
        # the LAZY search slot, never the property: the publisher must not
        # force search-service construction (and a full index build) on a
        # db that never indexed anything
        svc = getattr(db, "_search", None)
        if svc is None or not hasattr(svc, "corpus"):
            return None
        return svc.corpus()

    def _adjacency():
        from nornicdb_tpu.storage.adjacency import attach_snapshot

        snap = attach_snapshot(db.storage)
        if not snap.ready():
            # first export builds the CSR (an engine scan, on the
            # publisher thread) — the same work the first traversal
            # would do in-process, paid once for all workers
            snap.ensure()
        return snap

    with _READ_PLANE_LOCK:
        rp = getattr(db, "_read_plane_publisher", None)
        if rp is None:
            rp = ReadPlanePublisher(
                os.path.join(workdir, "readplane"),
                corpus_fn=_corpus,
                adjacency_fn=_adjacency,
                interval=interval,
            ).start()
            db._read_plane_publisher = rp
            db._read_plane_refs = 0
        db._read_plane_refs += 1
        return rp


def _release_read_plane(db, rp) -> None:
    if rp is None or db is None:
        return
    with _READ_PLANE_LOCK:
        if getattr(db, "_read_plane_publisher", None) is not rp:
            return
        db._read_plane_refs -= 1
        if db._read_plane_refs <= 0:
            rp.stop()
            db._read_plane_publisher = None


def _reserve_port(host: str) -> tuple[socket.socket, int]:
    """Bind (without listening) a SO_REUSEPORT socket on an ephemeral port
    and keep it open: the port stays ours while every worker binds it too."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    s.bind((host, 0))
    return s, s.getsockname()[1]


class WorkerPool:
    """Manage N protocol worker subprocesses in front of a primary.

    kind="http" fronts an HttpServer (primary_port = its .port);
    kind="grpc" fronts a GrpcSearchServer. The pool wires the primary
    db's storage events to the shared generation counter so worker caches
    invalidate on any mutation.
    """

    def __init__(self, db, primary_port: int, n_workers: int = 2,
                 host: str = "127.0.0.1", kind: str = "http",
                 public_port: int = 0,
                 rate_limit: Optional[tuple] = None,
                 broker: "Any" = True,
                 read_plane: bool = True,
                 respawn: bool = True,
                 workdir: Optional[str] = None,
                 publish_interval: float = 0.05,
                 auth_required: bool = False,
                 metrics: bool = True,
                 metrics_interval: float = 0.5):
        if kind not in ("http", "grpc"):
            raise ValueError(f"unknown worker kind {kind!r}")
        self.kind = kind
        self.rate_limit = rate_limit
        self.host = host
        self.n_workers = n_workers
        self.primary_port = primary_port
        self.generation = GenerationFile()
        self._reserved: Optional[socket.socket] = None
        if public_port == 0:
            self._reserved, public_port = _reserve_port(host)
        self.port = public_port
        self._procs: list[Optional[subprocess.Popen]] = []
        self._db = db
        self._bump_cb = None
        self.respawns = 0
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._respawn = respawn
        self._proc_lock = threading.Lock()
        self._workdir = workdir or tempfile.mkdtemp(prefix="nornic-pool-")
        self._own_workdir = workdir is None
        # with auth enforced on the primary, workers must NOT answer from
        # the device plane: the broker/shm ladder has no authenticator, so
        # serving it would hand unauthenticated clients search results the
        # primary would 401. Auth'd deployments keep cache+proxy (cached
        # entries are auth-keyed and only stored after the primary said 200).
        self.auth_required = auth_required
        # fleet telemetry: each worker publishes its registry exposition
        # into a per-proc shm segment; the primary's FLEET collector
        # merges them into /metrics (telemetry/federation.py)
        self.metrics = metrics
        self.metrics_interval = metrics_interval
        self._fleet_procs: list[tuple[str, str]] = []
        if metrics:
            fleet_dir = os.path.join(self._workdir, "fleet")
            os.makedirs(fleet_dir, exist_ok=True)
            for i in range(n_workers):
                proc = self._proc_name(i)
                prefix = os.path.join(fleet_dir, f"{proc}.seg")
                FLEET.register(proc, prefix)
                self._fleet_procs.append((proc, prefix))
        # device plane: the broker (one PJRT owner serving every worker's
        # search/embed batches) and the shared-memory read plane (one copy
        # of the corpus + CSR adjacency for every worker's fallback reads).
        # `broker` may also be an existing DeviceBroker to share between
        # pools (cli serve fronts HTTP and gRPC pools with ONE broker).
        self.broker = None
        self.read_plane = None
        if db is not None:
            from nornicdb_tpu.server.broker import DeviceBroker

            if isinstance(broker, DeviceBroker):
                self.broker = broker
                self._own_broker = False
            elif broker:
                self.broker = DeviceBroker(
                    db, os.path.join(self._workdir, "broker.sock")
                )
                self._own_broker = True
            else:
                self._own_broker = False
            if read_plane:
                self.read_plane = _ensure_read_plane(
                    db, self._workdir, publish_interval
                )
            gen = self.generation
            lock = threading.Lock()

            def _bump(kind_, entity):
                with lock:  # single-writer contract of GenerationFile
                    gen.bump()

            self._bump_cb = _bump
            db.storage.on_event(_bump)
        else:
            self._own_broker = False
        with _ACTIVE_POOLS_LOCK:
            _ACTIVE_POOLS[:] = [
                r for r in _ACTIVE_POOLS if r() is not None
            ]
            _ACTIVE_POOLS.append(weakref.ref(self))

    # -- worker process management ------------------------------------------
    def _proc_name(self, worker_id: int) -> str:
        return f"{self.kind}-worker-{worker_id}"

    def _worker_cfg(self, worker_id: int) -> str:
        rp = self.read_plane
        proc = self._proc_name(worker_id)
        return json.dumps({
            "kind": self.kind,
            "host": self.host,
            "port": self.port,
            "primary_port": self.primary_port,
            "gen_path": self.generation.path,
            "worker_id": worker_id,
            "proc": proc,
            "rate_limit": list(self.rate_limit) if self.rate_limit
                          else None,
            "broker_path": (self.broker.path
                            if self.broker and not self.auth_required
                            else None),
            "corpus_seg": (rp.paths["corpus"]
                           if rp and not self.auth_required else None),
            "adjacency_seg": (rp.paths["adjacency"]
                              if rp and not self.auth_required else None),
            # fleet telemetry segment this worker publishes into
            # (trace shipment rides the broker, so only metrics need it)
            "metrics_seg": (os.path.join(self._workdir, "fleet",
                                         f"{proc}.seg")
                            if self.metrics else None),
            "metrics_interval": self.metrics_interval,
            # the PRIMARY's applied telemetry knobs (YAML/CLI config is
            # applied to its singletons before pools start; env alone
            # would miss nornicdb.yaml): workers must capture slow
            # queries and sample traces under the SAME policy
            "telemetry": {
                "slow_query_ms": _slow_log.threshold_s * 1000.0,
                "tracing_enabled": _tracer.enabled,
                "trace_sample": _tracer.sample_rate,
            },
        })

    def _spawn(self, worker_id: int) -> subprocess.Popen:
        # the package may live off sys.path-only locations (sys.path
        # edits don't propagate to subprocesses) — point the worker at
        # wherever THIS nornicdb_tpu was imported from
        import nornicdb_tpu

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(nornicdb_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_parent + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        return subprocess.Popen(
            [sys.executable, "-m", "nornicdb_tpu.server.worker_main",
             self._worker_cfg(worker_id)],
            stdin=subprocess.DEVNULL,
            env=env,
        )

    def start(self) -> "WorkerPool":
        # spawn OUTSIDE the proc lock (Popen is process I/O; the monitor
        # polls under this lock — NL-LK02)
        procs = [self._spawn(i) for i in range(self.n_workers)]
        with self._proc_lock:
            self._procs.extend(procs)
        if self._respawn and self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="nornicdb-pool-monitor",
                daemon=True,
            )
            self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        """Respawn crashed workers: a kill -9 (or an OOM) during a fault
        window must cost capacity for under a second, not until restart."""
        while not self._stopping.wait(0.25):
            with self._proc_lock:
                procs = list(enumerate(self._procs))
            for i, p in procs:
                if p is None or p.poll() is None:
                    continue
                if self._stopping.is_set():
                    return
                log.warning(
                    "worker %d (pid %s) exited with %s; respawning",
                    i, p.pid, p.returncode,
                )
                try:
                    fresh = self._spawn(i)
                except OSError:
                    log.exception("worker %d respawn failed", i)
                    continue
                with self._proc_lock:
                    if self._stopping.is_set():
                        fresh.terminate()
                        return
                    self._procs[i] = fresh
                    self.respawns += 1

    def alive(self) -> int:
        with self._proc_lock:
            return sum(
                1 for p in self._procs if p is not None and p.poll() is None
            )

    def kill_worker(self, index: int = 0) -> Optional[int]:
        """SIGKILL one worker (crash injection for tests and the soak
        harness's worker_kill fault). Returns the killed pid."""
        with self._proc_lock:
            if index >= len(self._procs) or self._procs[index] is None:
                return None
            p = self._procs[index]
        if p.poll() is not None:
            return None
        p.send_signal(signal.SIGKILL)
        return p.pid

    def stats(self) -> dict:
        out = {
            "kind": self.kind,
            "port": self.port,
            "n_workers": self.n_workers,
            "alive": self.alive(),
            "respawns": self.respawns,
        }
        if self.broker is not None:
            out["broker"] = self.broker.stats()
        if self.read_plane is not None:
            out["read_plane"] = self.read_plane.stats()
        return out

    def worker_states(self) -> list[dict]:
        """Per-worker liveness/respawn state (the /admin/stats ``fleet``
        section's pool half)."""
        with self._proc_lock:
            procs = list(self._procs)
        out = []
        for i, p in enumerate(procs):
            out.append({
                "proc": self._proc_name(i),
                "alive": p is not None and p.poll() is None,
                "pid": p.pid if p is not None else None,
            })
        return out

    def stop(self) -> None:
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        with self._proc_lock:
            procs = [p for p in self._procs if p is not None]
            self._procs.clear()
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        if self._reserved is not None:
            self._reserved.close()
            self._reserved = None
        for proc, prefix in self._fleet_procs:
            # prefix-guarded: a newer pool re-registering the same proc
            # name must not be evicted by this pool's shutdown
            FLEET.unregister(proc, prefix=prefix)
        self._fleet_procs = []
        if self.broker is not None and self._own_broker:
            self.broker.stop()
        _release_read_plane(self._db, self.read_plane)
        self.read_plane = None
        if self._bump_cb is not None and self._db is not None:
            # unhook before closing the mmap: a leaked listener would write
            # to a closed buffer on every later mutation
            try:
                self._db.storage.off_event(self._bump_cb)
            except Exception:
                log.warning("off_event failed during worker stop",
                            exc_info=True)
            self._bump_cb = None
        self.generation.close()
        # remove our temp workdir ONLY when nothing shared still lives in
        # it: another pool on the same db may hold the refcounted read
        # plane whose segments are rooted here
        if self._own_workdir and getattr(
                self._db, "_read_plane_publisher", None) is None:
            import shutil

            shutil.rmtree(self._workdir, ignore_errors=True)


def _subproc_entry(argv: list[str]) -> None:
    cfg = json.loads(argv[0])
    gen = GenerationFile(cfg["gen_path"])
    rl = tuple(cfg["rate_limit"]) if cfg.get("rate_limit") else None
    proc = cfg.get("proc") or f"{cfg['kind']}-worker-{cfg['worker_id']}"
    if cfg.get("telemetry"):
        # adopt the primary's applied telemetry policy (slow-query
        # threshold, trace sampling) — env defaults alone would miss
        # YAML/CLI configuration the primary applied at startup
        import nornicdb_tpu.telemetry as _telemetry

        _telemetry.configure(**cfg["telemetry"])
    read_path = None
    if cfg.get("broker_path") or cfg.get("corpus_seg"):
        read_path = WorkerReadPath(
            cfg.get("broker_path"), cfg.get("corpus_seg"),
            cfg.get("adjacency_seg"), proc=proc,
        )
    if cfg.get("metrics_seg"):
        # fleet telemetry: publish this worker's registry exposition +
        # slow-query ring into its shm segment; the primary merges it
        # into /metrics with a proc label (telemetry/federation.py)
        from nornicdb_tpu.telemetry.federation import MetricsPublisher

        MetricsPublisher(
            cfg["metrics_seg"], proc,
            interval=float(cfg.get("metrics_interval") or 0.5),
        ).start()
    main = _http_worker_main if cfg["kind"] == "http" else _grpc_worker_main
    main(cfg["host"], cfg["port"], cfg["primary_port"], gen,
         cfg["worker_id"], rate_limit=rl, read_path=read_path)
