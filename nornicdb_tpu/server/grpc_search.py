"""Native gRPC search service.

Behavioral reference: /root/reference/pkg/nornicgrpc/ —
proto/nornicdb_search.proto + search_service.go: a lean gRPC surface for
high-throughput vector/hybrid search (the reference's fastest endpoint:
29,331 ops/s in testing/e2e/README.md).

grpc_tools (protoc's Python plugin) is not in this image, so the protobuf
messages are hand-encoded against the wire format (varint/tag codec below)
and the service is registered through grpc.GenericRpcHandler — no generated
stubs. Wire-compatible message shapes:

  SearchRequest  { string query = 1; int32 limit = 2;
                   repeated float vector = 3; float min_score = 4; }
  SearchHit      { string id = 1; float score = 2; string content = 3; }
  SearchResponse { repeated SearchHit hits = 1; int64 took_micros = 2; }

Service: nornicdb.SearchService / Search
"""

from __future__ import annotations

import struct
import time
from typing import Any, Iterator, Optional

from nornicdb_tpu.errors import NotFoundError, ResourceExhausted
from nornicdb_tpu.telemetry.metrics import REGISTRY as _REGISTRY
from nornicdb_tpu.telemetry.tracing import tracer as _tracer

SERVICE_NAME = "nornicdb.SearchService"

_GRPC_HIST = _REGISTRY.histogram(
    "nornicdb_grpc_request_seconds",
    "gRPC Search latency (incl. cache hits)",
)


# ---------------------------------------------------------------- protobuf
def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def encode_search_request(
    query: str = "", limit: int = 10,
    vector=None, min_score: float = 0.0,
) -> bytes:
    out = bytearray()
    if query:
        q = query.encode()
        out += _tag(1, 2) + _varint(len(q)) + q
    if limit:
        out += _tag(2, 0) + _varint(limit)
    if vector is not None and len(vector):
        # one vectorized f32 pack instead of a per-float struct.pack loop
        # (a 1024-dim query was ~1000 allocations per request)
        import numpy as np

        packed = np.asarray(vector, dtype="<f4").tobytes()
        out += _tag(3, 2) + _varint(len(packed)) + packed
    if min_score:
        out += _tag(4, 5) + struct.pack("<f", min_score)
    return bytes(out)


def decode_search_request(buf: bytes) -> dict[str, Any]:
    pos = 0
    out: dict[str, Any] = {"query": "", "limit": 10, "vector": [], "min_score": 0.0}
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, pos = _read_varint(buf, pos)
            if field == 2:
                out["limit"] = v
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            data = buf[pos : pos + ln]
            pos += ln
            if field == 1:
                out["query"] = data.decode()
            elif field == 3:
                # one frombuffer + C-level tolist instead of a per-float
                # struct.unpack_from loop (the profiled allocation storm
                # on the request hot path); stays a plain list for callers
                import numpy as np

                out["vector"] = np.frombuffer(data, dtype="<f4").tolist()
        elif wire == 5:
            (v,) = struct.unpack_from("<f", buf, pos)
            pos += 4
            if field == 4:
                out["min_score"] = v
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return out


def encode_search_response(hits: list[dict[str, Any]], took_micros: int) -> bytes:
    out = bytearray()
    for h in hits:
        hit = bytearray()
        hid = str(h["id"]).encode()
        hit += _tag(1, 2) + _varint(len(hid)) + hid
        hit += _tag(2, 5) + struct.pack("<f", float(h["score"]))
        content = str(h.get("content", "")).encode()
        if content:
            hit += _tag(3, 2) + _varint(len(content)) + content
        out += _tag(1, 2) + _varint(len(hit)) + bytes(hit)
    out += _tag(2, 0) + _varint(took_micros)
    return bytes(out)


def decode_search_response(buf: bytes) -> dict[str, Any]:
    pos = 0
    hits = []
    took = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 2 and field == 1:
            ln, pos = _read_varint(buf, pos)
            sub = buf[pos : pos + ln]
            pos += ln
            hit = {"id": "", "score": 0.0, "content": ""}
            spos = 0
            while spos < len(sub):
                skey, spos = _read_varint(sub, spos)
                sfield, swire = skey >> 3, skey & 7
                if swire == 2:
                    sln, spos = _read_varint(sub, spos)
                    data = sub[spos : spos + sln]
                    spos += sln
                    if sfield == 1:
                        hit["id"] = data.decode()
                    elif sfield == 3:
                        hit["content"] = data.decode()
                elif swire == 5:
                    (hit["score"],) = struct.unpack_from("<f", sub, spos)
                    spos += 4
                else:
                    v, spos = _read_varint(sub, spos)
            hits.append(hit)
        elif wire == 0 and field == 2:
            took, pos = _read_varint(buf, pos)
        else:
            break
    return {"hits": hits, "took_micros": took}


# ---------------------------------------------------------------- service
class GrpcSearchServer:
    """(ref: nornicgrpc search_service.go) — generic handler, no stubs."""

    def __init__(self, db, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 0):
        import grpc
        import os
        from concurrent import futures

        if max_workers <= 0:
            # handler work is tiny (cached search + hand-rolled protobuf);
            # on few-core boxes extra handler threads just add GIL churn
            try:
                cores = len(os.sched_getaffinity(0))
            except AttributeError:
                cores = os.cpu_count() or 1
            max_workers = max(2, min(8, cores * 2))
        self.db = db
        from nornicdb_tpu.server.respcache import ResponseCache

        self._resp_cache = ResponseCache(lambda: db.search._generation)
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method == f"/{SERVICE_NAME}/Search":
                    return grpc.unary_unary_rpc_method_handler(
                        outer._search,
                        request_deserializer=lambda b: b,
                        response_serializer=lambda b: b,
                    )
                return None

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def _search(self, request: bytes, context) -> bytes:
        # cache-hit fast path BEFORE the trace machinery: a hit skips
        # decode, rank, encode — building a trace root (+ spans) around a
        # dict lookup was ~30% of the per-request overhead on the hot
        # cached path, for a trace that says nothing
        t_hit = time.perf_counter()
        cached = self._resp_cache.get(request)
        if cached is not None:
            _GRPC_HIST.observe(time.perf_counter() - t_hit)
            return cached
        # ingress trace root; clients may attach a W3C traceparent as gRPC
        # metadata, carrying their trace across the process boundary
        traceparent = None
        try:
            for key, value in context.invocation_metadata() or ():
                if key == "traceparent":
                    traceparent = value
                    break
        except (AttributeError, TypeError):  # doubles without metadata
            traceparent = None
        t_req = time.perf_counter()
        try:
            with _tracer.start_trace("grpc.search", traceparent=traceparent):
                return self._search_traced(request)
        except ResourceExhausted as e:
            # serving admission control shed this query: surface the
            # canonical gRPC backpressure status so clients back off
            import grpc

            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        finally:
            _GRPC_HIST.observe(time.perf_counter() - t_req)

    def _search_traced(self, request: bytes) -> bytes:
        # serialized-response cache: generation-invalidated + short TTL,
        # shared policy with the HTTP search cache (server/respcache.py) —
        # skips decode, rank, node fetch, and protobuf encode on hits
        cached = self._resp_cache.get(request)
        if cached is not None:
            return cached
        gen_before = self._resp_cache.generation()
        t0 = time.perf_counter()
        req = decode_search_request(request)
        if len(req["vector"]):
            import numpy as np

            hits = self.db.search.vector_candidates(
                np.asarray(req["vector"], np.float32),
                k=req["limit"], min_similarity=req["min_score"],
            )
            out = []
            for nid, score in hits:
                node = None
                try:
                    node = self.db.storage.get_node(nid)
                except NotFoundError:
                    pass  # hit evicted between search and fetch: skip detail
                out.append(
                    {
                        "id": nid,
                        "score": score,
                        "content": node.properties.get("content", "") if node else "",
                    }
                )
        else:
            results = self.db.search.search(req["query"], limit=req["limit"])
            out = [
                {"id": r["id"], "score": r["score"], "content": r["content"]}
                for r in results
            ]
        took = int((time.perf_counter() - t0) * 1e6)
        payload = encode_search_response(out, took)
        self._resp_cache.put(request, payload, gen_before)
        return payload

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=1)


def search_over_grpc(
    host: str, port: int, query: str = "",
    vector: Optional[list[float]] = None, limit: int = 10,
    min_score: float = 0.0, channel=None,
) -> dict[str, Any]:
    """Client helper (used by tests/CLI; any protobuf-speaking Qdrant/neo4j
    ecosystem client can hit the same endpoint with generated stubs).
    Pass ``channel`` to reuse a connection across calls — per-call channel
    setup/teardown costs more than the search itself under load."""
    import grpc

    own_channel = channel is None
    if own_channel:
        channel = grpc.insecure_channel(f"{host}:{port}")
    fn = channel.unary_unary(
        f"/{SERVICE_NAME}/Search",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    req = encode_search_request(query, limit, vector, min_score)
    try:
        resp = fn(req, timeout=10)
    finally:
        if own_channel:
            channel.close()
    return decode_search_response(resp)
