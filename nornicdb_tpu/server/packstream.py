"""PackStream v2 codec (the Bolt wire serialization).

Behavioral reference: /root/reference/pkg/bolt/packstream.go (1,304 LoC
complete codec). Implements the marker scheme: tiny/8/16/32 ints, float64,
strings, lists, maps, booleans, null, bytes, and structures — including the
graph structs Node (0x4E), Relationship (0x52), UnboundRelationship (0x72)
and Path (0x50) used in RECORD messages.
"""

from __future__ import annotations

import struct
from typing import Any

from nornicdb_tpu.storage.types import Edge, Node

# structure tags
STRUCT_NODE = 0x4E
STRUCT_REL = 0x52
STRUCT_UNBOUND_REL = 0x72
STRUCT_PATH = 0x50


class Structure:
    def __init__(self, tag: int, fields: list[Any]):
        self.tag = tag
        self.fields = fields

    def __repr__(self) -> str:
        return f"Structure(0x{self.tag:02X}, {self.fields!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Structure)
            and self.tag == other.tag
            and self.fields == other.fields
        )


class Packer:
    def __init__(self) -> None:
        self.buf = bytearray()

    def pack(self, value: Any) -> "Packer":
        b = self.buf
        if value is None:
            b.append(0xC0)
        elif value is True:
            b.append(0xC3)
        elif value is False:
            b.append(0xC2)
        elif isinstance(value, int):
            self._pack_int(value)
        elif isinstance(value, float):
            b.append(0xC1)
            b += struct.pack(">d", value)
        elif isinstance(value, str):
            data = value.encode("utf-8")
            self._pack_header(len(data), 0x80, 0xD0)
            b += data
        elif isinstance(value, (bytes, bytearray)):
            n = len(value)
            if n < 0x100:
                b += bytes([0xCC, n])
            elif n < 0x10000:
                b.append(0xCD)
                b += struct.pack(">H", n)
            else:
                b.append(0xCE)
                b += struct.pack(">I", n)
            b += value
        elif isinstance(value, (list, tuple)):
            self._pack_header(len(value), 0x90, 0xD4)
            for item in value:
                self.pack(item)
        elif isinstance(value, dict):
            self._pack_header(len(value), 0xA0, 0xD8)
            for k, v in value.items():
                self.pack(str(k))
                self.pack(v)
        elif isinstance(value, Structure):
            n = len(value.fields)
            if n < 0x10:
                b.append(0xB0 + n)
            else:
                raise ValueError("structure too large")
            b.append(value.tag)
            for f in value.fields:
                self.pack(f)
        elif isinstance(value, Node):
            self.pack(node_struct(value))
        elif isinstance(value, Edge):
            self.pack(edge_struct(value))
        else:
            # numpy scalars / arrays and other iterables
            try:
                import numpy as np

                if isinstance(value, np.integer):
                    return self.pack(int(value))
                if isinstance(value, np.floating):
                    return self.pack(float(value))
                if isinstance(value, np.ndarray):
                    return self.pack(value.tolist())
            except ImportError:
                pass
            raise ValueError(f"cannot pack {type(value).__name__}")
        return self

    def _pack_int(self, v: int) -> None:
        b = self.buf
        if -0x10 <= v < 0x80:
            b.append(v & 0xFF)
        elif -0x80 <= v < 0x80:
            b.append(0xC8)
            b += struct.pack(">b", v)
        elif -0x8000 <= v < 0x8000:
            b.append(0xC9)
            b += struct.pack(">h", v)
        elif -0x80000000 <= v < 0x80000000:
            b.append(0xCA)
            b += struct.pack(">i", v)
        else:
            b.append(0xCB)
            b += struct.pack(">q", v)

    def _pack_header(self, n: int, tiny_marker: int, sized_marker: int) -> None:
        b = self.buf
        if n < 0x10:
            b.append(tiny_marker + n)
        elif n < 0x100:
            b += bytes([sized_marker, n])
        elif n < 0x10000:
            b.append(sized_marker + 1)
            b += struct.pack(">H", n)
        else:
            b.append(sized_marker + 2)
            b += struct.pack(">I", n)

    def bytes(self) -> bytes:
        return bytes(self.buf)


def pack(value: Any) -> bytes:
    return Packer().pack(value).bytes()


class Unpacker:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("packstream: truncated input")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def unpack(self) -> Any:
        marker = self._take(1)[0]
        if marker < 0x80:  # tiny positive int
            return marker
        if marker >= 0xF0:  # tiny negative int
            return marker - 0x100
        if 0x80 <= marker < 0x90:  # tiny string
            return self._take(marker & 0x0F).decode("utf-8")
        if 0x90 <= marker < 0xA0:  # tiny list
            return [self.unpack() for _ in range(marker & 0x0F)]
        if 0xA0 <= marker < 0xB0:  # tiny map
            return {self.unpack(): self.unpack() for _ in range(marker & 0x0F)}
        if 0xB0 <= marker < 0xC0:  # structure
            n = marker & 0x0F
            tag = self._take(1)[0]
            return Structure(tag, [self.unpack() for _ in range(n)])
        if marker == 0xC0:
            return None
        if marker == 0xC1:
            return struct.unpack(">d", self._take(8))[0]
        if marker == 0xC2:
            return False
        if marker == 0xC3:
            return True
        if marker == 0xC8:
            return struct.unpack(">b", self._take(1))[0]
        if marker == 0xC9:
            return struct.unpack(">h", self._take(2))[0]
        if marker == 0xCA:
            return struct.unpack(">i", self._take(4))[0]
        if marker == 0xCB:
            return struct.unpack(">q", self._take(8))[0]
        if marker == 0xCC:
            return bytes(self._take(self._take(1)[0]))
        if marker == 0xCD:
            return bytes(self._take(struct.unpack(">H", self._take(2))[0]))
        if marker == 0xCE:
            return bytes(self._take(struct.unpack(">I", self._take(4))[0]))
        if marker == 0xD0:
            return self._take(self._take(1)[0]).decode("utf-8")
        if marker == 0xD1:
            return self._take(struct.unpack(">H", self._take(2))[0]).decode("utf-8")
        if marker == 0xD2:
            return self._take(struct.unpack(">I", self._take(4))[0]).decode("utf-8")
        if marker == 0xD4:
            return [self.unpack() for _ in range(self._take(1)[0])]
        if marker == 0xD5:
            return [
                self.unpack()
                for _ in range(struct.unpack(">H", self._take(2))[0])
            ]
        if marker == 0xD6:
            return [
                self.unpack()
                for _ in range(struct.unpack(">I", self._take(4))[0])
            ]
        if marker == 0xD8:
            return {self.unpack(): self.unpack() for _ in range(self._take(1)[0])}
        if marker == 0xD9:
            return {
                self.unpack(): self.unpack()
                for _ in range(struct.unpack(">H", self._take(2))[0])
            }
        if marker == 0xDA:
            return {
                self.unpack(): self.unpack()
                for _ in range(struct.unpack(">I", self._take(4))[0])
            }
        raise ValueError(f"packstream: unknown marker 0x{marker:02X}")


def unpack(data: bytes) -> Any:
    return Unpacker(data).unpack()


# ---------------------------------------------------------------- graph types
def _element_int_id(id_: str) -> int:
    """Bolt's legacy numeric id field: stable hash of the string id."""
    import zlib

    return zlib.crc32(id_.encode()) & 0x7FFFFFFF


def node_struct(n: Node) -> Structure:
    props = dict(n.properties)
    return Structure(
        STRUCT_NODE,
        [_element_int_id(n.id), list(n.labels), props, n.id],  # + element_id (5.x)
    )


def edge_struct(e: Edge) -> Structure:
    return Structure(
        STRUCT_REL,
        [
            _element_int_id(e.id),
            _element_int_id(e.start_node),
            _element_int_id(e.end_node),
            e.type,
            dict(e.properties),
            e.id,
            e.start_node,
            e.end_node,
        ],
    )


def path_struct(p: dict) -> Structure:
    nodes = [node_struct(n) for n in p.get("nodes", [])]
    rels = [
        Structure(
            STRUCT_UNBOUND_REL,
            [_element_int_id(e.id), e.type, dict(e.properties), e.id],
        )
        for e in p.get("relationships", [])
    ]
    # index sequence: [rel_idx, node_idx, ...] 1-based alternating
    seq: list[int] = []
    for i in range(len(rels)):
        seq.append(i + 1)
        seq.append(i + 1)
    return Structure(STRUCT_PATH, [nodes, rels, seq])


def to_wire(value: Any) -> Any:
    """Convert executor result values into packable form."""
    if isinstance(value, Node):
        return node_struct(value)
    if isinstance(value, Edge):
        return edge_struct(value)
    if isinstance(value, dict):
        if value.get("__path__"):
            return path_struct(value)
        return {k: to_wire(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [to_wire(v) for v in value]
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            return value.tolist()
        if isinstance(value, np.integer):
            return int(value)
        if isinstance(value, np.floating):
            return float(value)
    except ImportError:
        pass
    return value
