"""Qdrant-compatible vector API.

Behavioral reference: /root/reference/pkg/qdrantgrpc/ — Collections/Points
services (collections_service.go, points_service.go), collection registry
mapped onto graph nodes with label "QdrantPoint" (registry.go), named-vector
support; points indexed into the same search service (server.go:207).

Two transports share this module's registry: Qdrant REST shapes mounted on
the HTTP server under /collections/* (this file), and the Qdrant v1.16
gRPC services on their own port (qdrant_grpc.py — Collections/Points/
Snapshots with auth interceptors, mirroring pkg/qdrantgrpc/server.go:207).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Optional

import numpy as np

from nornicdb_tpu.errors import AlreadyExistsError, NornicError, NotFoundError
from nornicdb_tpu.ops.similarity import DeviceCorpus
from nornicdb_tpu.storage.types import Engine, Node

POINT_LABEL = "QdrantPoint"


# ------------------------------------------------------------- filters
def _payload_get(payload: dict, key: str):
    """Dotted-path payload access (ref: Qdrant nested payload keys)."""
    cur: Any = payload
    for part in key.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _eq(a: Any, b: Any) -> bool:
    """Type-strict equality: True != 1 (payload bools vs integers)."""
    return isinstance(a, bool) == isinstance(b, bool) and a == b


def _match_one(value: Any, match: dict) -> bool:
    """Qdrant Match semantics: equality for keyword/integer/boolean, substring
    for text, membership for any/except; list-valued payloads match if any
    element matches (ref: pkg/qdrantgrpc points filters)."""
    values = value if isinstance(value, list) else [value]
    if "text" in match:
        needle = str(match["text"])
        return any(isinstance(v, str) and needle in v for v in values)
    if "any" in match:
        allowed = match["any"] if isinstance(match["any"], list) else []
        return any(any(_eq(v, a) for a in allowed) for v in values)
    if "except" in match:
        banned = match["except"] if isinstance(match["except"], list) else []
        return value is not None and all(
            not any(_eq(v, b) for b in banned) for v in values
        )
    for k in ("value", "keyword", "integer", "boolean"):
        if k in match:
            return any(_eq(v, match[k]) for v in values)
    raise NornicError(f"invalid match clause {match!r}")


def _range_ok(value: Any, rng: dict) -> bool:
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return False
    if "gt" in rng and rng["gt"] is not None and not value > rng["gt"]:
        return False
    if "gte" in rng and rng["gte"] is not None and not value >= rng["gte"]:
        return False
    if "lt" in rng and rng["lt"] is not None and not value < rng["lt"]:
        return False
    if "lte" in rng and rng["lte"] is not None and not value <= rng["lte"]:
        return False
    return True


def _eval_condition(cond: dict, point_id: Any, payload: dict) -> bool:
    if not isinstance(cond, dict):
        raise NornicError(f"invalid filter condition {cond!r}")
    if "must" in cond or "should" in cond or "must_not" in cond:
        return eval_filter(cond, point_id, payload)  # nested Filter
    if "filter" in cond:
        return eval_filter(cond["filter"], point_id, payload)
    if "has_id" in cond:
        ids = cond["has_id"]
        return point_id in (ids if isinstance(ids, list) else [ids])
    if "is_empty" in cond:
        v = _payload_get(payload, cond["is_empty"].get("key", ""))
        return v is None or v == [] or v == ""
    if "is_null" in cond:
        key = cond["is_null"].get("key", "")
        return _payload_get(payload, key) is None and _has_key(payload, key)
    key = cond.get("key")
    if key is None:
        raise NornicError(f"invalid filter condition {cond!r}")
    value = _payload_get(payload, key)
    if "match" in cond:
        return value is not None and _match_one(value, cond["match"])
    if "range" in cond:
        return _range_ok(value, cond["range"])
    raise NornicError(f"unsupported filter condition {cond!r}")


def _has_key(payload: dict, key: str) -> bool:
    parts = key.split(".")
    cur: Any = payload
    for part in parts:
        if not isinstance(cur, dict) or part not in cur:
            return False
        cur = cur[part]
    return True


def eval_filter(flt: Optional[dict], point_id: Any, payload: dict) -> bool:
    """Evaluate a Qdrant Filter (must AND / should OR / must_not NONE, each a
    list of Conditions; conditions may nest Filters). JSON-dict form shared by
    the REST transport and the gRPC decoder (ref: pkg/qdrantgrpc filter
    handling in points_service.go)."""
    if not flt:
        return True
    must = flt.get("must") or []
    should = flt.get("should") or []
    must_not = flt.get("must_not") or []
    if isinstance(must, dict):
        must = [must]
    if isinstance(should, dict):
        should = [should]
    if isinstance(must_not, dict):
        must_not = [must_not]
    if any(_eval_condition(c, point_id, payload) for c in must_not):
        return False
    if not all(_eval_condition(c, point_id, payload) for c in must):
        return False
    if should and not any(_eval_condition(c, point_id, payload) for c in should):
        return False
    return True


class QdrantCollections:
    """Collection registry over graph nodes (ref: registry.go:149 analogue —
    per-collection vector space + device corpus)."""

    def __init__(self, storage: Engine, vectorspaces=None):
        self.storage = storage
        self.vectorspaces = vectorspaces
        self._lock = threading.RLock()
        self._collections: dict[str, dict[str, Any]] = {}
        self._corpora: dict[str, DeviceCorpus] = {}
        # rebuild registry from persisted points (default AND named vectors)
        for n in storage.get_nodes_by_label(POINT_LABEL):
            coll = n.properties.get("_collection")
            if not coll:
                continue
            meta = self._collections.setdefault(
                coll, {"size": 0, "distance": "Cosine", "named": {}}
            )
            if n.embedding is not None and not meta["size"]:
                meta["size"] = int(n.embedding.shape[0])
            for vec_name, v in n.named_embeddings.items():
                meta.setdefault("named", {}).setdefault(
                    vec_name, {"size": int(v.shape[0]), "distance": "Cosine"}
                )
        for name in self._collections:
            self._rebuild_corpus(name)

    def _rebuild_corpus(self, name: str) -> None:
        info = self._collections[name]
        if info.get("size"):
            corpus = DeviceCorpus(dims=info["size"])
            for n in self.storage.get_nodes_by_label(POINT_LABEL):
                if n.properties.get("_collection") == name and n.embedding is not None:
                    corpus.add(n.id, n.embedding)
            self._corpora[name] = corpus
        for vec_name, spec in (info.get("named") or {}).items():
            nc = DeviceCorpus(dims=int(spec.get("size", 1)) or 1)
            for n in self.storage.get_nodes_by_label(POINT_LABEL):
                if n.properties.get("_collection") != name:
                    continue
                v = n.named_embeddings.get(vec_name)
                if v is not None:
                    nc.add(n.id, v)
            self._corpora[f"{name}/{vec_name}"] = nc

    # -- collections -------------------------------------------------------
    def create(self, name: str, size: int = 0, distance: str = "Cosine",
               named: Optional[dict[str, dict]] = None) -> None:
        """size/distance for the default vector; `named` maps vector names
        to {"size", "distance"} for named-vector collections
        (ref: named-vector support, pkg/qdrantgrpc registry.go)."""
        named = named or {}
        with self._lock:
            self._collections[name] = {
                "size": int(size), "distance": distance,
                "named": {k: {"size": int(v.get("size", 0)),
                              "distance": v.get("distance", "Cosine")}
                          for k, v in named.items()},
            }
            if size:
                self._corpora[name] = DeviceCorpus(dims=int(size))
            for vec_name, spec in named.items():
                self._corpora[f"{name}/{vec_name}"] = DeviceCorpus(
                    dims=int(spec.get("size", 0)) or 1
                )
        if self.vectorspaces is not None:
            from nornicdb_tpu.vectorspace import VectorSpaceKey

            if size:
                self.vectorspaces.register(
                    VectorSpaceKey(f"qdrant:{name}", int(size), distance.lower())
                )
            for vec_name, spec in named.items():
                self.vectorspaces.register(
                    VectorSpaceKey(
                        f"qdrant:{name}:{vec_name}", int(spec.get("size", 0)),
                        str(spec.get("distance", "Cosine")).lower(),
                    )
                )

    def drop(self, name: str) -> bool:
        with self._lock:
            existed = self._collections.pop(name, None) is not None
            self._corpora.pop(name, None)
            for key in [k for k in self._corpora if k.startswith(f"{name}/")]:
                self._corpora.pop(key, None)
        for n in list(self.storage.get_nodes_by_label(POINT_LABEL)):
            if n.properties.get("_collection") == name:
                self.storage.delete_node(n.id)
        return existed

    def list(self) -> list[dict[str, Any]]:
        with self._lock:
            return [{"name": n} for n in sorted(self._collections)]

    def params(self, name: str) -> Optional[dict[str, Any]]:
        """Public copy of a collection's vector params (size/distance/named),
        so transports never reach into the locked internal registry."""
        with self._lock:
            meta = self._collections.get(name)
            if meta is None:
                return None
            return {
                "size": meta.get("size", 0),
                "distance": meta.get("distance", "Cosine"),
                "named": {k: dict(v) for k, v in (meta.get("named") or {}).items()},
            }

    def info(self, name: str) -> Optional[dict[str, Any]]:
        with self._lock:
            meta = self._collections.get(name)
            if meta is None:
                return None
            count = sum(
                1
                for n in self.storage.get_nodes_by_label(POINT_LABEL)
                if n.properties.get("_collection") == name
            )
            return {
                "status": "green",
                "vectors_count": count,
                "points_count": count,
                "config": {
                    "params": {
                        "vectors": {"size": meta["size"], "distance": meta["distance"]}
                    }
                },
            }

    # -- points ------------------------------------------------------------
    def _node_id(self, collection: str, point_id: Any) -> str:
        return f"qdrant-{collection}-{point_id}"

    def upsert(self, collection: str, points: list[dict[str, Any]]) -> int:
        with self._lock:
            if collection not in self._collections:
                raise NotFoundError(f"collection {collection} not found")
            corpus = self._corpora.get(collection)
        n = 0
        for p in points:
            raw_vec = p["vector"]
            named_vecs: dict[str, np.ndarray] = {}
            vec = None
            if isinstance(raw_vec, dict):
                named_vecs = {k: np.asarray(v, np.float32)
                              for k, v in raw_vec.items()}
            else:
                vec = np.asarray(raw_vec, np.float32)
            nid = self._node_id(collection, p["id"])
            # underscore-prefixed keys are internal bookkeeping (_collection,
            # _point_id) — client payloads must never clobber them
            payload = {k: v for k, v in (p.get("payload") or {}).items()
                       if not k.startswith("_")}
            node = Node(
                id=nid,
                labels=[POINT_LABEL],
                properties={"_collection": collection, "_point_id": p["id"],
                            **payload},
                embedding=vec,
                named_embeddings=named_vecs,
            )
            try:
                self.storage.create_node(node)
            except AlreadyExistsError:
                existing = self.storage.get_node(nid)
                existing.properties = dict(node.properties)
                existing.embedding = vec
                existing.named_embeddings = named_vecs
                self.storage.update_node(existing)
            if vec is not None and corpus is not None:
                corpus.add(nid, vec)
            for vec_name, v in named_vecs.items():
                nc = self._corpora.get(f"{collection}/{vec_name}")
                if nc is None:
                    continue
                if nc.dims != v.shape[0]:
                    raise NornicError(
                        f"vector '{vec_name}' has {v.shape[0]} dims, "
                        f"collection expects {nc.dims}"
                    )
                nc.add(nid, v)
            n += 1
        return n

    def delete_points(self, collection: str, ids: list[Any]) -> int:
        with self._lock:
            corpora = [
                c for key, c in self._corpora.items()
                if key == collection or key.startswith(f"{collection}/")
            ]
        n = 0
        for pid in ids:
            nid = self._node_id(collection, pid)
            try:
                self.storage.delete_node(nid)
                n += 1
            except NotFoundError:
                continue
            for c in corpora:
                c.remove(nid)
        return n

    def _iter_points(self, collection: str):
        for n in self.storage.get_nodes_by_label(POINT_LABEL):
            if n.properties.get("_collection") == collection:
                yield n

    def matching_ids(self, collection: str,
                     query_filter: Optional[dict]) -> list[Any]:
        """Point ids in `collection` whose payload satisfies the Qdrant
        filter (all points when the filter is empty)."""
        if self.info(collection) is None:
            raise NotFoundError(f"collection {collection} not found")
        out = []
        for n in self._iter_points(collection):
            pid = n.properties.get("_point_id")
            payload = {k: v for k, v in n.properties.items()
                       if not k.startswith("_")}
            if eval_filter(query_filter, pid, payload):
                out.append(pid)
        return out

    def count(self, collection: str,
              query_filter: Optional[dict] = None) -> int:
        if not query_filter:
            info = self.info(collection)
            if info is None:
                raise NotFoundError(f"collection {collection} not found")
            return info["points_count"]
        return len(self.matching_ids(collection, query_filter))

    def scroll(self, collection: str, offset: Any = None, limit: int = 10,
               query_filter: Optional[dict] = None
               ) -> tuple[list[Any], Optional[Any]]:
        """Stable id-ordered page of point ids; returns (page, next_offset)
        (ref: points_service.go Scroll — deterministic paging)."""
        pts = sorted(
            self.matching_ids(collection, query_filter),
            key=lambda p: (isinstance(p, str), str(p)),
        )
        if offset is not None:
            key = (isinstance(offset, str), str(offset))
            pts = [p for p in pts if (isinstance(p, str), str(p)) >= key]
        page, rest = pts[:limit], pts[limit:]
        return page, (rest[0] if rest else None)

    def search(
        self,
        collection: str,
        vector,
        limit: int = 10,
        score_threshold: float = -1.0,
        with_payload: bool = True,
        query_filter: Optional[dict] = None,
    ) -> list[dict[str, Any]]:
        key = collection
        if isinstance(vector, dict):  # named vector: {"name": ..., "vector": [...]}
            key = f"{collection}/{vector.get('name', '')}"
            vector = vector.get("vector", [])
        with self._lock:
            corpus = self._corpora.get(key)
        if corpus is None:
            raise NotFoundError(f"collection {collection} not found")
        allowed = None
        k = limit
        if query_filter:
            allowed = {
                self._node_id(collection, pid)
                for pid in self.matching_ids(collection, query_filter)
            }
            # filtering happens post-top-k, so rank the whole corpus to
            # guarantee `limit` survivors when they exist (exact, like the
            # reference's filtered search; ANN-with-filter is a later lever)
            k = max(limit, len(corpus))
        res = corpus.search(
            np.asarray(vector, np.float32), k=k,
            min_similarity=score_threshold,
        )
        out = []
        for nid, score in res[0] if res else []:
            if allowed is not None and nid not in allowed:
                continue
            try:
                node = self.storage.get_node(nid)
            except NotFoundError:
                continue
            item = {"id": node.properties.get("_point_id"), "score": score,
                    "version": 0}
            if with_payload:
                item["payload"] = {
                    k: v for k, v in node.properties.items()
                    if not k.startswith("_")
                }
            out.append(item)
            if len(out) >= limit:
                break
        return out

    def retrieve(self, collection: str, ids: list[Any]) -> list[dict[str, Any]]:
        out = []
        for pid in ids:
            try:
                node = self.storage.get_node(self._node_id(collection, pid))
            except NotFoundError:
                continue
            if node.named_embeddings:
                vector: Any = {
                    k: v.tolist() for k, v in node.named_embeddings.items()
                }
                if node.embedding is not None:
                    vector[""] = node.embedding.tolist()
            else:
                vector = (
                    node.embedding.tolist()
                    if node.embedding is not None
                    else None
                )
            out.append(
                {
                    "id": pid,
                    "payload": {
                        k: v for k, v in node.properties.items()
                        if not k.startswith("_")
                    },
                    "vector": vector,
                }
            )
        return out


def handle_qdrant(registry: QdrantCollections, method: str, path: str,
                  body: dict) -> Optional[tuple[int, dict]]:
    """Route a /collections/* request; None if the path isn't Qdrant's."""

    def ok(result: Any, code: int = 200) -> tuple[int, dict]:
        return code, {"result": result, "status": "ok", "time": 0.0}

    m = re.fullmatch(r"/collections", path)
    if m and method == "GET":
        return ok({"collections": registry.list()})
    m = re.fullmatch(r"/collections/([^/]+)", path)
    if m:
        name = m.group(1)
        if method == "PUT":
            vectors = body.get("vectors", {})
            if isinstance(vectors, dict) and "size" in vectors:
                registry.create(name, int(vectors["size"]),
                                str(vectors.get("distance", "Cosine")))
            elif isinstance(vectors, dict) and vectors and all(
                isinstance(v, dict) for v in vectors.values()
            ):
                registry.create(name, named=vectors)  # named-vector config
            else:
                registry.create(name, int(body.get("size", 0)),
                                str(body.get("distance", "Cosine")))
            return ok(True)
        if method == "GET":
            info = registry.info(name)
            if info is None:
                return 404, {"status": {"error": f"collection {name} not found"}}
            return ok(info)
        if method == "DELETE":
            return ok(registry.drop(name))
    m = re.fullmatch(r"/collections/([^/]+)/points", path)
    if m and method == "PUT":
        n = registry.upsert(m.group(1), body.get("points", []))
        return ok({"operation_id": 0, "status": "completed", "upserted": n})
    m = re.fullmatch(r"/collections/([^/]+)/points/search", path)
    if m and method == "POST":
        hits = registry.search(
            m.group(1),
            body.get("vector", []),
            limit=int(body.get("limit", 10)),
            score_threshold=float(body.get("score_threshold", -1.0)),
            with_payload=bool(body.get("with_payload", True)),
            query_filter=body.get("filter"),
        )
        return ok(hits)
    m = re.fullmatch(r"/collections/([^/]+)/points/count", path)
    if m and method == "POST":
        return ok({"count": registry.count(m.group(1), body.get("filter"))})
    m = re.fullmatch(r"/collections/([^/]+)/points/scroll", path)
    if m and method == "POST":
        page, nxt = registry.scroll(
            m.group(1), offset=body.get("offset"),
            limit=int(body.get("limit", 10)),
            query_filter=body.get("filter"),
        )
        return ok({"points": registry.retrieve(m.group(1), page),
                   "next_page_offset": nxt})
    m = re.fullmatch(r"/collections/([^/]+)/points/delete", path)
    if m and method == "POST":
        n = registry.delete_points(m.group(1), body.get("points", []))
        return ok({"operation_id": 0, "status": "completed", "deleted": n})
    m = re.fullmatch(r"/collections/([^/]+)/points", path)
    if m and method == "POST":
        return ok(registry.retrieve(m.group(1), body.get("ids", [])))
    m = re.fullmatch(r"/collections/([^/]+)/snapshots", path)
    if m and method == "POST":
        # snapshot of the collection's points INCLUDING vectors
        # (ref: snapshots_service.go) — scans only QdrantPoint nodes
        name = m.group(1)
        if registry.info(name) is None:
            return 404, {"status": {"error": f"collection {name} not found"}}
        points = []
        for n in registry.storage.get_nodes_by_label(POINT_LABEL):
            if n.properties.get("_collection") != name:
                continue
            points.append(
                {
                    "id": n.properties.get("_point_id"),
                    "payload": {
                        k: v for k, v in n.properties.items()
                        if not k.startswith("_")
                    },
                    "vector": (
                        {k: v.tolist() for k, v in n.named_embeddings.items()}
                        if n.named_embeddings
                        else (n.embedding.tolist()
                              if n.embedding is not None else None)
                    ),
                }
            )
        return ok({"name": f"{name}-snapshot", "points": points,
                   "count": len(points)})
    return None
