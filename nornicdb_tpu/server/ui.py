"""Embedded web console.

Behavioral reference: /root/reference/ui/ — a React SPA (query console, AI
assistant, login) embedded via go:embed; headless builds exclude it
(-tags noui). This build embeds a single-file console (no build step, no
dependencies) serving the same three panes: Cypher console, hybrid search,
and Heimdall chat, all speaking the existing HTTP endpoints.
"""

UI_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>NornicDB-TPU Console</title>
<style>
  :root { --bg:#11151c; --panel:#1a2029; --fg:#d8dee9; --accent:#5fb3b3;
          --muted:#6c7a89; --err:#bf616a; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 ui-monospace, Menlo, monospace; }
  header { padding:12px 20px; border-bottom:1px solid #2a313c;
           display:flex; justify-content:space-between; align-items:center; }
  header b { color:var(--accent); }
  #stats { color:var(--muted); font-size:12px; }
  main { display:grid; grid-template-columns:1fr 1fr; gap:14px; padding:14px; }
  section { background:var(--panel); border-radius:8px; padding:14px; }
  section.wide { grid-column: 1 / span 2; }
  h2 { margin:0 0 10px; font-size:13px; color:var(--accent);
       text-transform:uppercase; letter-spacing:1px; }
  textarea, input { width:100%; background:#0d1117; color:var(--fg);
      border:1px solid #2a313c; border-radius:6px; padding:8px;
      font:inherit; }
  textarea { min-height:72px; resize:vertical; }
  button { margin-top:8px; background:var(--accent); color:#0d1117;
      border:0; border-radius:6px; padding:7px 16px; font:inherit;
      font-weight:bold; cursor:pointer; }
  pre { background:#0d1117; border-radius:6px; padding:10px; overflow:auto;
        max-height:320px; white-space:pre-wrap; }
  .err { color:var(--err); }
  table { border-collapse:collapse; width:100%; }
  td, th { border:1px solid #2a313c; padding:4px 8px; text-align:left; }
  th { color:var(--accent); }
</style>
</head>
<body>
<header>
  <div><b>NornicDB-TPU</b> console</div>
  <div id="stats">loading…</div>
</header>
<main>
  <section class="wide">
    <h2>Cypher</h2>
    <textarea id="cypher">MATCH (n) RETURN n LIMIT 10</textarea>
    <button onclick="runCypher()">Run (Ctrl-Enter)</button>
    <pre id="cypher-out"></pre>
  </section>
  <section>
    <h2>Hybrid search</h2>
    <input id="q" placeholder="semantic + fulltext query">
    <button onclick="runSearch()">Search</button>
    <pre id="search-out"></pre>
  </section>
  <section>
    <h2>Heimdall</h2>
    <input id="chat" placeholder="ask the assistant">
    <button onclick="runChat()">Send</button>
    <pre id="chat-out"></pre>
  </section>
</main>
<script>
async function post(path, body) {
  const r = await fetch(path, {method:'POST',
    headers:{'Content-Type':'application/json'}, body:JSON.stringify(body)});
  return r.json();
}
function esc(s){const d=document.createElement('div');d.innerText=s;return d.innerHTML;}
async function refreshStats() {
  try {
    const s = await (await fetch('/status')).json();
    document.getElementById('stats').innerText =
      `${s.nodes} nodes · ${s.edges} edges · up ${Math.round(s.uptime_seconds)}s`;
  } catch (e) {}
}
async function runCypher() {
  const out = document.getElementById('cypher-out');
  const stmt = document.getElementById('cypher').value;
  try {
    const r = await post('/db/neo4j/tx/commit', {statements:[{statement:stmt}]});
    if (r.errors && r.errors.length) {
      out.innerHTML = '<span class="err">' + esc(r.errors[0].message) + '</span>';
    } else {
      const res = r.results[0] || {columns:[], data:[]};
      let html = '<table><tr>' + res.columns.map(c=>'<th>'+esc(c)+'</th>').join('') + '</tr>';
      for (const row of res.data) {
        html += '<tr>' + row.row.map(v=>'<td>'+esc(JSON.stringify(v))+'</td>').join('') + '</tr>';
      }
      out.innerHTML = html + '</table>' +
        (res.stats && Object.keys(res.stats).length
          ? '<div>'+esc(JSON.stringify(res.stats))+'</div>' : '');
    }
  } catch (e) { out.innerHTML = '<span class="err">'+esc(String(e))+'</span>'; }
  refreshStats();
}
async function runSearch() {
  const out = document.getElementById('search-out');
  const r = await post('/nornicdb/search',
    {query: document.getElementById('q').value, limit: 8});
  out.innerText = (r.results||[]).map(
    x => x.score.toFixed(3) + '  ' + x.content).join('\\n') || '(no results)';
}
async function runChat() {
  const out = document.getElementById('chat-out');
  const r = await post('/api/bifrost/chat/completions',
    {messages:[{role:'user', content: document.getElementById('chat').value}]});
  out.innerText = r.choices ? r.choices[0].message.content : JSON.stringify(r);
}
document.getElementById('cypher').addEventListener('keydown', e => {
  if (e.key === 'Enter' && (e.ctrlKey || e.metaKey)) runCypher();
});
refreshStats();
setInterval(refreshStats, 5000);
</script>
</body>
</html>
"""
