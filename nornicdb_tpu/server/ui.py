"""Embedded web console.

Behavioral reference: /root/reference/ui/ — a React SPA (query console,
AI assistant, login at ui/src/pages/Login.tsx, user admin at
AdminUsers.tsx, security/API-token page at Security.tsx) embedded via
go:embed; headless builds exclude it (-tags noui). This build embeds a
single-file SPA (no build step, no dependencies) with the same views:
login (cookie session via POST /auth/token), Cypher console, hybrid
search, Heimdall chat, admin (user management + live server stats), and
security (change password, generate API tokens) — all speaking the same
HTTP endpoints as the reference UI's utils/api.ts.

Browser-parity affordances (ref: ui/src/pages/Browser.tsx Edit/Trash/
History + DB switcher): query history in localStorage (click to restore,
clear), per-node edit/delete buttons on node-shaped result cells (edit
prompts for a properties JSON then issues `SET n = $props` by id; delete
issues DETACH DELETE), and a database switcher in the header populated
from SHOW DATABASES that retargets /db/{name}/tx/commit.
"""

UI_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>NornicDB-TPU Console</title>
<style>
  :root { --bg:#11151c; --panel:#1a2029; --fg:#d8dee9; --accent:#5fb3b3;
          --muted:#6c7a89; --err:#bf616a; --ok:#a3be8c; }
  * { box-sizing: border-box; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:14px/1.5 ui-monospace, Menlo, monospace; }
  header { padding:12px 20px; border-bottom:1px solid #2a313c;
           display:flex; justify-content:space-between; align-items:center; }
  header b { color:var(--accent); }
  nav a { color:var(--muted); margin-right:14px; cursor:pointer;
          text-decoration:none; }
  nav a.active, nav a:hover { color:var(--accent); }
  #stats, #whoami { color:var(--muted); font-size:12px; }
  main { display:grid; grid-template-columns:1fr 1fr; gap:14px; padding:14px; }
  section { background:var(--panel); border-radius:8px; padding:14px; }
  section.wide { grid-column: 1 / span 2; }
  h2 { margin:0 0 10px; font-size:13px; color:var(--accent);
       text-transform:uppercase; letter-spacing:1px; }
  textarea, input, select { width:100%; background:#0d1117; color:var(--fg);
      border:1px solid #2a313c; border-radius:6px; padding:8px;
      font:inherit; }
  textarea { min-height:72px; resize:vertical; }
  button { margin-top:8px; background:var(--accent); color:#0d1117;
      border:0; border-radius:6px; padding:7px 16px; font:inherit;
      font-weight:bold; cursor:pointer; }
  button.small { margin:0; padding:2px 8px; font-weight:normal; }
  button.danger { background:var(--err); color:#fff; }
  pre { background:#0d1117; border-radius:6px; padding:10px; overflow:auto;
        max-height:320px; white-space:pre-wrap; }
  .err { color:var(--err); }
  .ok { color:var(--ok); }
  table { border-collapse:collapse; width:100%; }
  td, th { border:1px solid #2a313c; padding:4px 8px; text-align:left; }
  th { color:var(--accent); }
  #login-view { max-width:360px; margin:80px auto; }
  #login-view input { margin-bottom:10px; }
  .hidden { display:none !important; }
  .row { display:flex; gap:8px; align-items:center; }
</style>
</head>
<body>
<header>
  <div><b>NornicDB-TPU</b> console</div>
  <nav id="nav" class="hidden">
    <a data-view="console" href="/" onclick="return go(event,'console')">Console</a>
    <a data-view="admin" href="/admin" onclick="return go(event,'admin')">Admin</a>
    <a data-view="security" href="/security" onclick="return go(event,'security')">Security</a>
  </nav>
  <div class="row">
    <select id="db-select" class="hidden" style="width:auto"
            onchange="switchDb(this.value)"></select>
    <div id="whoami"></div>
    <button id="logout-btn" class="small hidden" onclick="logout()">logout</button>
    <div id="stats">loading…</div>
  </div>
</header>

<div id="login-view" class="hidden">
  <section>
    <h2>Sign in</h2>
    <input id="login-user" placeholder="username" autocomplete="username">
    <input id="login-pass" placeholder="password" type="password"
           autocomplete="current-password">
    <button onclick="doLogin()">Sign in</button>
    <div id="login-oauth"></div>
    <pre id="login-err" class="err hidden"></pre>
  </section>
</div>

<main id="console-view" class="hidden">
  <section class="wide">
    <h2>Cypher</h2>
    <textarea id="cypher">MATCH (n) RETURN n LIMIT 10</textarea>
    <div class="row">
      <button onclick="runCypher()">Run (Ctrl-Enter)</button>
      <button class="small" onclick="toggleHistory()">History</button>
    </div>
    <div id="history-panel" class="hidden">
      <div class="row" style="justify-content:space-between">
        <h2 style="margin:10px 0 4px">Query history</h2>
        <button class="small danger" onclick="clearHistory()">clear</button>
      </div>
      <div id="history-list"></div>
    </div>
    <pre id="cypher-out"></pre>
  </section>
  <section>
    <h2>Hybrid search</h2>
    <input id="q" placeholder="semantic + fulltext query">
    <button onclick="runSearch()">Search</button>
    <pre id="search-out"></pre>
  </section>
  <section>
    <h2>Heimdall</h2>
    <input id="chat" placeholder="ask the assistant">
    <button onclick="runChat()">Send</button>
    <pre id="chat-out"></pre>
  </section>
</main>

<main id="admin-view" class="hidden">
  <section>
    <h2>Users</h2>
    <div id="users-table">loading…</div>
    <h2 style="margin-top:14px">Create user</h2>
    <div class="row">
      <input id="new-user" placeholder="username">
      <input id="new-pass" placeholder="password" type="password">
      <select id="new-role">
        <option>viewer</option><option>editor</option><option>admin</option>
      </select>
    </div>
    <button onclick="createUser()">Create</button>
    <pre id="admin-msg" class="hidden"></pre>
  </section>
  <section>
    <h2>Server stats</h2>
    <div id="admin-stats">loading…</div>
    <button onclick="loadStats()">Refresh</button>
  </section>
</main>

<main id="security-view" class="hidden">
  <section>
    <h2>Change password</h2>
    <input id="old-pass" placeholder="current password" type="password">
    <input id="new-pass2" placeholder="new password" type="password"
           style="margin-top:8px">
    <button onclick="changePassword()">Change</button>
    <pre id="pw-msg" class="hidden"></pre>
  </section>
  <section>
    <h2>Generate API token</h2>
    <input id="token-subject" placeholder="label, e.g. my-mcp-server">
    <select id="token-ttl" style="margin-top:8px">
      <option value="3600">1 hour</option>
      <option value="86400">1 day</option>
      <option value="2592000">30 days</option>
      <option value="31536000" selected>1 year</option>
    </select>
    <button onclick="genToken()">Generate</button>
    <pre id="token-out" class="hidden"></pre>
  </section>
</main>

<script>
let ME = null, AUTH_ON = false, DB = 'neo4j';

async function post(path, body) {
  const r = await fetch(path, {method:'POST', credentials:'include',
    headers:{'Content-Type':'application/json'}, body:JSON.stringify(body)});
  return r.json();
}
async function get(path) {
  const r = await fetch(path, {credentials:'include'});
  if (r.status === 401) throw new Error('unauthorized');
  return r.json();
}
function esc(s){return String(s).replace(/[&<>"']/g, c => ({
  '&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));}
function show(id){
  for (const v of ['login-view','console-view','admin-view','security-view'])
    document.getElementById(v).classList.add('hidden');
  document.getElementById(id).classList.remove('hidden');
}

function go(ev, view) {
  if (ev) ev.preventDefault();
  document.querySelectorAll('nav a').forEach(a =>
    a.classList.toggle('active', a.dataset.view === view));
  history.replaceState(null, '', {console:'/', admin:'/admin',
    security:'/security'}[view] || '/');
  show(view + '-view');
  if (view === 'admin') { loadUsers(); loadStats(); }
  return false;
}

async function boot() {
  let cfg = {securityEnabled: false, oauthProviders: []};
  try { cfg = await get('/auth/config'); } catch (e) {}
  AUTH_ON = cfg.securityEnabled;
  if (AUTH_ON) {
    try {
      ME = await get('/auth/me');
    } catch (e) {
      // not signed in -> login view
      const oa = document.getElementById('login-oauth');
      oa.innerHTML = (cfg.oauthProviders||[]).map(p =>
        `<button onclick="location='${p.url}'">${esc(p.displayName)}</button>`
      ).join('');
      show('login-view');
      return;
    }
  } else {
    ME = {username:'anonymous', roles:['admin']};
  }
  document.getElementById('nav').classList.remove('hidden');
  document.getElementById('whoami').innerText =
    ME.username + ' (' + (ME.roles||[]).join(',') + ')';
  if (AUTH_ON)
    document.getElementById('logout-btn').classList.remove('hidden');
  const isAdmin = (ME.roles||[]).includes('admin');
  document.querySelector('nav a[data-view=admin]')
    .classList.toggle('hidden', !isAdmin);
  const path = location.pathname;
  go(null, path === '/admin' && isAdmin ? 'admin'
        : path === '/security' ? 'security' : 'console');
  refreshStats();
  loadDatabases();
}

async function doLogin() {
  const errBox = document.getElementById('login-err');
  errBox.classList.add('hidden');
  const r = await fetch('/auth/token', {method:'POST', credentials:'include',
    headers:{'Content-Type':'application/json'},
    body: JSON.stringify({
      username: document.getElementById('login-user').value,
      password: document.getElementById('login-pass').value})});
  if (!r.ok) {
    const e = await r.json().catch(() => ({error:'login failed'}));
    errBox.innerText = e.error || 'login failed';
    errBox.classList.remove('hidden');
    return;
  }
  await boot();
}

async function logout() {
  await post('/auth/logout', {});
  ME = null;
  document.getElementById('nav').classList.add('hidden');
  document.getElementById('logout-btn').classList.add('hidden');
  document.getElementById('whoami').innerText = '';
  show('login-view');
}

async function refreshStats() {
  try {
    const s = await get('/status');
    document.getElementById('stats').innerText =
      `${s.nodes} nodes · ${s.edges} edges · up ${Math.round(s.uptime_seconds)}s`;
  } catch (e) {}
}

// -- database switcher (ref: Browser.tsx DB selector) ------------------------
async function loadDatabases() {
  try {
    const r = await post(`/db/${DB}/tx/commit`,
      {statements:[{statement:'SHOW DATABASES'}]});
    const res = (r.results||[])[0];
    if (!res) return;
    const nameIdx = res.columns.indexOf('name');
    const sel = document.getElementById('db-select');
    sel.innerHTML = '';
    for (const row of res.data) {
      const o = document.createElement('option');
      o.value = o.text = row.row[nameIdx];
      o.selected = (o.value === DB);
      sel.add(o);
    }
    sel.classList.remove('hidden');
  } catch (e) {}
}
function switchDb(name) {
  DB = name;
  document.getElementById('cypher-out').innerHTML = '';
  refreshStats();
}

// -- query history (ref: Browser.tsx History affordance) ---------------------
const HIST_KEY = 'nornic_query_history', HIST_MAX = 50;
function loadHistory() {
  try { return JSON.parse(localStorage.getItem(HIST_KEY)) || []; }
  catch (e) { return []; }
}
function pushHistory(stmt) {
  stmt = stmt.trim();
  if (!stmt) return;
  const h = loadHistory().filter(q => q !== stmt);
  h.unshift(stmt);
  localStorage.setItem(HIST_KEY, JSON.stringify(h.slice(0, HIST_MAX)));
  renderHistory();
}
function clearHistory() {
  localStorage.removeItem(HIST_KEY);
  renderHistory();
}
function toggleHistory() {
  document.getElementById('history-panel').classList.toggle('hidden');
  renderHistory();
}
function renderHistory() {
  const box = document.getElementById('history-list');
  box.innerHTML = '';
  const h = loadHistory();
  if (!h.length) { box.innerText = '(empty)'; return; }
  for (const q of h) {
    const a = document.createElement('a');
    a.href = '#';
    a.style.display = 'block';
    a.style.color = 'var(--muted)';
    a.innerText = q.length > 120 ? q.slice(0, 120) + '…' : q;
    a.addEventListener('click', ev => {
      ev.preventDefault();
      document.getElementById('cypher').value = q;
    });
    box.appendChild(a);
  }
}

// -- node affordances in results (ref: Browser.tsx Edit/Trash) ---------------
function isNodeValue(v) {
  return v && typeof v === 'object' && !Array.isArray(v) &&
    typeof v.id === 'string' && Array.isArray(v.labels) &&
    typeof v.properties === 'object';
}
function txFailed(r) {
  // the tx API reports statement failures in errors[]; auth/transport
  // failures come back as {error: ...} — surface either, never swallow
  if (r && r.errors && r.errors.length) return r.errors[0].message;
  if (r && r.error) return r.error;
  return null;
}
async function editNode(node) {
  const txt = prompt('properties JSON for (' + node.labels.join(':') + ')',
                     JSON.stringify(node.properties));
  if (txt === null) return;
  let props;
  try { props = JSON.parse(txt); }
  catch (e) { alert('invalid JSON: ' + e); return; }
  const r = await post(`/db/${DB}/tx/commit`, {statements:[{
    statement: 'MATCH (n) WHERE id(n) = $id SET n = $props',
    parameters: {id: node.id, props}}]});
  const err = txFailed(r);
  if (err) { alert('edit failed: ' + err); return; }
  runCypher(true);
}
async function deleteNode(node) {
  if (!confirm('DETACH DELETE node ' + node.id + '?')) return;
  const r = await post(`/db/${DB}/tx/commit`, {statements:[{
    statement: 'MATCH (n) WHERE id(n) = $id DETACH DELETE n',
    parameters: {id: node.id}}]});
  const err = txFailed(r);
  if (err) { alert('delete failed: ' + err); return; }
  runCypher(true);
}

async function runCypher(rerun) {
  const out = document.getElementById('cypher-out');
  const stmt = document.getElementById('cypher').value;
  if (!rerun) pushHistory(stmt);
  try {
    const r = await post(`/db/${DB}/tx/commit`, {statements:[{statement:stmt}]});
    if (r.errors && r.errors.length) {
      out.innerHTML = '<span class="err">' + esc(r.errors[0].message) + '</span>';
    } else {
      const res = r.results[0] || {columns:[], data:[]};
      const table = document.createElement('table');
      const head = document.createElement('tr');
      head.innerHTML = res.columns.map(c=>'<th>'+esc(c)+'</th>').join('');
      table.appendChild(head);
      for (const row of res.data) {
        const tr = document.createElement('tr');
        for (const v of row.row) {
          const td = document.createElement('td');
          td.innerText = JSON.stringify(v);
          if (isNodeValue(v)) {
            td.append(document.createElement('br'));
            const ed = document.createElement('button');
            ed.className = 'small';
            ed.innerText = 'edit';
            ed.addEventListener('click', () => editNode(v));
            const del = document.createElement('button');
            del.className = 'small danger';
            del.innerText = 'delete';
            del.addEventListener('click', () => deleteNode(v));
            td.append(ed, del);
          }
          tr.appendChild(td);
        }
        table.appendChild(tr);
      }
      out.innerHTML = '';
      out.appendChild(table);
      if (res.stats && Object.keys(res.stats).length) {
        const d = document.createElement('div');
        d.innerText = JSON.stringify(res.stats);
        out.appendChild(d);
      }
    }
  } catch (e) { out.innerHTML = '<span class="err">'+esc(String(e))+'</span>'; }
  refreshStats();
}

async function runSearch() {
  const out = document.getElementById('search-out');
  const r = await post('/nornicdb/search',
    {query: document.getElementById('q').value, limit: 8});
  out.innerText = (r.results||[]).map(
    x => x.score.toFixed(3) + '  ' + x.content).join('\\n') || '(no results)';
}

async function runChat() {
  const out = document.getElementById('chat-out');
  const r = await post('/api/bifrost/chat/completions',
    {messages:[{role:'user', content: document.getElementById('chat').value}]});
  out.innerText = r.choices ? r.choices[0].message.content : JSON.stringify(r);
}

// -- admin view --------------------------------------------------------------
async function loadUsers() {
  const box = document.getElementById('users-table');
  try {
    const users = await get('/auth/users');
    // build rows with addEventListener, never string-interpolated inline
    // handlers — usernames are user-controlled input
    const table = document.createElement('table');
    table.innerHTML =
      '<tr><th>user</th><th>role</th><th>status</th><th></th></tr>';
    for (const u of users) {
      const role = (u.roles||[])[0] || 'viewer';
      const tr = document.createElement('tr');
      const tdName = document.createElement('td');
      tdName.innerText = u.username;
      const tdRole = document.createElement('td');
      const sel = document.createElement('select');
      for (const r of ['viewer','editor','admin']) {
        const o = document.createElement('option');
        o.text = r; o.selected = (r === role);
        sel.add(o);
      }
      sel.addEventListener('change', () => setRole(u.username, sel.value));
      tdRole.appendChild(sel);
      const tdStatus = document.createElement('td');
      tdStatus.innerHTML = u.disabled
        ? '<span class="err">disabled</span>'
        : '<span class="ok">active</span>';
      const tdActions = document.createElement('td');
      tdActions.className = 'row';
      const toggle = document.createElement('button');
      toggle.className = 'small';
      toggle.innerText = u.disabled ? 'enable' : 'disable';
      toggle.addEventListener('click', () =>
        setDisabled(u.username, !u.disabled));
      const del = document.createElement('button');
      del.className = 'small danger';
      del.innerText = 'delete';
      del.addEventListener('click', () => deleteUser(u.username));
      tdActions.append(toggle, del);
      tr.append(tdName, tdRole, tdStatus, tdActions);
      table.appendChild(tr);
    }
    box.innerHTML = '';
    box.appendChild(table);
  } catch (e) {
    box.innerHTML = '<span class="err">' + esc(String(e)) + '</span>';
  }
}
function adminMsg(text, isErr) {
  const m = document.getElementById('admin-msg');
  m.innerText = text; m.className = isErr ? 'err' : 'ok';
}
async function createUser() {
  const r = await fetch('/auth/users', {method:'POST', credentials:'include',
    headers:{'Content-Type':'application/json'},
    body: JSON.stringify({
      username: document.getElementById('new-user').value,
      password: document.getElementById('new-pass').value,
      roles: [document.getElementById('new-role').value]})});
  const body = await r.json();
  adminMsg(r.ok ? 'created ' + body.username : (body.error||'failed'), !r.ok);
  loadUsers();
}
async function setRole(name, role) {
  await fetch('/auth/users/' + encodeURIComponent(name), {method:'PUT',
    credentials:'include', headers:{'Content-Type':'application/json'},
    body: JSON.stringify({roles:[role]})});
  loadUsers();
}
async function setDisabled(name, disabled) {
  await fetch('/auth/users/' + encodeURIComponent(name), {method:'PUT',
    credentials:'include', headers:{'Content-Type':'application/json'},
    body: JSON.stringify({disabled})});
  loadUsers();
}
async function deleteUser(name) {
  if (!confirm('delete user ' + name + '?')) return;
  await fetch('/auth/users/' + encodeURIComponent(name),
    {method:'DELETE', credentials:'include'});
  loadUsers();
}
async function loadStats() {
  const box = document.getElementById('admin-stats');
  try {
    const s = await get('/admin/stats');
    let rows = '';
    const flat = (obj, prefix) => {
      for (const [k, v] of Object.entries(obj)) {
        if (v && typeof v === 'object' && !Array.isArray(v))
          flat(v, prefix + k + '.');
        else
          rows += `<tr><td>${esc(prefix+k)}</td><td>${esc(JSON.stringify(v))}</td></tr>`;
      }
    };
    flat(s, '');
    box.innerHTML = '<table><tr><th>metric</th><th>value</th></tr>' + rows + '</table>';
  } catch (e) {
    box.innerHTML = '<span class="err">' + esc(String(e)) + '</span>';
  }
}

// -- security view -----------------------------------------------------------
async function changePassword() {
  const m = document.getElementById('pw-msg');
  m.classList.remove('hidden');
  const r = await fetch('/auth/password', {method:'POST', credentials:'include',
    headers:{'Content-Type':'application/json'},
    body: JSON.stringify({
      old_password: document.getElementById('old-pass').value,
      new_password: document.getElementById('new-pass2').value})});
  const body = await r.json();
  m.innerText = r.ok ? 'password changed' : (body.error || 'failed');
  m.className = r.ok ? 'ok' : 'err';
}
async function genToken() {
  const out = document.getElementById('token-out');
  out.classList.remove('hidden');
  const r = await fetch('/auth/api-token', {method:'POST', credentials:'include',
    headers:{'Content-Type':'application/json'},
    body: JSON.stringify({
      subject: document.getElementById('token-subject').value,
      expires_in: parseInt(document.getElementById('token-ttl').value)})});
  const body = await r.json();
  out.innerText = r.ok
    ? 'Token (copy now — not shown again):\\n' + body.token
    : (body.error || 'failed');
  out.className = r.ok ? '' : 'err';
}

document.getElementById('cypher').addEventListener('keydown', e => {
  if (e.key === 'Enter' && (e.ctrlKey || e.metaKey)) runCypher();
});
boot();
setInterval(refreshStats, 5000);
</script>
</body>
</html>
"""
