"""Authentication + RBAC (ref: /root/reference/pkg/auth/)."""

from nornicdb_tpu.auth.auth import (
    PERM_ADMIN,
    PERM_CREATE,
    PERM_DELETE,
    PERM_READ,
    PERM_USER_MANAGE,
    PERM_WRITE,
    ROLE_ADMIN,
    ROLE_EDITOR,
    ROLE_NONE,
    ROLE_PERMISSIONS,
    ROLE_VIEWER,
    AuthConfig,
    Authenticator,
    User,
    hash_password,
    verify_password,
)

__all__ = [
    "PERM_ADMIN", "PERM_CREATE", "PERM_DELETE", "PERM_READ",
    "PERM_USER_MANAGE", "PERM_WRITE", "ROLE_ADMIN", "ROLE_EDITOR",
    "ROLE_NONE", "ROLE_PERMISSIONS", "ROLE_VIEWER", "AuthConfig",
    "Authenticator", "User", "hash_password", "verify_password",
]
