"""Authentication + RBAC.

Behavioral reference: /root/reference/pkg/auth/auth.go —
roles admin/editor/viewer/none (:160-163), permissions read/write/create/
delete/admin/user_manage (:171-176), bcrypt passwords (here: scrypt — no
external deps), users persisted as nodes in the system DB (:634-747), JWT
issue/validate/logout (:970, :1131), account lockout, audit event hook
(:619).

JWT is HS256 implemented with hmac/hashlib (no external jwt dependency).
"""

from __future__ import annotations

import base64
import logging
import hashlib
import hmac
import json
import os
import re
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from nornicdb_tpu.errors import AuthError, NotFoundError
from nornicdb_tpu.storage.types import Engine, Node
from nornicdb_tpu.telemetry.metrics import count_error as _count_error

logger = logging.getLogger(__name__)

# roles (ref: auth.go:160-163)
ROLE_ADMIN = "admin"
ROLE_EDITOR = "editor"
ROLE_VIEWER = "viewer"
ROLE_NONE = "none"

# permissions (ref: auth.go:171-176)
PERM_READ = "read"
PERM_WRITE = "write"
PERM_CREATE = "create"
PERM_DELETE = "delete"
PERM_ADMIN = "admin"
PERM_USER_MANAGE = "user_manage"

ROLE_PERMISSIONS = {
    ROLE_ADMIN: {
        PERM_READ, PERM_WRITE, PERM_CREATE, PERM_DELETE, PERM_ADMIN,
        PERM_USER_MANAGE,
    },
    ROLE_EDITOR: {PERM_READ, PERM_WRITE, PERM_CREATE, PERM_DELETE},
    ROLE_VIEWER: {PERM_READ},
    ROLE_NONE: set(),
}

_USER_LABEL = "_User"


def _b64(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


def hash_password(password: str, salt: Optional[bytes] = None) -> str:
    salt = salt or secrets.token_bytes(16)
    digest = hashlib.scrypt(
        password.encode(), salt=salt, n=2**14, r=8, p=1, dklen=32
    )
    return f"scrypt${_b64(salt)}${_b64(digest)}"


def verify_password(password: str, stored: str) -> bool:
    try:
        scheme, salt_s, digest_s = stored.split("$")
        if scheme != "scrypt":
            return False
        salt, digest = _unb64(salt_s), _unb64(digest_s)
        got = hashlib.scrypt(
            password.encode(), salt=salt, n=2**14, r=8, p=1, dklen=32
        )
        return hmac.compare_digest(got, digest)
    except (ValueError, TypeError):
        # malformed stored hash (wrong field count, bad base64, bad
        # scrypt params): treat as a non-match, never an auth crash
        return False


@dataclass
class User:
    username: str
    role: str = ROLE_VIEWER
    password_hash: str = ""
    created_at: float = field(default_factory=time.time)
    disabled: bool = False
    failed_attempts: int = 0
    locked_until: float = 0.0


@dataclass
class AuthConfig:
    token_ttl: float = 24 * 3600.0
    lockout_threshold: int = 5  # (ref: account lockout)
    lockout_duration: float = 300.0
    secret: Optional[str] = None


class Authenticator:
    """(ref: auth.Authenticator auth.go:362; NewAuthenticator :582)"""

    def __init__(
        self,
        system_storage: Engine,
        config: Optional[AuthConfig] = None,
        audit_hook: Optional[Callable[[str, dict], None]] = None,
    ):
        self.storage = system_storage
        self.config = config or AuthConfig()
        self.secret = (self.config.secret or secrets.token_hex(32)).encode()
        self.audit_hook = audit_hook
        self._lock = threading.RLock()
        self._revoked: set[str] = set()

    # -- audit ------------------------------------------------------------------
    def _audit(self, event: str, detail: dict) -> None:
        """(ref: audit event hook auth.go:619)"""
        if self.audit_hook is not None:
            try:
                self.audit_hook(event, detail)
            except Exception:
                logger.exception("audit hook failed for event %s", event)
                _count_error("auth")

    # -- user management (users as system-DB nodes, ref: auth.go:634-747) ------
    def _user_node_id(self, username: str) -> str:
        return f"user-{username}"

    _USERNAME_RE = re.compile(r"^[A-Za-z0-9._@-]{1,64}$")

    def create_user(
        self, username: str, password: str, role: str = ROLE_VIEWER
    ) -> User:
        if role not in ROLE_PERMISSIONS:
            raise AuthError(f"unknown role {role}")
        if not self._USERNAME_RE.match(username):
            raise AuthError(
                "invalid username (allowed: letters, digits, . _ @ -, max 64)"
            )
        user = User(username=username, role=role, password_hash=hash_password(password))
        node = Node(
            id=self._user_node_id(username),
            labels=[_USER_LABEL],
            properties={
                "username": username,
                "role": role,
                "password_hash": user.password_hash,
                "created_at": user.created_at,
                "disabled": False,
            },
        )
        self.storage.create_node(node)
        self._audit("user_created", {"username": username, "role": role})
        return user

    def get_user(self, username: str) -> User:
        try:
            n = self.storage.get_node(self._user_node_id(username))
        except NotFoundError:
            raise AuthError(f"user {username} not found")
        p = n.properties
        return User(
            username=p["username"],
            role=p.get("role", ROLE_VIEWER),
            password_hash=p.get("password_hash", ""),
            created_at=p.get("created_at", 0.0),
            disabled=p.get("disabled", False),
            failed_attempts=p.get("failed_attempts", 0),
            locked_until=p.get("locked_until", 0.0),
        )

    def _save_user(self, user: User) -> None:
        n = self.storage.get_node(self._user_node_id(user.username))
        n.properties.update(
            {
                "role": user.role,
                "password_hash": user.password_hash,
                "disabled": user.disabled,
                "failed_attempts": user.failed_attempts,
                "locked_until": user.locked_until,
            }
        )
        self.storage.update_node(n)

    def list_users(self) -> list[User]:
        out = []
        for n in self.storage.get_nodes_by_label(_USER_LABEL):
            out.append(
                User(
                    username=n.properties["username"],
                    role=n.properties.get("role", ROLE_VIEWER),
                    created_at=n.properties.get("created_at", 0.0),
                    disabled=n.properties.get("disabled", False),
                )
            )
        return sorted(out, key=lambda u: u.username)

    def delete_user(self, username: str) -> None:
        try:
            self.storage.delete_node(self._user_node_id(username))
            self._audit("user_deleted", {"username": username})
        except NotFoundError:
            raise AuthError(f"user {username} not found")

    def set_password(self, username: str, password: str) -> None:
        user = self.get_user(username)
        user.password_hash = hash_password(password)
        self._save_user(user)
        self._audit("password_changed", {"username": username})

    def set_disabled(self, username: str, disabled: bool) -> None:
        """(ref: DisableUser/EnableUser, server_auth.go handleUserByID PUT)"""
        user = self.get_user(username)
        user.disabled = disabled
        self._save_user(user)
        self._audit(
            "user_disabled" if disabled else "user_enabled",
            {"username": username},
        )

    def set_role(self, username: str, role: str) -> None:
        if role not in ROLE_PERMISSIONS:
            raise AuthError(f"unknown role {role}")
        user = self.get_user(username)
        user.role = role
        self._save_user(user)
        self._audit("role_changed", {"username": username, "role": role})

    # -- authentication -----------------------------------------------------------
    def check_password(self, username: str, password: str) -> bool:
        """Full login-semantics check for protocol authentication (Bolt,
        Qdrant gRPC): enforces disabled accounts, lockout counters, and
        audit events exactly like authenticate()."""
        try:
            return self.authenticate(username, password) is not None
        except AuthError:
            return False

    def verify_current_password(self, username: str, password: str) -> bool:
        """Verification for password-change flows: no token minting and no
        login_ok/login_failed events, but failed attempts DO count toward
        the account lockout and are audited — otherwise a hijacked session
        could brute-force the current password unthrottled through
        POST /auth/password while authenticate()'s lockout never engages."""
        # audit events collected under the lock, emitted after release: the
        # hook is externally supplied code (nornlint NL-LK03) — an audit
        # sink that logged back through this Authenticator would deadlock,
        # and a slow sink would serialize every login behind it
        events: list[tuple[str, dict]] = []
        try:
            with self._lock:
                try:
                    user = self.get_user(username)
                except AuthError:
                    return False
                now = time.time()
                if user.locked_until > now:
                    events.append((
                        "password_verify_rejected",
                        {"username": username, "reason": "locked"},
                    ))
                    return False
                if not verify_password(password, user.password_hash):
                    user.failed_attempts += 1
                    if user.failed_attempts >= self.config.lockout_threshold:
                        user.locked_until = now + self.config.lockout_duration
                        user.failed_attempts = 0
                    self._save_user(user)
                    events.append(
                        ("password_verify_failed", {"username": username}))
                    return False
                if user.failed_attempts:
                    user.failed_attempts = 0
                    self._save_user(user)
                return True
        finally:
            for event, detail in events:
                self._audit(event, detail)

    def authenticate(self, username: str, password: str) -> str:
        """Returns a JWT on success (ref: Authenticate auth.go:970)."""
        # same deferred-audit contract as verify_current_password: the hook
        # never runs under self._lock
        events: list[tuple[str, dict]] = []
        try:
            with self._lock:
                user = self.get_user(username)
                now = time.time()
                if user.disabled:
                    events.append(("login_rejected",
                                   {"username": username, "reason": "disabled"}))
                    raise AuthError("account disabled")
                if user.locked_until > now:
                    events.append(("login_rejected",
                                   {"username": username, "reason": "locked"}))
                    raise AuthError("account locked")
                if not verify_password(password, user.password_hash):
                    user.failed_attempts += 1
                    if user.failed_attempts >= self.config.lockout_threshold:
                        user.locked_until = now + self.config.lockout_duration
                        user.failed_attempts = 0
                    self._save_user(user)
                    events.append(("login_failed", {"username": username}))
                    raise AuthError("invalid credentials")
                if user.failed_attempts:
                    user.failed_attempts = 0
                    self._save_user(user)
        finally:
            for event, detail in events:
                self._audit(event, detail)
        token = self.issue_token(username, user.role)
        self._audit("login_ok", {"username": username})
        return token

    # -- JWT ---------------------------------------------------------------------
    def issue_token(
        self, username: str, role: str, ttl: Optional[float] = None
    ) -> str:
        header = {"alg": "HS256", "typ": "JWT"}
        now = int(time.time())
        payload = {
            "sub": username,
            "role": role,
            "iat": now,
            "exp": now + int(ttl if ttl is not None else self.config.token_ttl),
            "jti": secrets.token_hex(8),
        }
        h = _b64(json.dumps(header, separators=(",", ":")).encode())
        p = _b64(json.dumps(payload, separators=(",", ":")).encode())
        sig = hmac.new(self.secret, f"{h}.{p}".encode(), hashlib.sha256).digest()
        return f"{h}.{p}.{_b64(sig)}"

    def validate_token(self, token: str) -> Optional[dict[str, Any]]:
        """(ref: ValidateToken auth.go:1131)"""
        try:
            h, p, s = token.split(".")
            expected = hmac.new(self.secret, f"{h}.{p}".encode(), hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _unb64(s)):
                return None
            payload = json.loads(_unb64(p))
            if payload.get("exp", 0) < time.time():
                return None
            if payload.get("jti") in self._revoked:
                return None
            return payload
        except (ValueError, TypeError, KeyError):
            # malformed token (field count, base64, JSON, digest types):
            # invalid credential, not an error path worth logging
            return None

    def logout(self, token: str) -> None:
        payload = self.validate_token(token)
        if payload and "jti" in payload:
            with self._lock:
                self._revoked.add(payload["jti"])
            self._audit("logout", {"username": payload.get("sub")})

    # -- authorization ---------------------------------------------------------------
    def has_permission(self, role: str, permission: str) -> bool:
        return permission in ROLE_PERMISSIONS.get(role, set())

    def authorize(self, token: str, permission: str) -> dict[str, Any]:
        payload = self.validate_token(token)
        if payload is None:
            raise AuthError("invalid or expired token")
        # cut off live sessions of disabled accounts: a still-valid JWT for
        # a user the admin has since disabled must stop authorizing (API
        # tokens whose subject isn't a stored user are unaffected)
        try:
            user = self.get_user(payload.get("sub", ""))
        except AuthError:
            user = None
        if user is not None and user.disabled:
            raise AuthError("account disabled")
        if not self.has_permission(payload.get("role", ROLE_NONE), permission):
            raise AuthError(f"permission {permission} denied")
        return payload
