"""APOC graph-access function categories: node / rel / nodes / label /
neighbors / atomic / meta / schema / search / create / merge / graph /
cypher / community / algo / paths / path.

Behavioral reference: /root/reference/apoc/apoc.go registerAllFunctions and
the per-category dirs (node/, rel/, label/, community/community.go, ...).
Mutating functions persist through the executor's storage and return the
updated entity; community/algo delegate to the TPU segment-reduce
implementations in ops/graph_algos.py (the same kernels behind the gds.*
procedures). Where the reference takes Go func-typed predicate params that
Cypher can't express (nodes.go:301 Filter), the predicate is a Cypher
expression string evaluated with the entity bound as `n` — strictly more
usable from the query language.
"""

from __future__ import annotations

import fnmatch
import re
import threading
import uuid as _uuid
from typing import Any

from nornicdb_tpu.apoc.registry import register
from nornicdb_tpu.errors import NornicError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Node

_atomic_lock = threading.RLock()
# parsed-predicate memo: concurrent Cypher sessions evaluate apoc
# predicates on their own threads, so reads/writes go under a lock
_expr_memo: dict[str, Any] = {}
_expr_memo_lock = threading.Lock()


def _graph_fn(name):
    """register + needs_executor marker."""

    def deco(fn):
        fn.needs_executor = True
        return register(name)(fn)

    return deco


def _node(ex, v) -> Node:
    if isinstance(v, Node):
        return v
    n = ex.get_node_or_none(str(v))
    if n is None:
        raise NotFoundError(f"node {v} not found")
    return n


def _edge(ex, v) -> Edge:
    if isinstance(v, Edge):
        return v
    return ex.storage.get_edge(str(v))


def _save_node(ex, node: Node) -> Node:
    return ex.storage.update_node(node)


def _save_edge(ex, edge: Edge) -> Edge:
    return ex.storage.update_edge(edge)


def _eval_pred(ex, expr_text: str, bindings: dict) -> Any:
    from nornicdb_tpu.cypher.expr import EvalContext, evaluate
    from nornicdb_tpu.cypher.parser import parse

    with _expr_memo_lock:
        e = _expr_memo.get(expr_text)
    if e is None:
        q = parse(f"RETURN {expr_text}")
        e = q.clauses[0].items[0].expr
        with _expr_memo_lock:
            _expr_memo[expr_text] = e
    return evaluate(e, EvalContext(bindings, {}, ex))


# ============================================================== apoc.node
@_graph_fn("apoc.node.degreeIn")
def node_degree_in(ex, node):
    return len(ex.storage.get_incoming_edges(_node(ex, node).id))


@_graph_fn("apoc.node.degreeOut")
def node_degree_out(ex, node):
    return len(ex.storage.get_outgoing_edges(_node(ex, node).id))


@register("apoc.node.properties")
def node_properties(node):
    return dict(node.properties) if isinstance(node, (Node, Edge)) else None


@register("apoc.node.property")
def node_property(node, key):
    return node.properties.get(key) if isinstance(node, (Node, Edge)) else None


def _rels_of(ex, node, direction):
    nid = _node(ex, node).id
    out = []
    if direction in ("out", "both"):
        out.extend(ex.storage.get_outgoing_edges(nid))
    if direction in ("in", "both"):
        out.extend(ex.storage.get_incoming_edges(nid))
    return out


@_graph_fn("apoc.node.relationships")
def node_relationships(ex, node, rel_type=None):
    rels = _rels_of(ex, node, "both")
    return [r for r in rels if rel_type is None or r.type == rel_type]


@_graph_fn("apoc.node.relationshipsIn")
def node_relationships_in(ex, node, rel_type=None):
    return [r for r in _rels_of(ex, node, "in")
            if rel_type is None or r.type == rel_type]


@_graph_fn("apoc.node.relationshipsOut")
def node_relationships_out(ex, node, rel_type=None):
    return [r for r in _rels_of(ex, node, "out")
            if rel_type is None or r.type == rel_type]


@_graph_fn("apoc.node.relationshipTypes")
def node_relationship_types(ex, node):
    return sorted({r.type for r in _rels_of(ex, node, "both")})


@_graph_fn("apoc.node.relationshipTypesIn")
def node_relationship_types_in(ex, node):
    return sorted({r.type for r in _rels_of(ex, node, "in")})


@_graph_fn("apoc.node.relationshipTypesOut")
def node_relationship_types_out(ex, node):
    return sorted({r.type for r in _rels_of(ex, node, "out")})


@_graph_fn("apoc.node.relationshipExists")
def node_relationship_exists(ex, node, rel_type=None):
    return any(rel_type is None or r.type == rel_type
               for r in _rels_of(ex, node, "both"))


@_graph_fn("apoc.node.connected")
def node_connected(ex, n1, n2, rel_type=None):
    a, b = _node(ex, n1).id, _node(ex, n2).id
    for r in _rels_of(ex, n1, "both"):
        if rel_type is not None and r.type != rel_type:
            continue
        if b in (r.start_node, r.end_node) and a in (r.start_node, r.end_node):
            if a != b or r.start_node == r.end_node:
                return True
    return False


def _neighbor_ids(ex, node, direction):
    nid = _node(ex, node).id
    out = set()
    for r in _rels_of(ex, node, direction):
        out.add(r.end_node if r.start_node == nid else r.start_node)
    return out


@_graph_fn("apoc.node.neighbors")
def node_neighbors(ex, node):
    return [n for i in sorted(_neighbor_ids(ex, node, "both"))
            if (n := ex.get_node_or_none(i)) is not None]


@_graph_fn("apoc.node.neighborsIn")
def node_neighbors_in(ex, node):
    return [n for i in sorted(_neighbor_ids(ex, node, "in"))
            if (n := ex.get_node_or_none(i)) is not None]


@_graph_fn("apoc.node.neighborsOut")
def node_neighbors_out(ex, node):
    return [n for i in sorted(_neighbor_ids(ex, node, "out"))
            if (n := ex.get_node_or_none(i)) is not None]


@_graph_fn("apoc.node.isDense")
def node_is_dense(ex, node, threshold=50):
    """Degree above threshold (ref: dense-node flag, node.go IsDense)."""
    return len(_rels_of(ex, node, "both")) > int(threshold)


@register("apoc.node.toMap")
def node_to_map(node):
    if not isinstance(node, Node):
        return None
    return {"id": node.id, "labels": list(node.labels),
            "properties": dict(node.properties)}


@_graph_fn("apoc.node.fromMap")
def node_from_map(ex, m):
    """Create a node from {labels, properties[, id]} (persisted)."""
    node = Node(
        id=str(m.get("id") or f"apoc-{_uuid.uuid4()}"),
        labels=list(m.get("labels") or []),
        properties=dict(m.get("properties") or {}),
    )
    return ex.storage.create_node(node)


@_graph_fn("apoc.node.setProperty")
def node_set_property(ex, node, key, value):
    n = _node(ex, node)
    n.properties[key] = value
    return _save_node(ex, n)


@_graph_fn("apoc.node.setProperties")
def node_set_properties(ex, node, props):
    n = _node(ex, node)
    n.properties.update(props or {})
    return _save_node(ex, n)


@_graph_fn("apoc.node.removeProperty")
def node_remove_property(ex, node, key):
    n = _node(ex, node)
    n.properties.pop(key, None)
    return _save_node(ex, n)


@_graph_fn("apoc.node.removeProperties")
def node_remove_properties(ex, node, keys):
    n = _node(ex, node)
    for k in keys or []:
        n.properties.pop(k, None)
    return _save_node(ex, n)


@_graph_fn("apoc.node.addLabel")
def node_add_label(ex, node, label):
    n = _node(ex, node)
    if label not in n.labels:
        n.labels.append(label)
    return _save_node(ex, n)


@_graph_fn("apoc.node.addLabels")
def node_add_labels(ex, node, labels):
    n = _node(ex, node)
    for lbl in labels or []:
        if lbl not in n.labels:
            n.labels.append(lbl)
    return _save_node(ex, n)


@_graph_fn("apoc.node.removeLabel")
def node_remove_label(ex, node, label):
    n = _node(ex, node)
    n.labels = [l for l in n.labels if l != label]
    return _save_node(ex, n)


@_graph_fn("apoc.node.removeLabels")
def node_remove_labels(ex, node, labels):
    n = _node(ex, node)
    drop = set(labels or [])
    n.labels = [l for l in n.labels if l not in drop]
    return _save_node(ex, n)


@_graph_fn("apoc.node.clone")
def node_clone(ex, node):
    n = _node(ex, node)
    return ex.storage.create_node(Node(
        id=f"apoc-{_uuid.uuid4()}", labels=list(n.labels),
        properties=dict(n.properties),
    ))


@register("apoc.node.diff")
def node_diff(n1, n2):
    """Property/label diff (ref node.go Diff shape)."""
    p1 = dict(n1.properties) if isinstance(n1, Node) else {}
    p2 = dict(n2.properties) if isinstance(n2, Node) else {}
    l1 = set(n1.labels) if isinstance(n1, Node) else set()
    l2 = set(n2.labels) if isinstance(n2, Node) else set()
    return {
        "labels": {"onlyLeft": sorted(l1 - l2), "onlyRight": sorted(l2 - l1)},
        "properties": {
            "onlyLeft": {k: v for k, v in p1.items() if k not in p2},
            "onlyRight": {k: v for k, v in p2.items() if k not in p1},
            "different": {
                k: {"left": p1[k], "right": p2[k]}
                for k in p1.keys() & p2.keys() if p1[k] != p2[k]
            },
        },
    }


@register("apoc.node.equals")
def node_equals(n1, n2):
    if not isinstance(n1, Node) or not isinstance(n2, Node):
        return False
    return (sorted(n1.labels) == sorted(n2.labels)
            and n1.properties == n2.properties)


# =============================================================== apoc.rel
@register("apoc.rel.properties")
def rel_properties(rel):
    return dict(rel.properties) if isinstance(rel, Edge) else None


@register("apoc.rel.property")
def rel_property(rel, key):
    return rel.properties.get(key) if isinstance(rel, Edge) else None


@_graph_fn("apoc.rel.nodes")
def rel_nodes(ex, rel):
    r = _edge(ex, rel)
    return [ex.get_node_or_none(r.start_node), ex.get_node_or_none(r.end_node)]


@_graph_fn("apoc.rel.setProperty")
def rel_set_property(ex, rel, key, value):
    r = _edge(ex, rel)
    r.properties[key] = value
    return _save_edge(ex, r)


@_graph_fn("apoc.rel.setProperties")
def rel_set_properties(ex, rel, props):
    r = _edge(ex, rel)
    r.properties.update(props or {})
    return _save_edge(ex, r)


@_graph_fn("apoc.rel.removeProperty")
def rel_remove_property(ex, rel, key):
    r = _edge(ex, rel)
    r.properties.pop(key, None)
    return _save_edge(ex, r)


@_graph_fn("apoc.rel.removeProperties")
def rel_remove_properties(ex, rel, keys):
    r = _edge(ex, rel)
    for k in keys or []:
        r.properties.pop(k, None)
    return _save_edge(ex, r)


@register("apoc.rel.toMap")
def rel_to_map(rel):
    if not isinstance(rel, Edge):
        return None
    return {"id": rel.id, "type": rel.type, "start": rel.start_node,
            "end": rel.end_node, "properties": dict(rel.properties)}


@_graph_fn("apoc.rel.fromMap")
def rel_from_map(ex, m):
    edge = Edge(
        id=str(m.get("id") or f"apoc-{_uuid.uuid4()}"),
        start_node=str(m["start"]), end_node=str(m["end"]),
        type=str(m.get("type", "RELATED_TO")),
        properties=dict(m.get("properties") or {}),
    )
    return ex.storage.create_edge(edge)


@_graph_fn("apoc.rel.exists")
def rel_exists(ex, rel_id):
    try:
        ex.storage.get_edge(str(rel_id))
        return True
    except NotFoundError:
        return False


@_graph_fn("apoc.rel.delete")
def rel_delete(ex, rel):
    r = _edge(ex, rel)
    ex.storage.delete_edge(r.id)
    return True


@_graph_fn("apoc.rel.clone")
def rel_clone(ex, rel):
    r = _edge(ex, rel)
    return ex.storage.create_edge(Edge(
        id=f"apoc-{_uuid.uuid4()}", start_node=r.start_node,
        end_node=r.end_node, type=r.type, properties=dict(r.properties),
    ))


@_graph_fn("apoc.rel.reverse")
def rel_reverse(ex, rel):
    """Persisted endpoint swap (ref refactor.invertRelationship semantics)."""
    r = _edge(ex, rel)
    ex.storage.delete_edge(r.id)
    return ex.storage.create_edge(Edge(
        id=r.id, start_node=r.end_node, end_node=r.start_node,
        type=r.type, properties=dict(r.properties),
    ))


@register("apoc.rel.isAnyType")
def rel_is_any_type(rel, types):
    return isinstance(rel, Edge) and rel.type in (types or [])


@register("apoc.rel.hasProperty")
def rel_has_property(rel, key):
    return isinstance(rel, Edge) and key in rel.properties


@register("apoc.rel.hasProperties")
def rel_has_properties(rel, keys):
    return isinstance(rel, Edge) and all(k in rel.properties
                                         for k in (keys or []))


@register("apoc.rel.equals")
def rel_equals(r1, r2):
    if not isinstance(r1, Edge) or not isinstance(r2, Edge):
        return False
    return (r1.type == r2.type and r1.start_node == r2.start_node
            and r1.end_node == r2.end_node and r1.properties == r2.properties)


@register("apoc.rel.compare")
def rel_compare(r1, r2):
    return {
        "sameType": isinstance(r1, Edge) and isinstance(r2, Edge)
        and r1.type == r2.type,
        "sameEndpoints": isinstance(r1, Edge) and isinstance(r2, Edge)
        and (r1.start_node, r1.end_node) == (r2.start_node, r2.end_node),
        "equal": rel_equals(r1, r2),
    }


@register("apoc.rel.weight")
def rel_weight(rel, prop="weight", default=1.0):
    if not isinstance(rel, Edge):
        return None
    v = rel.properties.get(prop, default)
    return float(v) if isinstance(v, (int, float)) else default


@register("apoc.rel.direction")
def rel_direction(rel, node):
    nid = node.id if isinstance(node, Node) else str(node)
    if not isinstance(rel, Edge):
        return None
    if rel.start_node == nid:
        return "OUT"
    if rel.end_node == nid:
        return "IN"
    return None


@_graph_fn("apoc.rel.otherNode")
def rel_other_node(ex, rel, node):
    r = _edge(ex, rel)
    nid = node.id if isinstance(node, Node) else str(node)
    other = r.end_node if r.start_node == nid else r.start_node
    return ex.get_node_or_none(other)


@register("apoc.rel.isBetween")
def rel_is_between(rel, n1, n2):
    a = n1.id if isinstance(n1, Node) else str(n1)
    b = n2.id if isinstance(n2, Node) else str(n2)
    return isinstance(rel, Edge) and {rel.start_node, rel.end_node} == {a, b}


@register("apoc.rel.isDirectedBetween")
def rel_is_directed_between(rel, from_n, to_n):
    a = from_n.id if isinstance(from_n, Node) else str(from_n)
    b = to_n.id if isinstance(to_n, Node) else str(to_n)
    return isinstance(rel, Edge) and rel.start_node == a and rel.end_node == b


# ============================================================= apoc.label
@_graph_fn("apoc.label.list")
def label_list(ex):
    labels = set()
    for n in ex.storage.all_nodes():
        labels.update(n.labels)
    return sorted(labels)


@_graph_fn("apoc.label.count")
def label_count(ex, label):
    return ex.storage.count_nodes_by_label(label)


@_graph_fn("apoc.label.nodes")
def label_nodes(ex, label):
    return sorted(ex.storage.get_nodes_by_label(label), key=lambda n: n.id)


@_graph_fn("apoc.label.add")
def label_add(ex, node, label):
    return node_add_label(ex, node, label)


@_graph_fn("apoc.label.remove")
def label_remove(ex, node, label):
    return node_remove_label(ex, node, label)


@_graph_fn("apoc.label.replace")
def label_replace(ex, node, old_labels, new_labels):
    n = _node(ex, node)
    drop = set(old_labels or [])
    n.labels = [l for l in n.labels if l not in drop]
    for lbl in new_labels or []:
        if lbl not in n.labels:
            n.labels.append(lbl)
    return _save_node(ex, n)


@register("apoc.label.has")
def label_has(node, label):
    return isinstance(node, Node) and label in node.labels


@register("apoc.label.hasAny")
def label_has_any(node, labels):
    return isinstance(node, Node) and any(l in node.labels
                                          for l in (labels or []))


@register("apoc.label.hasAll")
def label_has_all(node, labels):
    return isinstance(node, Node) and all(l in node.labels
                                          for l in (labels or []))


@register("apoc.label.get")
def label_get(node):
    return list(node.labels) if isinstance(node, Node) else None


@_graph_fn("apoc.label.set")
def label_set(ex, node, labels):
    n = _node(ex, node)
    n.labels = list(labels or [])
    return _save_node(ex, n)


@_graph_fn("apoc.label.clear")
def label_clear(ex, node):
    return label_set(ex, node, [])


@_graph_fn("apoc.label.merge")
def label_merge(ex, node, labels):
    return node_add_labels(ex, node, labels)


@register("apoc.label.diff")
def label_diff(n1, n2):
    l1 = set(n1.labels) if isinstance(n1, Node) else set()
    l2 = set(n2.labels) if isinstance(n2, Node) else set()
    return {"onlyLeft": sorted(l1 - l2), "onlyRight": sorted(l2 - l1),
            "common": sorted(l1 & l2)}


@register("apoc.label.union")
def label_union(n1, n2):
    l1 = set(n1.labels) if isinstance(n1, Node) else set()
    l2 = set(n2.labels) if isinstance(n2, Node) else set()
    return sorted(l1 | l2)


@register("apoc.label.intersection")
def label_intersection(n1, n2):
    l1 = set(n1.labels) if isinstance(n1, Node) else set()
    l2 = set(n2.labels) if isinstance(n2, Node) else set()
    return sorted(l1 & l2)


@register("apoc.label.compare")
def label_compare(n1, n2):
    d = label_diff(n1, n2)
    return {**d, "equal": not d["onlyLeft"] and not d["onlyRight"]}


@register("apoc.label.validate")
def label_validate(label):
    """Valid Cypher label: identifier-shaped (ref label.go Validate)."""
    return bool(re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", str(label or "")))


@register("apoc.label.normalize")
def label_normalize(label):
    """PascalCase normalization: 'person name' -> 'PersonName'."""
    parts = re.split(r"[\s_\-]+", str(label or "").strip())
    return "".join(p[:1].upper() + p[1:] for p in parts if p)


@register("apoc.label.toString")
def label_to_string(labels):
    return ":".join(labels or [])


@register("apoc.label.fromString")
def label_from_string(s):
    return [p for p in str(s or "").split(":") if p]


@register("apoc.label.pattern")
def label_pattern(label):
    return f"(:{label})"


@register("apoc.label.fromPattern")
def label_from_pattern(pattern):
    return re.findall(r":([A-Za-z_][A-Za-z0-9_]*)", str(pattern or ""))


@register("apoc.label.format")
def label_format(label, style="pascal"):
    s = str(label or "")
    parts = [p for p in re.split(r"[\s_\-]+|(?<=[a-z])(?=[A-Z])", s) if p]
    style = str(style).lower()
    if style in ("pascal", "label"):
        return "".join(p[:1].upper() + p[1:].lower() for p in parts)
    if style == "camel":
        out = "".join(p[:1].upper() + p[1:].lower() for p in parts)
        return out[:1].lower() + out[1:]
    if style in ("snake", "snake_case"):
        return "_".join(p.lower() for p in parts)
    if style in ("upper", "constant"):
        return "_".join(p.upper() for p in parts)
    return s


@_graph_fn("apoc.label.search")
def label_search(ex, pattern):
    return [l for l in label_list(ex) if fnmatch.fnmatch(l, str(pattern))]


@_graph_fn("apoc.label.stats")
def label_stats(ex):
    counts: dict[str, int] = {}
    for n in ex.storage.all_nodes():
        for l in n.labels:
            counts[l] = counts.get(l, 0) + 1
    return counts


# ============================================================ apoc.nodes
@_graph_fn("apoc.nodes.get")
def nodes_get(ex, ids):
    return [n for i in (ids or []) if (n := ex.get_node_or_none(str(i)))]


@_graph_fn("apoc.nodes.delete")
def nodes_delete(ex, nodes):
    count = 0
    for v in nodes or []:
        nid = v.id if isinstance(v, Node) else str(v)
        try:
            ex.storage.delete_node(nid)
            count += 1
        except NotFoundError:
            continue
    return count


@_graph_fn("apoc.nodes.link")
def nodes_link(ex, nodes, rel_type):
    """Chain nodes with rel_type in list order (ref nodes.go Link)."""
    out = []
    seq = [_node(ex, v) for v in (nodes or [])]
    for a, b in zip(seq, seq[1:]):
        out.append(ex.storage.create_edge(Edge(
            id=f"apoc-{_uuid.uuid4()}", start_node=a.id, end_node=b.id,
            type=str(rel_type), properties={},
        )))
    return out


@register("apoc.nodes.distinct")
def nodes_distinct(nodes):
    seen: dict[str, Node] = {}
    for n in nodes or []:
        if isinstance(n, Node) and n.id not in seen:
            seen[n.id] = n
    return list(seen.values())


@_graph_fn("apoc.nodes.connected")
def nodes_connected(ex, n1, n2, rel_type=None):
    return node_connected(ex, n1, n2, rel_type)


@_graph_fn("apoc.nodes.isDense")
def nodes_is_dense(ex, node, threshold=50):
    return node_is_dense(ex, node, threshold)


@_graph_fn("apoc.nodes.relationships")
def nodes_relationships(ex, node):
    return node_relationships(ex, node)


@_graph_fn("apoc.nodes.distinctRels")
def nodes_distinct_rels(ex, node):
    seen: dict[str, Edge] = {}
    for r in node_relationships(ex, node):
        seen.setdefault(r.id, r)
    return list(seen.values())


@register("apoc.nodes.intersect")
def nodes_intersect(nodes1, nodes2):
    ids2 = {n.id for n in (nodes2 or []) if isinstance(n, Node)}
    return [n for n in nodes_distinct(nodes1) if n.id in ids2]


@register("apoc.nodes.union")
def nodes_union(nodes1, nodes2):
    return nodes_distinct(list(nodes1 or []) + list(nodes2 or []))


@register("apoc.nodes.difference")
def nodes_difference(nodes1, nodes2):
    ids2 = {n.id for n in (nodes2 or []) if isinstance(n, Node)}
    return [n for n in nodes_distinct(nodes1) if n.id not in ids2]


@register("apoc.nodes.sort")
def nodes_sort(nodes, prop, descending=False):
    def key(n):
        v = n.properties.get(prop)
        return (v is None, v if isinstance(v, (int, float)) else str(v))

    return sorted([n for n in (nodes or []) if isinstance(n, Node)],
                  key=key, reverse=bool(descending))


@_graph_fn("apoc.nodes.filter")
def nodes_filter(ex, nodes, predicate):
    """predicate: Cypher expression over `n` (e.g. 'n.age > 30')."""
    return [n for n in (nodes or [])
            if _eval_pred(ex, predicate, {"n": n}) is True]


@_graph_fn("apoc.nodes.partition")
def nodes_partition(ex, nodes, predicate):
    yes, no = [], []
    for n in nodes or []:
        (yes if _eval_pred(ex, predicate, {"n": n}) is True else no).append(n)
    return [yes, no]


@_graph_fn("apoc.nodes.map")
def nodes_map(ex, nodes, expr):
    """expr: Cypher expression over `n` (e.g. 'n.name')."""
    return [_eval_pred(ex, expr, {"n": n}) for n in (nodes or [])]


@_graph_fn("apoc.nodes.reduce")
def nodes_reduce(ex, nodes, expr, init=None):
    """expr over `acc` and `n` (e.g. 'acc + n.age')."""
    acc = init
    for n in nodes or []:
        acc = _eval_pred(ex, expr, {"acc": acc, "n": n})
    return acc


@register("apoc.nodes.toMap")
def nodes_to_map(nodes):
    return {n.id: node_to_map(n) for n in (nodes or [])
            if isinstance(n, Node)}


@_graph_fn("apoc.nodes.fromMap")
def nodes_from_map(ex, m):
    return [node_from_map(ex, spec) for spec in (m or {}).values()]


@_graph_fn("apoc.nodes.batch")
def nodes_batch(ex, nodes, batch_size, expr):
    """Apply `expr` (over `batch`) to size-batches; returns per-batch
    results."""
    nodes = list(nodes or [])
    size = max(int(batch_size), 1)
    return [
        _eval_pred(ex, expr, {"batch": nodes[i:i + size]})
        for i in range(0, len(nodes), size)
    ]


@_graph_fn("apoc.nodes.collapse")
def nodes_collapse(ex, nodes):
    """Merge nodes into the first: union labels/properties, rewire rels
    (ref refactor.mergeNodes)."""
    seq = [_node(ex, v) for v in (nodes or [])]
    if not seq:
        return None
    target = seq[0]
    for other in seq[1:]:
        for lbl in other.labels:
            if lbl not in target.labels:
                target.labels.append(lbl)
        for k, v in other.properties.items():
            target.properties.setdefault(k, v)
        for r in ex.storage.get_outgoing_edges(other.id):
            ex.storage.delete_edge(r.id)
            if r.end_node != target.id:
                ex.storage.create_edge(Edge(
                    id=r.id, start_node=target.id, end_node=r.end_node,
                    type=r.type, properties=dict(r.properties)))
        for r in ex.storage.get_incoming_edges(other.id):
            try:
                ex.storage.delete_edge(r.id)
            except NotFoundError:
                continue  # self-loop already rewired above
            if r.start_node != target.id:
                ex.storage.create_edge(Edge(
                    id=r.id, start_node=r.start_node, end_node=target.id,
                    type=r.type, properties=dict(r.properties)))
        ex.storage.delete_node(other.id)
    return _save_node(ex, target)


@_graph_fn("apoc.nodes.group")
def nodes_group(ex, labels, props):
    """Group nodes carrying `labels` by the given property values; returns
    [{values, count, ids}] (ref nodes.go Group shape)."""
    props = list(props or [])
    groups: dict[tuple, dict] = {}
    for label in labels or []:
        for n in ex.storage.get_nodes_by_label(label):
            key = tuple(repr(n.properties.get(p)) for p in props)
            g = groups.setdefault(key, {
                "values": {p: n.properties.get(p) for p in props},
                "count": 0, "ids": [],
            })
            g["count"] += 1
            g["ids"].append(n.id)
    return list(groups.values())


@_graph_fn("apoc.nodes.cycles")
def nodes_cycles(ex, nodes, rel_type=None):
    """Directed cycles among the given nodes (bounded DFS)."""
    idset = {(_node(ex, v)).id for v in (nodes or [])}
    cycles = []
    for start in sorted(idset):
        stack = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            for r in ex.storage.get_outgoing_edges(cur):
                if rel_type is not None and r.type != rel_type:
                    continue
                nxt = r.end_node
                if nxt == start and len(path) > 1:
                    if min(path) == start:  # canonical: smallest id first
                        cycles.append(path)
                elif nxt in idset and nxt not in path and len(path) < 16:
                    stack.append((nxt, path + [nxt]))
    return cycles


# ========================================================= apoc.neighbors
def _hop_sets(ex, node, rel_type, max_hops):
    nid = _node(ex, node).id
    frontier = {nid}
    seen = {nid}
    levels = []
    for _ in range(int(max_hops)):
        nxt = set()
        for cur in frontier:
            for r in ex.storage.get_outgoing_edges(cur):
                if rel_type in (None, "") or r.type == rel_type:
                    nxt.add(r.end_node)
            for r in ex.storage.get_incoming_edges(cur):
                if rel_type in (None, "") or r.type == rel_type:
                    nxt.add(r.start_node)
        nxt -= seen
        seen |= nxt
        levels.append(nxt)
        frontier = nxt
        if not nxt:
            break
    return levels


@_graph_fn("apoc.neighbors.atHop")
def neighbors_at_hop(ex, node, rel_type, hop):
    levels = _hop_sets(ex, node, rel_type, int(hop))
    ids = levels[int(hop) - 1] if len(levels) >= int(hop) else set()
    return [n for i in sorted(ids) if (n := ex.get_node_or_none(i))]


@_graph_fn("apoc.neighbors.toHop")
def neighbors_to_hop(ex, node, rel_type, hop):
    ids: set = set()
    for level in _hop_sets(ex, node, rel_type, int(hop)):
        ids |= level
    return [n for i in sorted(ids) if (n := ex.get_node_or_none(i))]


@_graph_fn("apoc.neighbors.bfs")
def neighbors_bfs(ex, node, rel_type=None, max_hops=10):
    return neighbors_to_hop(ex, node, rel_type, max_hops)


@_graph_fn("apoc.neighbors.dfs")
def neighbors_dfs(ex, node, rel_type=None, max_hops=10):
    """DFS preorder of reachable neighbors (directed out + in)."""
    nid = _node(ex, node).id
    seen = {nid}
    order = []
    stack = [(nid, 0)]
    while stack:
        cur, depth = stack.pop()
        if depth >= int(max_hops):
            continue
        nbrs = set()
        for r in ex.storage.get_outgoing_edges(cur):
            if rel_type in (None, "") or r.type == rel_type:
                nbrs.add(r.end_node)
        for r in ex.storage.get_incoming_edges(cur):
            if rel_type in (None, "") or r.type == rel_type:
                nbrs.add(r.start_node)
        for nxt in sorted(nbrs, reverse=True):
            if nxt not in seen:
                seen.add(nxt)
                order.append(nxt)
                stack.append((nxt, depth + 1))
    return [n for i in order if (n := ex.get_node_or_none(i))]


@_graph_fn("apoc.neighbors.count")
def neighbors_count(ex, node, rel_type=None):
    ids = _neighbor_ids(ex, node, "both")
    if rel_type not in (None, ""):
        nid = _node(ex, node).id
        ids = set()
        for r in _rels_of(ex, node, "both"):
            if r.type == rel_type:
                ids.add(r.end_node if r.start_node == nid else r.start_node)
    return len(ids)


@_graph_fn("apoc.neighbors.exists")
def neighbors_exists(ex, node, rel_type=None):
    return neighbors_count(ex, node, rel_type) > 0


# ============================================================ apoc.atomic
# (ref apoc/atomic/atomic.go — process-wide mutex around read-modify-write)
@_graph_fn("apoc.atomic.increment")
def atomic_increment(ex, node, prop, delta=1):
    with _atomic_lock:
        n = _node(ex, node)
        cur = n.properties.get(prop, 0)
        n.properties[prop] = (cur if isinstance(cur, (int, float)) else 0) + delta
        _save_node(ex, n)
        return n.properties[prop]


@_graph_fn("apoc.atomic.decrement")
def atomic_decrement(ex, node, prop, delta=1):
    return atomic_increment(ex, node, prop, -delta)


@_graph_fn("apoc.atomic.update")
def atomic_update(ex, node, prop, value):
    with _atomic_lock:
        n = _node(ex, node)
        n.properties[prop] = value
        _save_node(ex, n)
        return value


@_graph_fn("apoc.atomic.remove")
def atomic_remove(ex, node, prop, index=None):
    """Remove a property, or one index from a list property."""
    with _atomic_lock:
        n = _node(ex, node)
        if index is None or not isinstance(n.properties.get(prop), list):
            n.properties.pop(prop, None)
            _save_node(ex, n)
            return None
        lst = list(n.properties[prop])
        i = int(index)
        if 0 <= i < len(lst):
            lst.pop(i)
        n.properties[prop] = lst
        _save_node(ex, n)
        return lst


@_graph_fn("apoc.atomic.compareAndSwap")
def atomic_cas(ex, node, prop, old, new):
    with _atomic_lock:
        n = _node(ex, node)
        if n.properties.get(prop) != old:
            return False
        n.properties[prop] = new
        _save_node(ex, n)
        return True
