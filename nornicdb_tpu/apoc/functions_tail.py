"""APOC final gap-fill: temporal / xml / spatial / convert / date / text /
meta / schema / import function forms completing the reference's registry
inventory (ref: /root/reference/apoc/apoc.go registerAllFunctions).

Temporal values use the framework's field-map convention
(cypher/temporal_fns.py: __temporal__/iso/epochMillis; durations carry
milliseconds) so results compose with the Cypher temporal accessors.
"""

from __future__ import annotations

import datetime as _dt
import json as _json
import math
import re
import xml.etree.ElementTree as _ET
from typing import Any

from nornicdb_tpu.apoc.functions_ext import _latlon, _xml_to_map
from nornicdb_tpu.apoc.functions_graph import _graph_fn
from nornicdb_tpu.apoc.registry import register
from nornicdb_tpu.errors import NornicError
from nornicdb_tpu.storage.types import Edge, Node


def _temporal():
    """Lazy: cypher/__init__ imports apoc, so this module must not import
    cypher at module load."""
    from nornicdb_tpu.cypher.temporal_fns import (
        _datetime_map,
        _parse_input,
        fn_duration,
    )

    return _datetime_map, _parse_input, fn_duration

# ========================================================== apoc.temporal


@register("apoc.temporal.parse")
def temporal_parse(value, fmt=None):
    """ISO-8601 (or java-style subset format) -> datetime map."""
    if value is None:
        return None
    if fmt and not str(fmt).lower().startswith("iso"):
        py = (str(fmt).replace("yyyy", "%Y").replace("MM", "%m")
              .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M")
              .replace("ss", "%S"))
        dt = _dt.datetime.strptime(str(value), py)
        dm, _, _ = _temporal()
        return dm(dt.replace(tzinfo=_dt.timezone.utc))
    dm, pi, _ = _temporal()
    return dm(pi(value))


@register("apoc.temporal.toEpochMillis")
def temporal_to_epoch(value):
    _, pi, _ = _temporal()
    return int(pi(value).timestamp() * 1000) if value is not None else None


@register("apoc.temporal.fromEpochMillis")
def temporal_from_epoch(ms):
    if ms is None:
        return None
    dm, _, _ = _temporal()
    return dm(_dt.datetime.fromtimestamp(int(ms) / 1000.0, _dt.timezone.utc))


@register("apoc.temporal.duration")
def temporal_duration(value):
    _, _, fd = _temporal()
    return fd(value)


@register("apoc.temporal.formatDuration")
def temporal_format_duration(duration):
    if duration is None:
        return None
    if isinstance(duration, dict) and "iso" in duration:
        return duration["iso"]
    _, _, fd = _temporal()
    return fd(duration)["iso"]


def _dur_ms(duration) -> int:
    if isinstance(duration, dict) and "milliseconds" in duration:
        return int(duration["milliseconds"])
    if isinstance(duration, (int, float)):
        return int(duration)
    _, _, fd = _temporal()
    return int(fd(duration)["milliseconds"])


@register("apoc.temporal.add")
def temporal_add(value, duration):
    dm, pi, _ = _temporal()
    dt = pi(value)
    return dm(dt + _dt.timedelta(milliseconds=_dur_ms(duration)))


@register("apoc.temporal.subtract")
def temporal_subtract(value, duration):
    dm, pi, _ = _temporal()
    dt = pi(value)
    return dm(dt - _dt.timedelta(milliseconds=_dur_ms(duration)))


@register("apoc.temporal.isBetween")
def temporal_is_between(value, start, end):
    _, pi, _ = _temporal()
    t = pi(value)
    return pi(start) <= t <= pi(end)


@register("apoc.temporal.dayOfWeek")
def temporal_day_of_week(value):
    return _temporal()[1](value).isoweekday()


@register("apoc.temporal.dayOfYear")
def temporal_day_of_year(value):
    return _temporal()[1](value).timetuple().tm_yday


@register("apoc.temporal.weekOfYear")
def temporal_week_of_year(value):
    return _temporal()[1](value).isocalendar()[1]


@register("apoc.temporal.timezone")
def temporal_timezone(value):
    if isinstance(value, dict) and "timezone" in value:
        return value["timezone"]
    return str(_temporal()[1](value).tzinfo or "UTC")


@register("apoc.temporal.toUTC")
def temporal_to_utc(value):
    dm, pi, _ = _temporal()
    return dm(pi(value).astimezone(_dt.timezone.utc))


@register("apoc.temporal.toLocal")
def temporal_to_local(value, offset_minutes=0):
    dm, pi, _ = _temporal()
    tz = _dt.timezone(_dt.timedelta(minutes=int(offset_minutes)))
    return dm(pi(value).astimezone(tz))


_TRUNC_UNITS = ("year", "month", "day", "hour", "minute", "second")


@register("apoc.temporal.truncate")
def temporal_truncate(value, unit="day"):
    dm, pi, _ = _temporal()
    dt = pi(value)
    u = str(unit).lower()
    if u not in _TRUNC_UNITS and u != "week":
        raise NornicError(f"unknown truncation unit {unit!r}")
    if u == "week":
        start = dt - _dt.timedelta(days=dt.isoweekday() - 1)
        return temporal_truncate(start, "day")
    repl = {}
    for candidate, zero in (("month", 1), ("day", 1), ("hour", 0),
                            ("minute", 0), ("second", 0)):
        if _TRUNC_UNITS.index(u) < _TRUNC_UNITS.index(candidate):
            repl[candidate] = zero
    return dm(dt.replace(microsecond=0, **repl))


@register("apoc.temporal.round")
def temporal_round(value, unit="hour"):
    dm, pi, _ = _temporal()
    dt = pi(value)
    u = str(unit).lower()
    step = {"second": 1, "minute": 60, "hour": 3600, "day": 86400}.get(u)
    if step is None:
        raise NornicError(f"unknown rounding unit {unit!r}")
    ts = dt.timestamp()
    return dm(_dt.datetime.fromtimestamp(
        round(ts / step) * step, _dt.timezone.utc))


# =============================================================== apoc.xml
def _xml_from_value(v) -> _ET.Element:
    """Accept a map form ({_type, attrs, _text, _children}) or an XML
    string."""
    if isinstance(v, str):
        return _ET.fromstring(v)
    if isinstance(v, dict):
        el = _ET.Element(str(v.get("_type", "node")))
        for k, val in v.items():
            if k in ("_type", "_text", "_children"):
                continue
            el.set(k, str(val))
        if v.get("_text"):
            el.text = str(v["_text"])
        for child in v.get("_children", []):
            el.append(_xml_from_value(child))
        return el
    raise NornicError("expected an XML string or map")


@register("apoc.xml.toMap")
def xml_to_map(doc):
    return _xml_to_map(_xml_from_value(doc))


@register("apoc.xml.fromMap")
@register("apoc.xml.toString")
def xml_to_string(doc):
    return _ET.tostring(_xml_from_value(doc), encoding="unicode")


@register("apoc.xml.create")
def xml_create(name, attrs=None, text=None):
    out: dict = {"_type": str(name)}
    out.update({k: v for k, v in (attrs or {}).items()})
    if text is not None:
        out["_text"] = str(text)
    return out


@register("apoc.xml.clone")
def xml_clone(node):
    return _json.loads(_json.dumps(xml_to_map(node)))


@register("apoc.xml.setAttribute")
def xml_set_attribute(node, attr, value):
    out = xml_clone(node)
    out[str(attr)] = value
    return out


@register("apoc.xml.setText")
def xml_set_text(node, text):
    out = xml_clone(node)
    out["_text"] = str(text)
    return out


@register("apoc.xml.addChild")
def xml_add_child(parent, child):
    out = xml_clone(parent)
    out.setdefault("_children", []).append(xml_to_map(child))
    return out


@register("apoc.xml.removeChild")
def xml_remove_child(parent, child_type):
    out = xml_clone(parent)
    out["_children"] = [c for c in out.get("_children", [])
                        if c.get("_type") != str(child_type)]
    return out


@register("apoc.xml.query")
def xml_query(doc, path):
    """ElementTree XPath subset query -> list of matched maps."""
    el = _xml_from_value(doc)
    return [_xml_to_map(m) for m in el.findall(str(path))]


@register("apoc.xml.namespace")
@register("apoc.xml.getNamespace")
def xml_namespace(node):
    tag = str((node or {}).get("_type") if isinstance(node, dict)
              else _xml_from_value(node).tag)
    m = re.match(r"\{([^}]+)\}", tag)
    return m.group(1) if m else None


@register("apoc.xml.prettify")
def xml_prettify(doc):
    el = _xml_from_value(doc)
    _ET.indent(el)
    return _ET.tostring(el, encoding="unicode")


@register("apoc.xml.minify")
def xml_minify(doc):
    s = xml_to_string(doc) if not isinstance(doc, str) else doc
    return re.sub(r">\s+<", "><", str(s).strip())


@register("apoc.xml.fromJson")
def xml_from_json(j):
    """JSON object -> XML map (keys become child elements)."""
    obj = _json.loads(j) if isinstance(j, str) else j

    def build(name, v):
        if isinstance(v, dict):
            return {"_type": str(name),
                    "_children": [build(k, c) for k, c in v.items()]}
        if isinstance(v, list):
            return {"_type": str(name),
                    "_children": [build("item", c) for c in v]}
        return {"_type": str(name), "_text": "" if v is None else str(v)}

    return build("root", obj)


@register("apoc.xml.transform")
def xml_transform(doc, mapping):
    """Rename element types via {'old': 'new'} (lightweight stand-in for
    the reference's XSLT placeholder, xml.go Transform)."""
    m = mapping or {}

    def walk(node):
        out = dict(node)
        out["_type"] = m.get(out.get("_type"), out.get("_type"))
        if "_children" in out:
            out["_children"] = [walk(c) for c in out["_children"]]
        return out

    return walk(xml_to_map(doc))


# =========================================================== apoc.spatial
@register("apoc.spatial.haversineDistance")
def spatial_haversine(lat1, lon1, lat2, lon2):
    from nornicdb_tpu.apoc.functions_ext import _EARTH_R_M

    p1, l1 = math.radians(float(lat1)), math.radians(float(lon1))
    p2, l2 = math.radians(float(lat2)), math.radians(float(lon2))
    a = (math.sin((p2 - p1) / 2) ** 2
         + math.cos(p1) * math.cos(p2) * math.sin((l2 - l1) / 2) ** 2)
    return 2 * _EARTH_R_M * math.asin(math.sqrt(a))


@register("apoc.spatial.vincentyDistance")
def spatial_vincenty(lat1, lon1, lat2, lon2):
    """Vincenty inverse on the WGS-84 ellipsoid (meters)."""
    a, f = 6378137.0, 1 / 298.257223563
    b = (1 - f) * a
    L = math.radians(float(lon2) - float(lon1))
    u1 = math.atan((1 - f) * math.tan(math.radians(float(lat1))))
    u2 = math.atan((1 - f) * math.tan(math.radians(float(lat2))))
    su1, cu1 = math.sin(u1), math.cos(u1)
    su2, cu2 = math.sin(u2), math.cos(u2)
    lam = L
    for _ in range(100):
        sl, cl = math.sin(lam), math.cos(lam)
        ss = math.sqrt((cu2 * sl) ** 2 + (cu1 * su2 - su1 * cu2 * cl) ** 2)
        if ss == 0:
            return 0.0
        cs = su1 * su2 + cu1 * cu2 * cl
        sig = math.atan2(ss, cs)
        sa = cu1 * cu2 * sl / ss
        c2a = 1 - sa ** 2
        c2m = cs - 2 * su1 * su2 / c2a if c2a else 0.0
        C = f / 16 * c2a * (4 + f * (4 - 3 * c2a))
        lam_prev = lam
        lam = L + (1 - C) * f * sa * (
            sig + C * ss * (c2m + C * cs * (-1 + 2 * c2m ** 2)))
        if abs(lam - lam_prev) < 1e-12:
            break
    u2_ = c2a * (a ** 2 - b ** 2) / (b ** 2)
    A = 1 + u2_ / 16384 * (4096 + u2_ * (-768 + u2_ * (320 - 175 * u2_)))
    B = u2_ / 1024 * (256 + u2_ * (-128 + u2_ * (74 - 47 * u2_)))
    dsig = B * ss * (c2m + B / 4 * (cs * (-1 + 2 * c2m ** 2)
                                    - B / 6 * c2m * (-3 + 4 * ss ** 2)
                                    * (-3 + 4 * c2m ** 2)))
    return b * A * (sig - dsig)


@register("apoc.spatial.area")
def spatial_area(polygon):
    """Spherical excess area of a lat/lon polygon (m^2, shoelace on the
    equirectangular projection — adequate for small polygons)."""
    from nornicdb_tpu.apoc.functions_ext import _EARTH_R_M

    pts = [_latlon(p) for p in (polygon or [])]
    if len(pts) < 3:
        return 0.0
    lat0 = sum(p[0] for p in pts) / len(pts)
    scale = math.cos(math.radians(lat0))
    xy = [(math.radians(lon) * scale * _EARTH_R_M,
           math.radians(lat) * _EARTH_R_M) for lat, lon in pts]
    s = 0.0
    for (x1, y1), (x2, y2) in zip(xy, xy[1:] + xy[:1]):
        s += x1 * y2 - x2 * y1
    return abs(s) / 2.0


@register("apoc.spatial.nearest")
def spatial_nearest(point, points):
    lat, lon = _latlon(point)
    best, best_d = None, None
    for p in points or []:
        la, lo = _latlon(p)
        d = spatial_haversine(lat, lon, la, lo)
        if best_d is None or d < best_d:
            best, best_d = p, d
    return best


@register("apoc.spatial.kNearest")
def spatial_k_nearest(point, points, k):
    lat, lon = _latlon(point)
    scored = sorted(
        (points or []),
        key=lambda p: spatial_haversine(lat, lon, *_latlon(p)),
    )
    return scored[: int(k)]


def _bbox(geom):
    pts = [_latlon(p) for p in (geom if isinstance(geom, list) else [geom])]
    lats = [p[0] for p in pts]
    lons = [p[1] for p in pts]
    return min(lats), min(lons), max(lats), max(lons)


@register("apoc.spatial.intersects")
def spatial_intersects(g1, g2):
    """Bounding-box intersection of two point sets."""
    a = _bbox(g1)
    b = _bbox(g2)
    return not (a[2] < b[0] or b[2] < a[0] or a[3] < b[1] or b[3] < a[1])


@register("apoc.spatial.contains")
def spatial_contains(g1, g2):
    """Bounding box of g1 contains every point of g2."""
    a = _bbox(g1)
    b = _bbox(g2)
    return a[0] <= b[0] and a[1] <= b[1] and a[2] >= b[2] and a[3] >= b[3]


@register("apoc.spatial.toGeoJSON")
def spatial_to_geojson(geom):
    if isinstance(geom, list):
        return {"type": "Polygon", "coordinates": [[
            [_latlon(p)[1], _latlon(p)[0]] for p in geom]]}
    lat, lon = _latlon(geom)
    return {"type": "Point", "coordinates": [lon, lat]}


@register("apoc.spatial.fromGeoJSON")
def spatial_from_geojson(gj):
    g = _json.loads(gj) if isinstance(gj, str) else (gj or {})
    t = g.get("type")
    if t == "Point":
        lon, lat = g["coordinates"][:2]
        return {"latitude": lat, "longitude": lon}
    if t == "Polygon":
        return [{"latitude": lat, "longitude": lon}
                for lon, lat in g["coordinates"][0]]
    raise NornicError(f"unsupported GeoJSON type {t!r}")


# =========================================================== apoc.convert
@register("apoc.convert.toNode")
def convert_to_node(m, labels=None):
    if isinstance(m, Node):
        return m
    if not isinstance(m, dict):
        return None
    props = dict(m.get("properties") or
                 {k: v for k, v in m.items()
                  if k not in ("id", "labels")})
    return Node(id=str(m.get("id", "")), labels=list(labels or m.get("labels") or []),
                properties=props)


@register("apoc.convert.fromJsonNode")
def convert_from_json_node(j):
    return convert_to_node(_json.loads(j) if isinstance(j, str) else j)


@register("apoc.convert.toNodeList")
def convert_to_node_list(maps):
    return [convert_to_node(m) for m in (maps or [])]


@register("apoc.convert.toRelationship")
def convert_to_relationship(m, rel_type=None):
    if isinstance(m, Edge):
        return m
    if not isinstance(m, dict):
        return None
    return Edge(
        id=str(m.get("id", "")), start_node=str(m.get("start", "")),
        end_node=str(m.get("end", "")),
        type=str(rel_type or m.get("type", "RELATED_TO")),
        properties=dict(m.get("properties") or {}),
    )


@register("apoc.convert.toRelationshipList")
def convert_to_relationship_list(maps):
    return [convert_to_relationship(m) for m in (maps or [])]


@register("apoc.convert.getJsonPropertyMap")
def convert_get_json_property_map(entity, key):
    """Parse a JSON-string property into a map."""
    props = entity.properties if isinstance(entity, (Node, Edge)) \
        else (entity or {})
    v = props.get(key)
    if v is None:
        return None
    return _json.loads(v) if isinstance(v, str) else v


@register("apoc.convert.toTree")
def convert_to_tree(paths):
    """Paths ([{nodes, relationships}] or node-id lists) -> nested tree
    keyed by parent (ref convert.go ToTree shape: children under
    lowercased rel type)."""
    roots: dict[str, dict] = {}
    index: dict[str, dict] = {}

    def entry(n):
        if isinstance(n, Node):
            nid = n.id
            data = {"_id": nid, "_labels": list(n.labels), **n.properties}
        else:
            nid = str(n)
            data = {"_id": nid}
        if nid not in index:
            index[nid] = data
        return index[nid]

    for p in paths or []:
        nodes = p.get("nodes", []) if isinstance(p, dict) else list(p)
        rels = p.get("relationships", []) if isinstance(p, dict) else []
        if not nodes:
            continue
        root = entry(nodes[0])
        roots[root["_id"]] = root
        for i in range(1, len(nodes)):
            parent = entry(nodes[i - 1])
            child = entry(nodes[i])
            key = (rels[i - 1].type.lower()
                   if i - 1 < len(rels) and isinstance(rels[i - 1], Edge)
                   else "children")
            bucket = parent.setdefault(key, [])
            if child not in bucket:
                bucket.append(child)
            roots.pop(child["_id"], None)
    return list(roots.values())


# =============================================================== apoc.date
@register("apoc.date.convertFormat")
def date_convert_format(text, from_fmt, to_fmt):
    def py(fmt):
        return (str(fmt).replace("yyyy", "%Y").replace("MM", "%m")
                .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M")
                .replace("ss", "%S"))

    dt = _dt.datetime.strptime(str(text), py(from_fmt))
    return dt.strftime(py(to_fmt))


@register("apoc.date.toYears")
def date_to_years(ts):
    """Epoch millis -> fractional years since 1970."""
    return float(ts) / (365.2425 * 86400 * 1000)


@register("apoc.date.systemTimezone")
def date_system_timezone():
    return "UTC"  # the engine normalizes all temporals to UTC


@register("apoc.date.parseAsZonedDateTime")
def date_parse_zoned(text, fmt=None):
    return temporal_parse(text, fmt)


# =============================================================== apoc.text
@register("apoc.text.doubleMetaphone")
def text_double_metaphone(s):
    """Primary Double Metaphone code (simplified clean-room variant
    covering the common English rules; 'Smith' -> 'SM0')."""
    if not s:
        return ""
    w = re.sub(r"[^A-Z]", "", str(s).upper())
    if not w:
        return ""
    out = []
    i = 0
    n = len(w)
    vowels = "AEIOUY"
    if w[:2] in ("GN", "KN", "PN", "WR", "PS"):
        i = 1
    if w[0] == "X":
        out.append("S")
        i = max(i, 1)
    while i < n and len(out) < 4:
        c = w[i]
        nxt = w[i + 1] if i + 1 < n else ""
        prev = w[i - 1] if i > 0 else ""
        if c in vowels:
            if i == 0:
                out.append("A")
            i += 1
            continue
        if c == "B":
            out.append("P")
            i += 2 if nxt == "B" else 1
        elif c == "C":
            if nxt == "H":
                out.append("X")
                i += 2
            elif nxt in "IEY":
                out.append("S")
                i += 1
            else:
                out.append("K")
                i += 2 if nxt in "CKQ" else 1
        elif c == "D":
            if nxt == "G" and i + 2 < n and w[i + 2] in "IEY":
                out.append("J")
                i += 3
            else:
                out.append("T")
                i += 2 if nxt in "DT" else 1
        elif c == "F":
            out.append("F")
            i += 2 if nxt == "F" else 1
        elif c == "G":
            if nxt == "H":
                if i > 0 and prev not in vowels:
                    out.append("K")
                i += 2
            elif nxt == "N":
                out.append("KN" if i == 0 else "N")
                i += 2
            elif nxt in "IEY":
                out.append("J")
                i += 1
            else:
                out.append("K")
                i += 2 if nxt == "G" else 1
        elif c == "H":
            if prev in vowels and nxt not in vowels:
                i += 1
            else:
                out.append("H")
                i += 1
        elif c == "J":
            out.append("J")
            i += 1
        elif c in "KQ":
            out.append("K")
            i += 2 if nxt in "KQ" else 1
        elif c == "L":
            out.append("L")
            i += 2 if nxt == "L" else 1
        elif c == "M":
            out.append("M")
            i += 2 if nxt == "M" else 1
        elif c == "N":
            out.append("N")
            i += 2 if nxt == "N" else 1
        elif c == "P":
            if nxt == "H":
                out.append("F")
                i += 2
            else:
                out.append("P")
                i += 2 if nxt == "P" else 1
        elif c == "R":
            out.append("R")
            i += 2 if nxt == "R" else 1
        elif c == "S":
            if nxt == "H":
                out.append("X")
                i += 2
            elif w[i:i + 3] in ("SIO", "SIA"):
                out.append("X")
                i += 1
            else:
                out.append("S")
                i += 2 if nxt == "S" else 1
        elif c == "T":
            if nxt == "H":
                out.append("0")
                i += 2
            elif w[i:i + 3] in ("TIO", "TIA"):
                out.append("X")
                i += 1
            else:
                out.append("T")
                i += 2 if nxt == "T" else 1
        elif c == "V":
            out.append("F")
            i += 1
        elif c == "W":
            if nxt in vowels:
                out.append("W")
            i += 1
        elif c == "X":
            out.append("KS")
            i += 1
        elif c == "Z":
            out.append("S")
            i += 1
        else:
            i += 1
    return "".join(out)[:4]


# ============================================ meta/schema/import fn forms
@_graph_fn("apoc.meta.data")
def meta_data_fn(ex):
    """Tabular label/property/type rows (function form of the
    apoc.meta.data procedure)."""
    rows = []
    seen: dict = {}
    for n in ex.storage.all_nodes():
        for label in n.labels:
            for k, v in n.properties.items():
                from nornicdb_tpu.apoc.functions_graph2 import _cypher_type

                key = (label, k)
                if key not in seen:
                    seen[key] = _cypher_type(v)
                    rows.append({"label": label, "property": k,
                                 "type": seen[key]})
    return rows


@_graph_fn("apoc.meta.schema")
def meta_schema_fn(ex):
    out: dict = {}
    for n in ex.storage.all_nodes():
        for label in n.labels:
            entry = out.setdefault(
                label, {"type": "node", "count": 0, "properties": {}})
            entry["count"] += 1
            for k, v in n.properties.items():
                from nornicdb_tpu.apoc.functions_graph2 import _cypher_type

                entry["properties"].setdefault(k, {"type": _cypher_type(v)})
    return out


@_graph_fn("apoc.meta.nodeTypeProperties")
def meta_node_type_properties_fn(ex):
    rows = []
    seen: set = set()
    for n in ex.storage.all_nodes():
        for label in n.labels:
            for k, v in n.properties.items():
                from nornicdb_tpu.apoc.functions_graph2 import _cypher_type

                key = (label, k, _cypher_type(v))
                if key not in seen:
                    seen.add(key)
                    rows.append({"nodeType": f":`{label}`",
                                 "propertyName": k,
                                 "propertyTypes": [key[2]]})
    return rows


@_graph_fn("apoc.meta.relTypeProperties")
def meta_rel_type_properties_fn(ex):
    rows = []
    seen: set = set()
    for e in ex.storage.all_edges():
        for k, v in e.properties.items():
            from nornicdb_tpu.apoc.functions_graph2 import _cypher_type

            key = (e.type, k, _cypher_type(v))
            if key not in seen:
                seen.add(key)
                rows.append({"relType": f":`{e.type}`", "propertyName": k,
                             "propertyTypes": [key[2]]})
    return rows


@_graph_fn("apoc.schema.nodes")
def schema_nodes_fn(ex):
    out = []
    for i in ex.schema.list_indexes():
        out.append({"name": i.name, "label": i.label,
                    "properties": list(i.properties), "status": "ONLINE",
                    "type": i.kind})
    return out


@_graph_fn("apoc.schema.relationships")
def schema_relationships_fn(ex):
    return []  # relationship indexes are not part of the schema manager


@_graph_fn("apoc.import.json")
def import_json_fn(ex, path):
    from nornicdb_tpu.apoc.export_import import import_json

    return import_json(ex, [str(path)], {})


@_graph_fn("apoc.import.csv")
def import_csv_fn(ex, path):
    from nornicdb_tpu.apoc.export_import import import_csv

    return import_csv(ex, [str(path)], {})


@_graph_fn("apoc.import.graphML")
def import_graphml_fn(ex, path):
    from nornicdb_tpu.apoc.export_import import import_graphml

    return import_graphml(ex, [str(path)], {})
