"""APOC graph-access categories, part 2: meta / schema / search / create /
merge / graph / cypher / community / algo / paths / path.

Behavioral reference: /root/reference/apoc/apoc.go registerAllFunctions +
per-category dirs. community/algo delegate to the TPU segment-reduce
implementations in ops/graph_algos.py (same kernels as gds.*); the
reference's own exotic variants alias the basic ones the same way
(community.go:810 InfoMap -> LabelPropagation, :1063 WalkTrap -> FastGreedy).
Community results use {nodeId: communityId} maps; path results are node-id
lists — the value-level twins of the procedure forms.
"""

from __future__ import annotations

import json as _json
import re
import uuid as _uuid
from typing import Any, Optional

import numpy as np

from nornicdb_tpu.apoc.functions_graph import (
    _edge,
    _eval_pred,
    _graph_fn,
    _node,
    node_to_map,
    rel_to_map,
)
from nornicdb_tpu.apoc.registry import register
from nornicdb_tpu.errors import NornicError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Node

# ============================================================== apoc.meta


def _cypher_type(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "FLOAT"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "LIST"
    if isinstance(v, Node):
        return "NODE"
    if isinstance(v, Edge):
        return "RELATIONSHIP"
    if isinstance(v, dict):
        if {"nodes", "relationships"} <= set(v.keys()):
            return "PATH"
        return "MAP"
    return type(v).__name__.upper()


@register("apoc.meta.typeOf")
@register("apoc.meta.cypherType")
def meta_type_of(v):
    return _cypher_type(v)


@register("apoc.meta.types")
@register("apoc.meta.cypherTypes")
def meta_types(m):
    return {k: _cypher_type(v) for k, v in (m or {}).items()}


@register("apoc.meta.isNode")
def meta_is_node(v):
    return isinstance(v, Node)


@register("apoc.meta.isRelationship")
def meta_is_relationship(v):
    return isinstance(v, Edge)


@register("apoc.meta.isPath")
def meta_is_path(v):
    return isinstance(v, dict) and {"nodes", "relationships"} <= set(v.keys())


@_graph_fn("apoc.meta.nodeLabels")
def meta_node_labels(ex):
    labels: set = set()
    for n in ex.storage.all_nodes():
        labels.update(n.labels)
    return sorted(labels)


@_graph_fn("apoc.meta.relTypes")
def meta_rel_types(ex):
    return sorted({e.type for e in ex.storage.all_edges()})


@_graph_fn("apoc.meta.propertyKeys")
def meta_property_keys(ex):
    keys: set = set()
    for n in ex.storage.all_nodes():
        keys.update(n.properties.keys())
    for e in ex.storage.all_edges():
        keys.update(e.properties.keys())
    return sorted(keys)


@_graph_fn("apoc.meta.stats")
def meta_stats(ex):
    label_counts: dict[str, int] = {}
    for n in ex.storage.all_nodes():
        for l in n.labels:
            label_counts[l] = label_counts.get(l, 0) + 1
    type_counts: dict[str, int] = {}
    for e in ex.storage.all_edges():
        type_counts[e.type] = type_counts.get(e.type, 0) + 1
    return {
        "nodeCount": ex.storage.node_count(),
        "relCount": ex.storage.edge_count(),
        "labels": label_counts,
        "relTypes": type_counts,
        "labelCount": len(label_counts),
        "relTypeCount": len(type_counts),
    }


@_graph_fn("apoc.meta.graph")
def meta_graph(ex):
    """Label-level meta graph: nodes = labels, rels = observed
    (label)-[type]->(label) triples (ref meta.go Graph)."""
    rels: set = set()
    for e in ex.storage.all_edges():
        s = ex.get_node_or_none(e.start_node)
        t = ex.get_node_or_none(e.end_node)
        for sl in (s.labels if s else ["?"]):
            for tl in (t.labels if t else ["?"]):
                rels.add((sl, e.type, tl))
    return {
        "nodes": meta_node_labels(ex),
        "relationships": [
            {"start": s, "type": t, "end": d} for s, t, d in sorted(rels)
        ],
    }


@_graph_fn("apoc.meta.graphSample")
def meta_graph_sample(ex, sample=100):
    """Meta graph from the first `sample` edges."""
    rels: set = set()
    for i, e in enumerate(ex.storage.all_edges()):
        if i >= int(sample):
            break
        s = ex.get_node_or_none(e.start_node)
        t = ex.get_node_or_none(e.end_node)
        for sl in (s.labels if s else ["?"]):
            for tl in (t.labels if t else ["?"]):
                rels.add((sl, e.type, tl))
    return {"relationships": [
        {"start": s, "type": t, "end": d} for s, t, d in sorted(rels)]}


@_graph_fn("apoc.meta.subGraph")
def meta_subgraph(ex, config=None):
    """Meta graph restricted to config {labels: [...], rels: [...]}."""
    cfg = config or {}
    want_labels = set(cfg.get("labels") or [])
    want_types = set(cfg.get("rels") or cfg.get("relTypes") or [])
    full = meta_graph(ex)
    rels = [
        r for r in full["relationships"]
        if (not want_types or r["type"] in want_types)
        and (not want_labels
             or (r["start"] in want_labels and r["end"] in want_labels))
    ]
    nodes = sorted({r["start"] for r in rels} | {r["end"] for r in rels}
                   | (want_labels & set(full["nodes"])))
    return {"nodes": nodes, "relationships": rels}


@_graph_fn("apoc.meta.cardinality")
def meta_cardinality(ex, label):
    return ex.storage.count_nodes_by_label(label)


@_graph_fn("apoc.meta.constraints")
def meta_constraints(ex):
    return [
        {"name": c.name, "label": c.label, "properties": list(c.properties),
         "kind": c.kind}
        for c in ex.schema.list_constraints()
    ]


@_graph_fn("apoc.meta.indexes")
def meta_indexes(ex):
    return [
        {"name": i.name, "kind": i.kind, "label": i.label,
         "properties": list(i.properties)}
        for i in ex.schema.list_indexes()
    ]


@_graph_fn("apoc.meta.functions")
def meta_functions(ex):
    from nornicdb_tpu.apoc.registry import all_functions

    return all_functions()


@_graph_fn("apoc.meta.procedures")
def meta_procedures(ex):
    from nornicdb_tpu.cypher.executor import PROCEDURES

    return sorted(PROCEDURES)


@register("apoc.meta.version")
def meta_version():
    import nornicdb_tpu

    return getattr(nornicdb_tpu, "__version__", "0.2.0")


@register("apoc.meta.config")
def meta_config():
    from nornicdb_tpu.apoc.registry import categories

    return {"categories": categories()}


@_graph_fn("apoc.meta.export")
@_graph_fn("apoc.meta.snapshot")
def meta_export(ex):
    """Schema snapshot: labels/types/keys + declared indexes/constraints."""
    return {
        "labels": meta_node_labels(ex),
        "relTypes": meta_rel_types(ex),
        "propertyKeys": meta_property_keys(ex),
        "indexes": meta_indexes(ex),
        "constraints": meta_constraints(ex),
    }


@_graph_fn("apoc.meta.import")
@_graph_fn("apoc.meta.restore")
def meta_import(ex, snapshot):
    """Recreate declared indexes/constraints from a meta.export snapshot."""
    created = {"indexes": 0, "constraints": 0}
    for i in (snapshot or {}).get("indexes", []):
        ex.schema.create_index(
            i["name"], i.get("kind", "property"), i["label"],
            list(i["properties"]), if_not_exists=True,
        )
        created["indexes"] += 1
    for c in (snapshot or {}).get("constraints", []):
        ex.schema.create_constraint(
            c["name"], c["label"], list(c["properties"]),
            kind=c.get("kind", "unique"), if_not_exists=True,
        )
        created["constraints"] += 1
    return created


@register("apoc.meta.compare")
@register("apoc.meta.diff")
def meta_compare(s1, s2):
    out = {}
    for key in ("labels", "relTypes", "propertyKeys"):
        a = set((s1 or {}).get(key) or [])
        b = set((s2 or {}).get(key) or [])
        out[key] = {"onlyLeft": sorted(a - b), "onlyRight": sorted(b - a)}
    return out


@register("apoc.meta.validate")
def meta_validate(schema):
    return isinstance(schema, dict) and all(
        isinstance(schema.get(k, []), list)
        for k in ("labels", "relTypes", "propertyKeys")
    )


@_graph_fn("apoc.meta.analyze")
def meta_analyze(ex):
    stats = meta_stats(ex)
    n = stats["nodeCount"]
    return {
        **stats,
        "avgDegree": (2.0 * stats["relCount"] / n) if n else 0.0,
        "propertyKeyCount": len(meta_property_keys(ex)),
    }


@_graph_fn("apoc.meta.pattern")
def meta_pattern(ex):
    g = meta_graph(ex)
    return [f"(:{r['start']})-[:{r['type']}]->(:{r['end']})"
            for r in g["relationships"]]


@_graph_fn("apoc.meta.toString")
def meta_to_string(ex):
    return _json.dumps(meta_export(ex), sort_keys=True)


@register("apoc.meta.fromString")
def meta_from_string(s):
    return _json.loads(s)


# ============================================================ apoc.schema
@_graph_fn("apoc.schema.labels")
def schema_labels(ex):
    return meta_node_labels(ex)


@_graph_fn("apoc.schema.types")
def schema_types(ex):
    return meta_rel_types(ex)


@_graph_fn("apoc.schema.nodeConstraints")
def schema_node_constraints(ex):
    return meta_constraints(ex)


@_graph_fn("apoc.schema.nodeIndexes")
def schema_node_indexes(ex):
    return meta_indexes(ex)


@_graph_fn("apoc.schema.relationshipConstraints")
def schema_rel_constraints(ex):
    return []  # relationship constraints are not part of the schema manager


@_graph_fn("apoc.schema.relationshipIndexes")
def schema_rel_indexes(ex):
    return []


@_graph_fn("apoc.schema.info")
def schema_info(ex):
    return {"indexes": meta_indexes(ex), "constraints": meta_constraints(ex)}


def _index_name(label, props):
    return f"idx_{label}_{'_'.join(props)}"


@_graph_fn("apoc.schema.createIndex")
def schema_create_index(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    idx = ex.schema.create_index(
        _index_name(label, props),
        "composite" if len(props) > 1 else "property",
        label, props, if_not_exists=True,
    )
    return {"name": idx.name, "label": idx.label,
            "properties": list(idx.properties)}


@_graph_fn("apoc.schema.dropIndex")
def schema_drop_index(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    ex.schema.drop_index(_index_name(label, props), if_exists=True)
    return True


@_graph_fn("apoc.schema.createConstraint")
@_graph_fn("apoc.schema.createUniqueConstraint")
def schema_create_constraint(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    c = ex.schema.create_constraint(
        f"constraint_{label}_{'_'.join(props)}", label, props,
        if_not_exists=True,
    )
    return {"name": c.name, "label": c.label, "properties": list(c.properties),
            "kind": c.kind}


@_graph_fn("apoc.schema.createExistsConstraint")
def schema_create_exists_constraint(ex, label, prop):
    c = ex.schema.create_constraint(
        f"exists_{label}_{prop}", label, [prop], kind="exists",
        if_not_exists=True,
    )
    return {"name": c.name, "label": c.label, "kind": c.kind}


@_graph_fn("apoc.schema.createNodeKeyConstraint")
def schema_create_node_key(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    c = ex.schema.create_constraint(
        f"nodekey_{label}_{'_'.join(props)}", label, props, kind="node_key",
        if_not_exists=True,
    )
    return {"name": c.name, "label": c.label, "kind": c.kind}


@_graph_fn("apoc.schema.dropConstraint")
def schema_drop_constraint(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    for prefix in ("constraint", "nodekey"):
        ex.schema.drop_constraint(
            f"{prefix}_{label}_{'_'.join(props)}", if_exists=True)
    if len(props) == 1:
        ex.schema.drop_constraint(f"exists_{label}_{props[0]}", if_exists=True)
    return True


@_graph_fn("apoc.schema.nodeConstraintExists")
def schema_constraint_exists(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    return any(
        c.label == label and list(c.properties) == props
        for c in ex.schema.list_constraints()
    )


@_graph_fn("apoc.schema.nodeIndexExists")
def schema_index_exists(ex, label, properties):
    props = [properties] if isinstance(properties, str) else list(properties)
    return ex.schema.find_index(label, props) is not None


@_graph_fn("apoc.schema.properties")
def schema_properties(ex, label):
    keys: set = set()
    for n in ex.storage.get_nodes_by_label(label):
        keys.update(n.properties.keys())
    return sorted(keys)


@_graph_fn("apoc.schema.propertiesDistinct")
def schema_properties_distinct(ex, label, prop):
    vals = []
    seen = set()
    for n in ex.storage.get_nodes_by_label(label):
        v = n.properties.get(prop)
        k = repr(v)
        if v is not None and k not in seen:
            seen.add(k)
            vals.append(v)
    try:
        return sorted(vals)
    except TypeError:
        return sorted(vals, key=repr)


@_graph_fn("apoc.schema.export")
@_graph_fn("apoc.schema.snapshot")
def schema_export(ex):
    return schema_info(ex)


@_graph_fn("apoc.schema.import")
@_graph_fn("apoc.schema.restore")
def schema_import(ex, snapshot):
    return meta_import(ex, snapshot)


@register("apoc.schema.compare")
def schema_compare(s1, s2):
    def names(s, key):
        return {i.get("name") for i in (s or {}).get(key, [])}

    return {
        key: {"onlyLeft": sorted(names(s1, key) - names(s2, key)),
              "onlyRight": sorted(names(s2, key) - names(s1, key))}
        for key in ("indexes", "constraints")
    }


@_graph_fn("apoc.schema.validate")
def schema_validate(ex):
    """Checks every unique constraint actually holds (ref schema.go
    Validate); returns violations."""
    violations = []
    for c in ex.schema.list_constraints():
        if c.kind not in ("unique", "node_key"):
            continue
        seen: dict = {}
        for n in ex.storage.get_nodes_by_label(c.label):
            key = tuple(repr(n.properties.get(p)) for p in c.properties)
            if all(n.properties.get(p) is not None for p in c.properties):
                if key in seen:
                    violations.append({
                        "constraint": c.name, "nodes": [seen[key], n.id]})
                seen[key] = n.id
    return {"valid": not violations, "violations": violations}


@_graph_fn("apoc.schema.stats")
def schema_stats(ex):
    return {
        "indexCount": len(ex.schema.list_indexes()),
        "constraintCount": len(ex.schema.list_constraints()),
    }


@_graph_fn("apoc.schema.analyze")
def schema_analyze(ex):
    """Suggest indexes for labels with many nodes but none declared."""
    suggestions = []
    for label in meta_node_labels(ex):
        count = ex.storage.count_nodes_by_label(label)
        has = any(i.label == label for i in ex.schema.list_indexes())
        if count >= 100 and not has:
            suggestions.append({"label": label, "count": count,
                                "suggestion": "add an index"})
    return {"suggestions": suggestions, **schema_stats(ex)}


@_graph_fn("apoc.schema.optimize")
def schema_optimize(ex):
    """No-op optimizer (indexes here are maintained eagerly); reports what
    analyze would."""
    return {"optimized": 0, **schema_analyze(ex)}


@_graph_fn("apoc.schema.assert")
def schema_assert(ex, indexes, constraints):
    """Declarative sync (ref schema.go Assert): maps {label: [props...]}."""
    out = []
    for label, props_list in (indexes or {}).items():
        for props in props_list:
            props = [props] if isinstance(props, str) else list(props)
            schema_create_index(ex, label, props)
            out.append({"label": label, "key": props, "unique": False})
    for label, props_list in (constraints or {}).items():
        for props in props_list:
            props = [props] if isinstance(props, str) else list(props)
            schema_create_constraint(ex, label, props)
            out.append({"label": label, "key": props, "unique": True})
    return out


# ============================================================ apoc.search
def _label_nodes(ex, label):
    return sorted(ex.storage.get_nodes_by_label(label), key=lambda n: n.id)


@_graph_fn("apoc.search.node")
def search_node(ex, label, prop, value):
    return [n for n in _label_nodes(ex, label)
            if n.properties.get(prop) == value]


@_graph_fn("apoc.search.nodeAll")
def search_node_all(ex, label, props):
    return [
        n for n in _label_nodes(ex, label)
        if all(n.properties.get(k) == v for k, v in (props or {}).items())
    ]


@_graph_fn("apoc.search.nodeAny")
def search_node_any(ex, label, props):
    return [
        n for n in _label_nodes(ex, label)
        if any(n.properties.get(k) == v for k, v in (props or {}).items())
    ]


@_graph_fn("apoc.search.nodeReduced")
def search_node_reduced(ex, label, props):
    """Matching nodes reduced to {id, labels} (ref search.go NodeReduced)."""
    return [{"id": n.id, "labels": list(n.labels)}
            for n in search_node_all(ex, label, props)]


@_graph_fn("apoc.search.regex")
def search_regex(ex, label, prop, pattern):
    # bounded engine (see cypher/expr.py): a catastrophic pattern over a
    # large label must error, not wedge the query thread
    from nornicdb_tpu.cypher.expr import _compiled

    pat = _compiled(str(pattern))
    return [n for n in _label_nodes(ex, label)
            if isinstance(n.properties.get(prop), str)
            and pat.fullmatch(n.properties[prop])]


@_graph_fn("apoc.search.prefix")
def search_prefix(ex, label, prop, prefix):
    return [n for n in _label_nodes(ex, label)
            if isinstance(n.properties.get(prop), str)
            and n.properties[prop].startswith(str(prefix))]


@_graph_fn("apoc.search.suffix")
def search_suffix(ex, label, prop, suffix):
    return [n for n in _label_nodes(ex, label)
            if isinstance(n.properties.get(prop), str)
            and n.properties[prop].endswith(str(suffix))]


@_graph_fn("apoc.search.contains")
def search_contains(ex, label, prop, needle):
    return [n for n in _label_nodes(ex, label)
            if isinstance(n.properties.get(prop), str)
            and str(needle) in n.properties[prop]]


@_graph_fn("apoc.search.match")
def search_match(ex, label, prop, glob):
    import fnmatch

    return [n for n in _label_nodes(ex, label)
            if isinstance(n.properties.get(prop), str)
            and fnmatch.fnmatch(n.properties[prop], str(glob))]


@_graph_fn("apoc.search.range")
def search_range(ex, label, prop, lo, hi):
    out = []
    for n in _label_nodes(ex, label):
        v = n.properties.get(prop)
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and lo <= v <= hi:
            out.append(n)
    return out


@_graph_fn("apoc.search.in")
def search_in(ex, label, prop, values):
    vals = list(values or [])
    return [n for n in _label_nodes(ex, label)
            if n.properties.get(prop) in vals]


@_graph_fn("apoc.search.notIn")
def search_not_in(ex, label, prop, values):
    vals = list(values or [])
    return [n for n in _label_nodes(ex, label)
            if n.properties.get(prop) not in vals]


@_graph_fn("apoc.search.exists")
@_graph_fn("apoc.search.notNull")
def search_exists(ex, label, prop):
    return [n for n in _label_nodes(ex, label)
            if n.properties.get(prop) is not None]


@_graph_fn("apoc.search.missing")
@_graph_fn("apoc.search.null")
def search_missing(ex, label, prop):
    return [n for n in _label_nodes(ex, label)
            if n.properties.get(prop) is None]


def _lev(a: str, b: str) -> int:
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                           prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


@_graph_fn("apoc.search.fuzzy")
def search_fuzzy(ex, label, prop, query, max_distance=2):
    q = str(query).lower()
    out = []
    for n in _label_nodes(ex, label):
        v = n.properties.get(prop)
        if isinstance(v, str) and _lev(v.lower(), q) <= int(max_distance):
            out.append(n)
    return out


@_graph_fn("apoc.search.didYouMean")
def search_did_you_mean(ex, label, prop, query):
    best, best_d = None, None
    q = str(query).lower()
    for n in _label_nodes(ex, label):
        v = n.properties.get(prop)
        if isinstance(v, str):
            d = _lev(v.lower(), q)
            if best_d is None or d < best_d:
                best, best_d = v, d
    return best


@_graph_fn("apoc.search.suggest")
@_graph_fn("apoc.search.autocomplete")
def search_suggest(ex, label, prop, prefix, limit=10):
    vals = sorted({
        n.properties[prop] for n in _label_nodes(ex, label)
        if isinstance(n.properties.get(prop), str)
        and n.properties[prop].lower().startswith(str(prefix).lower())
    })
    return vals[: int(limit)]


@register("apoc.search.score")
def search_score(node, query):
    """Token-overlap score of a node's string properties vs the query."""
    if not isinstance(node, Node):
        return 0.0
    tokens = set(str(query).lower().split())
    if not tokens:
        return 0.0
    text = " ".join(str(v).lower() for v in node.properties.values()
                    if isinstance(v, str))
    hits = sum(1 for t in tokens if t in text)
    return hits / len(tokens)


@register("apoc.search.highlight")
def search_highlight(text, query, pre="<b>", post="</b>"):
    out = str(text)
    for token in sorted(set(str(query).split()), key=len, reverse=True):
        if token:
            out = re.sub(
                f"({re.escape(token)})", rf"{pre}\1{post}", out,
                flags=re.IGNORECASE,
            )
    return out


@_graph_fn("apoc.search.fullText")
def search_fulltext(ex, label, query, limit=10):
    """Scored substring search across all string properties."""
    from nornicdb_tpu.apoc.functions_graph2 import search_score

    scored = [
        (search_score(n, query), n) for n in _label_nodes(ex, label)
    ]
    scored = [(s, n) for s, n in scored if s > 0]
    scored.sort(key=lambda t: (-t[0], t[1].id))
    return [{"node": n, "score": s} for s, n in scored[: int(limit)]]


@_graph_fn("apoc.search.parallel")
def search_parallel(ex, queries):
    """[{label, prop, value}] batch of point searches."""
    return [search_node(ex, q["label"], q["prop"], q["value"])
            for q in (queries or [])]


@_graph_fn("apoc.search.multiSearchAll")
def search_multi_all(ex, queries):
    """Nodes matching every {label, prop, value} query."""
    results = search_parallel(ex, queries)
    if not results:
        return []
    ids = set.intersection(*({n.id for n in r} for r in results))
    out = {n.id: n for r in results for n in r if n.id in ids}
    return sorted(out.values(), key=lambda n: n.id)


@_graph_fn("apoc.search.multiSearchAny")
def search_multi_any(ex, queries):
    out = {n.id: n for r in search_parallel(ex, queries) for n in r}
    return sorted(out.values(), key=lambda n: n.id)


@_graph_fn("apoc.search.index")
def search_index(ex, label, properties):
    return schema_create_index(ex, label, properties)


@_graph_fn("apoc.search.dropIndex")
def search_drop_index(ex, label, properties):
    return schema_drop_index(ex, label, properties)


@_graph_fn("apoc.search.reindex")
def search_reindex(ex, label=None):
    """Re-registers every node into the schema property maps."""
    count = 0
    for n in ex.storage.all_nodes():
        if label is None or label in n.labels:
            ex.schema.index_node(n)
            count += 1
    return {"reindexed": count}


# ============================================================ apoc.create
@register("apoc.create.uuid")
def create_uuid():
    return str(_uuid.uuid4())


@register("apoc.create.uuids")
def create_uuids(n):
    return [str(_uuid.uuid4()) for _ in range(int(n))]


@_graph_fn("apoc.create.node")
def create_node(ex, labels, props):
    return ex.storage.create_node(Node(
        id=f"apoc-{_uuid.uuid4()}", labels=list(labels or []),
        properties=dict(props or {}),
    ))


@_graph_fn("apoc.create.nodes")
def create_nodes(ex, labels, props_list):
    return [create_node(ex, labels, p) for p in (props_list or [])]


@_graph_fn("apoc.create.relationship")
def create_relationship(ex, n1, rel_type, n2, props=None):
    a, b = _node(ex, n1), _node(ex, n2)
    return ex.storage.create_edge(Edge(
        id=f"apoc-{_uuid.uuid4()}", start_node=a.id, end_node=b.id,
        type=str(rel_type), properties=dict(props or {}),
    ))


@register("apoc.create.vNode")
def create_vnode(labels, props):
    """Virtual node: never persisted (ref create.go VNode)."""
    return Node(id=f"vnode-{_uuid.uuid4()}", labels=list(labels or []),
                properties=dict(props or {}))


@register("apoc.create.vNodes")
def create_vnodes(labels, props_list):
    return [create_vnode(labels, p) for p in (props_list or [])]


@register("apoc.create.vRelationship")
def create_vrelationship(n1, rel_type, n2, props=None):
    a = n1.id if isinstance(n1, Node) else str(n1)
    b = n2.id if isinstance(n2, Node) else str(n2)
    return Edge(id=f"vrel-{_uuid.uuid4()}", start_node=a, end_node=b,
                type=str(rel_type), properties=dict(props or {}))


@register("apoc.create.vPattern")
def create_vpattern(from_props, rel_type, to_props, rel_props=None):
    a = create_vnode(from_props.pop("_labels", []) if isinstance(from_props, dict) else [], from_props)
    b = create_vnode(to_props.pop("_labels", []) if isinstance(to_props, dict) else [], to_props)
    r = create_vrelationship(a, rel_type, b, rel_props)
    return {"from": a, "rel": r, "to": b}


@_graph_fn("apoc.create.addLabels")
def create_add_labels(ex, node, labels):
    from nornicdb_tpu.apoc.functions_graph import node_add_labels

    return node_add_labels(ex, node, labels)


@_graph_fn("apoc.create.removeLabels")
def create_remove_labels(ex, node, labels):
    from nornicdb_tpu.apoc.functions_graph import node_remove_labels

    return node_remove_labels(ex, node, labels)


@_graph_fn("apoc.create.setProperty")
def create_set_property(ex, node, key, value):
    from nornicdb_tpu.apoc.functions_graph import node_set_property

    return node_set_property(ex, node, key, value)


@_graph_fn("apoc.create.setProperties")
def create_set_properties(ex, node, props):
    from nornicdb_tpu.apoc.functions_graph import node_set_properties

    return node_set_properties(ex, node, props)


@_graph_fn("apoc.create.removeProperties")
def create_remove_properties(ex, node, keys):
    from nornicdb_tpu.apoc.functions_graph import node_remove_properties

    return node_remove_properties(ex, node, keys)


@_graph_fn("apoc.create.setRelProperty")
def create_set_rel_property(ex, rel, key, value):
    from nornicdb_tpu.apoc.functions_graph import rel_set_property

    return rel_set_property(ex, rel, key, value)


@_graph_fn("apoc.create.setRelProperties")
def create_set_rel_properties(ex, rel, props):
    from nornicdb_tpu.apoc.functions_graph import rel_set_properties

    return rel_set_properties(ex, rel, props)


@_graph_fn("apoc.create.removeRelProperties")
def create_remove_rel_properties(ex, rel, keys):
    from nornicdb_tpu.apoc.functions_graph import rel_remove_properties

    return rel_remove_properties(ex, rel, keys)


@_graph_fn("apoc.create.clone")
def create_clone(ex, node):
    from nornicdb_tpu.apoc.functions_graph import node_clone

    return node_clone(ex, node)


@_graph_fn("apoc.create.cloneSubgraph")
def create_clone_subgraph(ex, nodes, rels):
    """Clone nodes + the rels among them; returns {nodes, rels} clones."""
    mapping: dict[str, Node] = {}
    out_nodes = []
    for v in nodes or []:
        n = _node(ex, v)
        clone = ex.storage.create_node(Node(
            id=f"apoc-{_uuid.uuid4()}", labels=list(n.labels),
            properties=dict(n.properties)))
        mapping[n.id] = clone
        out_nodes.append(clone)
    out_rels = []
    for v in rels or []:
        r = _edge(ex, v)
        if r.start_node in mapping and r.end_node in mapping:
            out_rels.append(ex.storage.create_edge(Edge(
                id=f"apoc-{_uuid.uuid4()}",
                start_node=mapping[r.start_node].id,
                end_node=mapping[r.end_node].id,
                type=r.type, properties=dict(r.properties))))
    return {"nodes": out_nodes, "rels": out_rels}


# ============================================================= apoc.merge
@_graph_fn("apoc.merge.mergeNode")
@_graph_fn("apoc.merge.nodeEager")
def merge_node(ex, labels, match_props, on_create=None, on_match=None):
    """MERGE semantics: find by labels+props, else create
    (ref merge.go MergeNode)."""
    labels = list(labels or [])
    match_props = dict(match_props or {})
    for n in (ex.storage.get_nodes_by_label(labels[0])
              if labels else ex.storage.all_nodes()):
        if all(l in n.labels for l in labels) and all(
            n.properties.get(k) == v for k, v in match_props.items()
        ):
            if on_match:
                n.properties.update(on_match)
                return ex.storage.update_node(n)
            return n
    return ex.storage.create_node(Node(
        id=f"apoc-{_uuid.uuid4()}", labels=labels,
        properties={**match_props, **(on_create or {})},
    ))


@_graph_fn("apoc.merge.mergeRelationship")
@_graph_fn("apoc.merge.relationshipEager")
def merge_relationship(ex, n1, rel_type, n2, props=None):
    a, b = _node(ex, n1), _node(ex, n2)
    for r in ex.storage.get_outgoing_edges(a.id):
        if r.end_node == b.id and r.type == rel_type:
            if props:
                r.properties.update(props)
                return ex.storage.update_edge(r)
            return r
    return ex.storage.create_edge(Edge(
        id=f"apoc-{_uuid.uuid4()}", start_node=a.id, end_node=b.id,
        type=str(rel_type), properties=dict(props or {}),
    ))


@_graph_fn("apoc.merge.nodes")
def merge_nodes(ex, nodes):
    from nornicdb_tpu.apoc.functions_graph import nodes_collapse

    return nodes_collapse(ex, nodes)


@_graph_fn("apoc.merge.properties")
def merge_properties(ex, node, props):
    n = _node(ex, node)
    for k, v in (props or {}).items():
        n.properties.setdefault(k, v)
    return ex.storage.update_node(n)


@register("apoc.merge.deepMerge")
def merge_deep(m1, m2):
    def deep(a, b):
        out = dict(a)
        for k, v in b.items():
            if isinstance(out.get(k), dict) and isinstance(v, dict):
                out[k] = deep(out[k], v)
            else:
                out[k] = v
        return out

    return deep(m1 or {}, m2 or {})


@_graph_fn("apoc.merge.labels")
def merge_labels(ex, node, labels):
    from nornicdb_tpu.apoc.functions_graph import node_add_labels

    return node_add_labels(ex, node, labels)


@_graph_fn("apoc.merge.pattern")
def merge_pattern(ex, pattern, props=None):
    """'(:A)-[:T]->(:B)' -> merge both nodes + rel."""
    m = re.fullmatch(
        r"\(:(\w+)\)-\[:(\w+)\]->\(:(\w+)\)", str(pattern).strip())
    if not m:
        raise NornicError(f"unsupported merge pattern {pattern!r}")
    a = merge_node(ex, [m.group(1)], (props or {}).get("from") or {})
    b = merge_node(ex, [m.group(3)], (props or {}).get("to") or {})
    r = merge_relationship(ex, a, m.group(2), b,
                           (props or {}).get("rel") or {})
    return {"from": a, "rel": r, "to": b}


@_graph_fn("apoc.merge.batch")
def merge_batch(ex, items, config=None):
    """[{labels, props}] batch of mergeNode calls."""
    return [merge_node(ex, it.get("labels"), it.get("props"))
            for it in (items or [])]


@_graph_fn("apoc.merge.conditional")
def merge_conditional(ex, condition, config):
    """Merge only when `condition` (Cypher expr) is true."""
    if _eval_pred(ex, str(condition), {}) is not True:
        return None
    cfg = config or {}
    return merge_node(ex, cfg.get("labels"), cfg.get("props"))


@register("apoc.merge.strategy")
def merge_strategy(name):
    allowed = {"COMBINE", "OVERWRITE", "DISCARD"}
    s = str(name).upper()
    if s not in allowed:
        raise NornicError(f"unknown merge strategy {name!r}")
    return s


@register("apoc.merge.conflict")
def merge_conflict(n1, n2, strategy="COMBINE"):
    """Resolve property conflicts between two nodes' maps."""
    p1 = dict(n1.properties) if isinstance(n1, Node) else dict(n1 or {})
    p2 = dict(n2.properties) if isinstance(n2, Node) else dict(n2 or {})
    s = str(strategy).upper()
    if s == "OVERWRITE":
        return {**p1, **p2}
    if s == "DISCARD":
        return {**p2, **p1}
    # COMBINE: conflicting keys become lists
    out = dict(p1)
    for k, v in p2.items():
        if k in out and out[k] != v:
            cur = out[k] if isinstance(out[k], list) else [out[k]]
            out[k] = cur + [v]
        else:
            out[k] = v
    return out


@register("apoc.merge.validate")
def merge_validate(props):
    """Mergeable props: plain keys, no None keys, scalar/list/map values."""
    if not isinstance(props, dict):
        return False
    return all(isinstance(k, str) and k for k in props)


@_graph_fn("apoc.merge.preview")
def merge_preview(ex, config):
    """What mergeNode would do, without writing."""
    cfg = config or {}
    labels = list(cfg.get("labels") or [])
    props = dict(cfg.get("props") or {})
    for n in (ex.storage.get_nodes_by_label(labels[0])
              if labels else ex.storage.all_nodes()):
        if all(l in n.labels for l in labels) and all(
            n.properties.get(k) == v for k, v in props.items()
        ):
            return {"action": "match", "node": n}
    return {"action": "create", "labels": labels, "props": props}


_merge_snapshots: dict[str, dict] = {}


@_graph_fn("apoc.merge.snapshot")
def merge_snapshot(ex, node):
    """Capture a node's state for later rollback; returns a snapshot id."""
    n = _node(ex, node)
    sid = str(_uuid.uuid4())
    _merge_snapshots[sid] = {
        "id": n.id, "labels": list(n.labels), "properties": dict(n.properties)
    }
    return sid


@_graph_fn("apoc.merge.rollback")
def merge_rollback(ex, snapshot_id):
    snap = _merge_snapshots.pop(str(snapshot_id), None)
    if snap is None:
        return False
    n = _node(ex, snap["id"])
    n.labels = list(snap["labels"])
    n.properties = dict(snap["properties"])
    ex.storage.update_node(n)
    return True


# ============================================================= apoc.graph
@register("apoc.graph.from")
def graph_from(nodes, rels, name="graph"):
    return {"name": name, "nodes": list(nodes or []),
            "relationships": list(rels or [])}


@register("apoc.graph.fromData")
def graph_from_data(data):
    d = data or {}
    return graph_from(d.get("nodes"), d.get("relationships") or d.get("rels"))


@register("apoc.graph.fromPath")
def graph_from_path(path):
    p = path or {}
    return graph_from(p.get("nodes"), p.get("relationships"))


@register("apoc.graph.fromPaths")
def graph_from_paths(paths):
    nodes: dict[str, Node] = {}
    rels: dict[str, Edge] = {}
    for p in paths or []:
        for n in (p or {}).get("nodes", []):
            if isinstance(n, Node):
                nodes[n.id] = n
        for r in (p or {}).get("relationships", []):
            if isinstance(r, Edge):
                rels[r.id] = r
    return graph_from(list(nodes.values()), list(rels.values()))


@register("apoc.graph.fromDocument")
def graph_from_document(doc):
    """Nested map -> virtual graph: one node per map, CHILD rels (ref
    graph.go FromDocument)."""
    nodes: list[Node] = []
    rels: list[Edge] = []

    def walk(obj, label):
        scalars = {k: v for k, v in obj.items()
                   if not isinstance(v, (dict, list))}
        node = create_vnode([label], scalars)
        nodes.append(node)
        for k, v in obj.items():
            children = v if isinstance(v, list) else [v]
            for child in children:
                if isinstance(child, dict):
                    cn = walk(child, k.capitalize())
                    rels.append(create_vrelationship(node, k.upper(), cn))
        return node

    if isinstance(doc, str):
        doc = _json.loads(doc)
    if isinstance(doc, dict):
        walk(doc, doc.get("type", "Document"))
    return graph_from(nodes, rels)


@_graph_fn("apoc.graph.fromCypher")
def graph_from_cypher(ex, query, params=None):
    res = ex.execute(str(query), params or {})
    nodes: dict[str, Node] = {}
    rels: dict[str, Edge] = {}
    for row in res.rows:
        for v in row:
            if isinstance(v, Node):
                nodes[v.id] = v
            elif isinstance(v, Edge):
                rels[v.id] = v
    return graph_from(list(nodes.values()), list(rels.values()))


@register("apoc.graph.validate")
def graph_validate(graph):
    """Every rel endpoint must be among the graph's nodes."""
    g = graph or {}
    ids = {n.id for n in g.get("nodes", []) if isinstance(n, Node)}
    dangling = [
        r.id for r in g.get("relationships", [])
        if isinstance(r, Edge)
        and (r.start_node not in ids or r.end_node not in ids)
    ]
    return {"valid": not dangling, "dangling": dangling}


@register("apoc.graph.nodes")
def graph_nodes(graph):
    return list((graph or {}).get("nodes", []))


@register("apoc.graph.relationships")
def graph_relationships(graph):
    return list((graph or {}).get("relationships", []))


@register("apoc.graph.merge")
def graph_merge(g1, g2):
    nodes: dict[str, Node] = {}
    rels: dict[str, Edge] = {}
    for g in (g1 or {}), (g2 or {}):
        for n in g.get("nodes", []):
            if isinstance(n, Node):
                nodes[n.id] = n
        for r in g.get("relationships", []):
            if isinstance(r, Edge):
                rels[r.id] = r
    return graph_from(list(nodes.values()), list(rels.values()))


@register("apoc.graph.clone")
def graph_clone(graph):
    g = graph or {}
    return graph_from(list(g.get("nodes", [])),
                      list(g.get("relationships", [])),
                      name=g.get("name", "graph"))


@register("apoc.graph.stats")
def graph_stats(graph):
    g = graph or {}
    n = len(g.get("nodes", []))
    m = len(g.get("relationships", []))
    return {"nodeCount": n, "relCount": m,
            "density": (m / (n * (n - 1))) if n > 1 else 0.0}


@register("apoc.graph.toMap")
def graph_to_map(graph):
    g = graph or {}
    return {
        "name": g.get("name", "graph"),
        "nodes": [node_to_map(n) for n in g.get("nodes", [])
                  if isinstance(n, Node)],
        "relationships": [rel_to_map(r) for r in g.get("relationships", [])
                          if isinstance(r, Edge)],
    }


@register("apoc.graph.fromMap")
def graph_from_map(m):
    g = m or {}
    nodes = [Node(id=str(s["id"]), labels=list(s.get("labels") or []),
                  properties=dict(s.get("properties") or {}))
             for s in g.get("nodes", [])]
    rels = [Edge(id=str(s["id"]), start_node=str(s["start"]),
                 end_node=str(s["end"]), type=str(s.get("type", "RELATED_TO")),
                 properties=dict(s.get("properties") or {}))
            for s in g.get("relationships", [])]
    return graph_from(nodes, rels, name=g.get("name", "graph"))


@register("apoc.graph.subgraph")
def graph_subgraph(graph, node_ids):
    g = graph or {}
    keep = {str(i) for i in (node_ids or [])}
    nodes = [n for n in g.get("nodes", [])
             if isinstance(n, Node) and n.id in keep]
    rels = [r for r in g.get("relationships", [])
            if isinstance(r, Edge) and r.start_node in keep
            and r.end_node in keep]
    return graph_from(nodes, rels)


# ============================================================ apoc.cypher
@_graph_fn("apoc.cypher.run")
@_graph_fn("apoc.cypher.doIt")
def cypher_run(ex, query, params=None):
    res = ex.execute(str(query), params or {})
    return res.rows_as_dicts()


@_graph_fn("apoc.cypher.runMany")
def cypher_run_many(ex, queries, params=None):
    return [cypher_run(ex, q, params) for q in (queries or [])]


@_graph_fn("apoc.cypher.runFirstColumn")
def cypher_run_first_column(ex, query, params=None):
    res = ex.execute(str(query), params or {})
    return [row[0] for row in res.rows if row]


@_graph_fn("apoc.cypher.runFirstColumnSingle")
def cypher_run_first_column_single(ex, query, params=None):
    col = cypher_run_first_column(ex, query, params)
    return col[0] if col else None


@_graph_fn("apoc.cypher.runFirstColumnMany")
def cypher_run_first_column_many(ex, queries, params=None):
    return [cypher_run_first_column(ex, q, params) for q in (queries or [])]


@register("apoc.cypher.parse")
def cypher_parse(query):
    """Parse and describe the statement (clause names)."""
    from nornicdb_tpu.cypher.parser import parse

    stmt = parse(str(query))
    clauses = [type(c).__name__ for c in getattr(stmt, "clauses", [])]
    return {"valid": True, "statement": type(stmt).__name__,
            "clauses": clauses}


@register("apoc.cypher.validate")
def cypher_validate(query):
    from nornicdb_tpu.cypher.parser import parse

    try:
        parse(str(query))
        return True
    # the exception IS the (negative) validation result the caller asked
    # for — not an operational error worth a log line or counter
    except Exception:  # nornlint: disable=NL-ERR02
        return False


@_graph_fn("apoc.cypher.explain")
def cypher_explain(ex, query):
    res = ex.execute(f"EXPLAIN {query}")
    return res.rows[0][0] if res.rows else None


@_graph_fn("apoc.cypher.profile")
def cypher_profile(ex, query):
    res = ex.execute(f"PROFILE {query}")
    return res.rows[0][0] if res.rows else None


@_graph_fn("apoc.cypher.parallel")
@_graph_fn("apoc.cypher.mapParallel")
def cypher_parallel(ex, query, items, param_name="item"):
    """Run the query once per item with $item bound (the reference fans
    out goroutines; here items run through the scan thread pool)."""
    from nornicdb_tpu.cypher.parallel import parallel_map

    return parallel_map(
        list(items or []),
        lambda it: cypher_run(ex, query, {param_name: it}),
    )


@register("apoc.cypher.toMap")
def cypher_to_map(result):
    if isinstance(result, list):
        return result[0] if result else {}
    return result


@register("apoc.cypher.toList")
def cypher_to_list(result):
    return result if isinstance(result, list) else [result]


@register("apoc.cypher.toJson")
def cypher_to_json(result):
    def default(o):
        if isinstance(o, Node):
            return node_to_map(o)
        if isinstance(o, Edge):
            return rel_to_map(o)
        return str(o)

    return _json.dumps(result, default=default, sort_keys=True)


@_graph_fn("apoc.cypher.runFile")
def cypher_run_file(ex, path):
    """Run semicolon-separated statements from a local file."""
    with open(str(path), "r", encoding="utf-8") as f:
        text = f.read()
    out = []
    for stmt in text.split(";"):
        stmt = stmt.strip()
        if stmt:
            out.append(cypher_run(ex, stmt))
    return out


# ===================================================== community / algo
def _graph_arrays(ex, nodes, rels):
    """(ids, src, dst) index arrays from Node/id lists + Edge/[s,d] lists;
    when rels is None, edges among the nodes are read from storage."""
    ids = [v.id if isinstance(v, Node) else str(v) for v in (nodes or [])]
    pos = {nid: i for i, nid in enumerate(ids)}
    src, dst = [], []
    if rels is None:
        for nid in ids:
            for r in ex.storage.get_outgoing_edges(nid):
                if r.end_node in pos:
                    src.append(pos[nid])
                    dst.append(pos[r.end_node])
    else:
        for r in rels:
            if isinstance(r, Edge):
                s, d = r.start_node, r.end_node
            elif isinstance(r, dict):
                s, d = str(r["start"]), str(r["end"])
            else:
                s, d = str(r[0]), str(r[1])
            if s in pos and d in pos:
                src.append(pos[s])
                dst.append(pos[d])
    return ids, np.asarray(src, np.int32), np.asarray(dst, np.int32)


def _by_id(ids, values):
    return {nid: (v.item() if hasattr(v, "item") else v)
            for nid, v in zip(ids, values)}


@_graph_fn("apoc.community.louvain")
@_graph_fn("apoc.community.fastGreedy")
@_graph_fn("apoc.community.walkTrap")
@_graph_fn("apoc.community.spinGlass")
def community_louvain(ex, nodes, rels=None, config=None):
    from nornicdb_tpu.ops.graph_algos import louvain

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, louvain(src, dst, len(ids)))


@_graph_fn("apoc.community.labelPropagation")
@_graph_fn("apoc.community.infoMap")
def community_label_propagation(ex, nodes, rels=None, iters=10):
    from nornicdb_tpu.ops.graph_algos import label_propagation

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, label_propagation(src, dst, len(ids),
                                         iters=int(iters or 10)))


@_graph_fn("apoc.community.modularity")
def community_modularity(ex, nodes, rels, communities):
    from nornicdb_tpu.ops.graph_algos import modularity

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return 0.0
    labels = np.asarray(
        [int((communities or {}).get(nid, 0)) for nid in ids], np.int32)
    return float(modularity(src, dst, len(ids), labels))


@_graph_fn("apoc.community.triangleCount")
def community_triangle_count(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import triangle_counts

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, triangle_counts(src, dst, len(ids)))


@_graph_fn("apoc.community.totalTriangles")
def community_total_triangles(ex, nodes, rels=None):
    counts = community_triangle_count(ex, nodes, rels)
    return sum(counts.values()) // 3 if counts else 0


@_graph_fn("apoc.community.clusteringCoefficient")
def community_clustering(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import clustering_coefficient

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, clustering_coefficient(src, dst, len(ids)))


@_graph_fn("apoc.community.averageClusteringCoefficient")
def community_avg_clustering(ex, nodes, rels=None):
    c = community_clustering(ex, nodes, rels)
    return sum(c.values()) / len(c) if c else 0.0


@_graph_fn("apoc.community.connectedComponents")
@_graph_fn("apoc.community.weaklyConnectedComponents")
def community_wcc(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import connected_components

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, connected_components(src, dst, len(ids)))


@_graph_fn("apoc.community.numComponents")
def community_num_components(ex, nodes, rels=None):
    comps = community_wcc(ex, nodes, rels)
    return len(set(comps.values())) if comps else 0


@_graph_fn("apoc.community.stronglyConnectedComponents")
def community_scc(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import strongly_connected_components

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, strongly_connected_components(src, dst, len(ids)))


@_graph_fn("apoc.community.kCore")
def community_k_core(ex, nodes, rels=None, k=2):
    from nornicdb_tpu.ops.graph_algos import k_core

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return []
    core = k_core(src, dst, len(ids))
    return [nid for nid, c in zip(ids, core) if int(c) >= int(k)]


@_graph_fn("apoc.community.coreNumber")
def community_core_number(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import k_core

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, k_core(src, dst, len(ids)))


@_graph_fn("apoc.community.conductance")
def community_conductance(ex, nodes, rels, communities, community):
    from nornicdb_tpu.ops.graph_algos import conductance

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return 0.0
    labels = np.asarray(
        [int((communities or {}).get(nid, 0)) for nid in ids], np.int32)
    return float(conductance(src, dst, len(ids), labels, int(community)))


@_graph_fn("apoc.community.density")
def community_density(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import density

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return 0.0
    return float(density(src, dst, len(ids)))


@_graph_fn("apoc.algo.pageRank")
def algo_pagerank(ex, nodes, rels=None, damping=0.85, iters=20):
    from nornicdb_tpu.ops.graph_algos import pagerank

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, pagerank(src, dst, len(ids), damping=float(damping),
                                iters=int(iters)))


@_graph_fn("apoc.algo.degreeCentrality")
def algo_degree_centrality(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import degree_centrality

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, degree_centrality(src, dst, len(ids)))


@_graph_fn("apoc.algo.closenessCentrality")
def algo_closeness_centrality(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import closeness_centrality

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, closeness_centrality(src, dst, len(ids)))


@_graph_fn("apoc.algo.betweennessCentrality")
def algo_betweenness_centrality(ex, nodes, rels=None):
    from nornicdb_tpu.ops.graph_algos import betweenness_centrality

    ids, src, dst = _graph_arrays(ex, nodes, rels)
    if not ids:
        return {}
    return _by_id(ids, betweenness_centrality(src, dst, len(ids)))


@_graph_fn("apoc.algo.community")
def algo_community(ex, nodes, rels=None):
    return community_louvain(ex, nodes, rels)


def _weighted_adj(ex, weight_prop=None):
    adj: dict[str, list[tuple[str, float]]] = {}
    for e in ex.storage.all_edges():
        w = 1.0
        if weight_prop:
            v = e.properties.get(weight_prop)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w = float(v)
        adj.setdefault(e.start_node, []).append((e.end_node, w))
        adj.setdefault(e.end_node, []).append((e.start_node, w))
    return adj


@_graph_fn("apoc.algo.dijkstra")
def algo_dijkstra(ex, start, end, weight_prop=None):
    """Shortest weighted path -> {path: [ids], cost} or None."""
    import heapq

    s, t = _node(ex, start).id, _node(ex, end).id
    adj = _weighted_adj(ex, weight_prop)
    dist = {s: 0.0}
    prev: dict[str, str] = {}
    heap = [(0.0, s)]
    seen = set()
    while heap:
        d, cur = heapq.heappop(heap)
        if cur in seen:
            continue
        seen.add(cur)
        if cur == t:
            break
        for nxt, w in adj.get(cur, []):
            nd = d + w
            if nd < dist.get(nxt, float("inf")):
                dist[nxt] = nd
                prev[nxt] = cur
                heapq.heappush(heap, (nd, nxt))
    if t not in dist:
        return None
    path = [t]
    while path[-1] != s:
        path.append(prev[path[-1]])
    return {"path": path[::-1], "cost": dist[t]}


@_graph_fn("apoc.algo.aStar")
def algo_astar(ex, start, end, config=None):
    """A* = dijkstra here (admissible zero heuristic; config may carry
    weightProperty)."""
    cfg = config or {}
    return algo_dijkstra(ex, start, end, cfg.get("weightProperty"))


@_graph_fn("apoc.algo.allPairs")
def algo_all_pairs(ex, nodes, rels=None):
    """All-pairs hop distances among the given nodes (BFS per node)."""
    ids, src, dst = _graph_arrays(ex, nodes, rels)
    adj: dict[int, set[int]] = {}
    for s, d in zip(src.tolist(), dst.tolist()):
        adj.setdefault(s, set()).add(d)
        adj.setdefault(d, set()).add(s)
    out = {}
    for i, nid in enumerate(ids):
        dist = {i: 0}
        frontier = [i]
        while frontier:
            nxt = []
            for cur in frontier:
                for nb in adj.get(cur, ()):
                    if nb not in dist:
                        dist[nb] = dist[cur] + 1
                        nxt.append(nb)
            frontier = nxt
        out[nid] = {ids[j]: h for j, h in dist.items() if j != i}
    return out


@_graph_fn("apoc.algo.cover")
def algo_cover(ex, node_ids):
    """Edges whose both endpoints are in the given set (ref algo.go
    Cover)."""
    keep = {(_node(ex, v)).id for v in (node_ids or [])}
    out = []
    for nid in sorted(keep):
        for r in ex.storage.get_outgoing_edges(nid):
            if r.end_node in keep:
                out.append(r)
    return out


# ======================================================= paths / path
def _bfs_paths(ex, start_id, end_id, max_len=6, all_paths=False, limit=1000):
    """Simple (node-unique) directed+undirected paths via DFS."""
    out = []
    stack = [(start_id, [start_id])]
    while stack and len(out) < limit:
        cur, path = stack.pop()
        if cur == end_id and len(path) > 1:
            out.append(path)
            if not all_paths:
                break
            continue
        if len(path) > max_len:
            continue
        nbrs = set()
        for r in ex.storage.get_outgoing_edges(cur):
            nbrs.add(r.end_node)
        for r in ex.storage.get_incoming_edges(cur):
            nbrs.add(r.start_node)
        for nxt in sorted(nbrs, reverse=True):
            if nxt == end_id or nxt not in path:
                stack.append((nxt, path + [nxt]))
    return out


@_graph_fn("apoc.paths.all")
@_graph_fn("apoc.paths.simple")
@_graph_fn("apoc.paths.elementary")
def paths_all(ex, start, end, max_length=6):
    s, t = _node(ex, start).id, _node(ex, end).id
    return _bfs_paths(ex, s, t, int(max_length), all_paths=True)


@_graph_fn("apoc.paths.shortest")
def paths_shortest(ex, start, end):
    s, t = _node(ex, start).id, _node(ex, end).id
    # BFS = fewest hops
    frontier = [s]
    prev = {s: None}
    while frontier and t not in prev:
        nxt = []
        for cur in frontier:
            nbrs = set()
            for r in ex.storage.get_outgoing_edges(cur):
                nbrs.add(r.end_node)
            for r in ex.storage.get_incoming_edges(cur):
                nbrs.add(r.start_node)
            for nb in sorted(nbrs):
                if nb not in prev:
                    prev[nb] = cur
                    nxt.append(nb)
        frontier = nxt
    if t not in prev:
        return None
    path = [t]
    while path[-1] != s:
        path.append(prev[path[-1]])
    return path[::-1]


@_graph_fn("apoc.paths.longest")
def paths_longest(ex, start, end, max_length=8):
    ps = paths_all(ex, start, end, max_length)
    return max(ps, key=len) if ps else None


@_graph_fn("apoc.paths.kShortest")
def paths_k_shortest(ex, start, end, k=3, max_length=8):
    ps = paths_all(ex, start, end, max_length)
    return sorted(ps, key=lambda p: (len(p), p))[: int(k)]


@_graph_fn("apoc.paths.count")
def paths_count(ex, start, end, max_length=6):
    return len(paths_all(ex, start, end, max_length))


@_graph_fn("apoc.paths.exists")
def paths_exists(ex, start, end):
    return paths_shortest(ex, start, end) is not None


@_graph_fn("apoc.paths.distance")
def paths_distance(ex, start, end):
    p = paths_shortest(ex, start, end)
    return len(p) - 1 if p else None


@_graph_fn("apoc.paths.withLength")
def paths_with_length(ex, start, end, length):
    return [p for p in paths_all(ex, start, end, int(length))
            if len(p) - 1 == int(length)]


@_graph_fn("apoc.paths.withinLength")
def paths_within_length(ex, start, end, max_length):
    return paths_all(ex, start, end, int(max_length))


@_graph_fn("apoc.paths.cycles")
def paths_cycles(ex, start, max_length=8):
    """Directed cycles through `start`."""
    s = _node(ex, start).id
    out = []
    stack = [(s, [s])]
    while stack:
        cur, path = stack.pop()
        for r in ex.storage.get_outgoing_edges(cur):
            nxt = r.end_node
            if nxt == s and len(path) > 1:
                out.append(path + [s])
            elif nxt not in path and len(path) < int(max_length):
                stack.append((nxt, path + [nxt]))
    return out


@_graph_fn("apoc.paths.disjoint")
def paths_disjoint(ex, start, end, max_length=6):
    """Greedy node-disjoint path set."""
    used: set = set()
    out = []
    for p in sorted(paths_all(ex, start, end, max_length),
                    key=lambda p: (len(p), p)):
        inner = set(p[1:-1])
        if not inner & used:
            out.append(p)
            used |= inner
    return out


@_graph_fn("apoc.paths.edgeDisjoint")
def paths_edge_disjoint(ex, start, end, max_length=6):
    used: set = set()
    out = []
    for p in sorted(paths_all(ex, start, end, max_length),
                    key=lambda p: (len(p), p)):
        edges = {tuple(sorted((a, b))) for a, b in zip(p, p[1:])}
        if not edges & used:
            out.append(p)
            used |= edges
    return out


@_graph_fn("apoc.paths.hamiltonian")
def paths_hamiltonian(ex, nodes):
    """Hamiltonian path over the given nodes (backtracking, small sets)."""
    ids = [(_node(ex, v)).id for v in (nodes or [])]
    idset = set(ids)
    if len(ids) > 12:
        raise NornicError("hamiltonian search capped at 12 nodes")

    def nbrs(nid):
        out = set()
        for r in ex.storage.get_outgoing_edges(nid):
            out.add(r.end_node)
        for r in ex.storage.get_incoming_edges(nid):
            out.add(r.start_node)
        return out & idset

    def walk(path):
        if len(path) == len(ids):
            return path
        for nb in sorted(nbrs(path[-1])):
            if nb not in path:
                r = walk(path + [nb])
                if r:
                    return r
        return None

    for s in sorted(ids):
        r = walk([s])
        if r:
            return r
    return None


@_graph_fn("apoc.paths.eulerian")
def paths_eulerian(ex, nodes):
    """Eulerian path over the subgraph induced by `nodes` (Hierholzer,
    undirected)."""
    ids = {(_node(ex, v)).id for v in (nodes or [])}
    adj: dict[str, list] = {i: [] for i in ids}
    edges = set()
    for nid in ids:
        for r in ex.storage.get_outgoing_edges(nid):
            if r.end_node in ids and r.id not in edges:
                edges.add(r.id)
                adj[nid].append((r.end_node, r.id))
                adj[r.end_node].append((nid, r.id))
    odd = [i for i in sorted(ids) if len(adj[i]) % 2 == 1]
    if len(odd) not in (0, 2) or not edges:
        return None
    start = odd[0] if odd else sorted(ids)[0]
    used: set = set()
    stack = [start]
    path = []
    while stack:
        cur = stack[-1]
        found = None
        for nb, eid in adj[cur]:
            if eid not in used:
                found = (nb, eid)
                break
        if found:
            used.add(found[1])
            stack.append(found[0])
        else:
            path.append(stack.pop())
    if len(used) != len(edges):
        return None
    return path[::-1]


@register("apoc.paths.common")
def paths_common(p1, p2):
    s = set(p2 or [])
    return [x for x in (p1 or []) if x in s]


@register("apoc.paths.unique")
def paths_unique(paths):
    seen = set()
    out = []
    for p in paths or []:
        key = tuple(p)
        if key not in seen:
            seen.add(key)
            out.append(p)
    return out


@register("apoc.paths.merge")
def paths_merge(p1, p2):
    p1, p2 = list(p1 or []), list(p2 or [])
    if p1 and p2 and p1[-1] == p2[0]:
        return p1 + p2[1:]
    return p1 + p2


@register("apoc.paths.reverse")
def paths_reverse(path):
    return list(reversed(path or []))


@register("apoc.paths.slice")
def paths_slice(path, start, end=None):
    p = list(path or [])
    return p[int(start): (int(end) if end is not None else len(p))]


@_graph_fn("apoc.path.shortestPath")
def path_shortest(ex, start, end):
    return paths_shortest(ex, start, end)


@_graph_fn("apoc.path.allShortestPaths")
def path_all_shortest(ex, start, end):
    sp = paths_shortest(ex, start, end)
    if sp is None:
        return []
    want = len(sp) - 1
    return [p for p in paths_all(ex, start, end, want)
            if len(p) - 1 == want]


@_graph_fn("apoc.path.subgraphNodes")
def path_subgraph_nodes(ex, start, config=None):
    cfg = config or {}
    from nornicdb_tpu.apoc.functions_graph import neighbors_to_hop

    return neighbors_to_hop(
        ex, start, cfg.get("relationshipFilter"),
        int(cfg.get("maxLevel", 3)),
    )


@_graph_fn("apoc.path.subgraphAll")
def path_subgraph_all(ex, start, config=None):
    nodes = path_subgraph_nodes(ex, start, config)
    ids = {n.id for n in nodes} | {_node(ex, start).id}
    rels = []
    for nid in sorted(ids):
        for r in ex.storage.get_outgoing_edges(nid):
            if r.end_node in ids:
                rels.append(r)
    return {"nodes": nodes, "relationships": rels}


@_graph_fn("apoc.path.spanningTree")
def path_spanning_tree(ex, start, config=None):
    """BFS tree edges from start (ref path.go SpanningTree)."""
    cfg = config or {}
    max_level = int(cfg.get("maxLevel", 5))
    s = _node(ex, start).id
    seen = {s}
    frontier = [s]
    tree = []
    for _ in range(max_level):
        nxt = []
        for cur in frontier:
            for r in ex.storage.get_outgoing_edges(cur):
                if r.end_node not in seen:
                    seen.add(r.end_node)
                    tree.append(r)
                    nxt.append(r.end_node)
            for r in ex.storage.get_incoming_edges(cur):
                if r.start_node not in seen:
                    seen.add(r.start_node)
                    tree.append(r)
                    nxt.append(r.start_node)
        frontier = nxt
    return tree


@_graph_fn("apoc.path.expandConfig")
def path_expand_config(ex, start, config=None):
    """Paths from start honoring {maxLevel, relationshipFilter, labelFilter,
    uniqueness: NODE_PATH} (subset of the reference's expandConfig)."""
    cfg = config or {}
    max_level = int(cfg.get("maxLevel", 3))
    rel_filter = cfg.get("relationshipFilter")
    label_filter = cfg.get("labelFilter")
    s = _node(ex, start).id
    out = []
    stack = [(s, [s])]
    while stack:
        cur, path = stack.pop()
        if len(path) > 1:
            out.append(path)
        if len(path) > max_level:
            continue
        for r in ex.storage.get_outgoing_edges(cur):
            if rel_filter and r.type != rel_filter:
                continue
            if r.end_node in path:
                continue
            if label_filter:
                n = ex.get_node_or_none(r.end_node)
                if n is None or label_filter not in n.labels:
                    continue
            stack.append((r.end_node, path + [r.end_node]))
    return out


@register("apoc.path.combine")
def path_combine(p1, p2):
    return paths_merge(p1, p2)


@register("apoc.path.elements")
def path_elements(path):
    if isinstance(path, dict):
        nodes = path.get("nodes", [])
        rels = path.get("relationships", [])
        out = []
        for i, n in enumerate(nodes):
            out.append(n)
            if i < len(rels):
                out.append(rels[i])
        return out
    return list(path or [])


@register("apoc.path.slice")
def path_slice(path, offset, length=None):
    p = list(path or [])
    start = int(offset)
    return p[start: start + int(length)] if length is not None else p[start:]
