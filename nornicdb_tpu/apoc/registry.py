"""APOC function registry.

Behavioral reference: /root/reference/apoc/apoc.go:121 (Call),
registry/registry.go:44-120 (central registry), category env gates
(apoc/config.go: NORNICDB_APOC_<CATEGORY>_ENABLED).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Optional

_REGISTRY: dict[str, Callable] = {}
_CATEGORIES: dict[str, set[str]] = {}
_lock = threading.Lock()


def register(name: str, category: Optional[str] = None):
    """Register an apoc.* function. Name is the full dotted name."""
    cat = category or name.split(".")[1] if name.count(".") >= 1 else "util"

    def deco(fn):
        with _lock:
            _REGISTRY[name.lower()] = fn
            _CATEGORIES.setdefault(cat, set()).add(name.lower())
        return fn

    return deco


def category_enabled(category: str) -> bool:
    """(ref: apoc/config.go env gates — enabled by default here)"""
    env = os.environ.get(f"NORNICDB_APOC_{category.upper()}_ENABLED")
    if env is None:
        return True
    return env.lower() not in ("0", "false", "no")


def lookup(name: str) -> Optional[Callable]:
    """(ref: apoc.Call apoc.go:121 -> callFunction :1386)"""
    fn = _REGISTRY.get(name.lower())
    if fn is None:
        return None
    parts = name.lower().split(".")
    if len(parts) >= 2 and not category_enabled(parts[1]):
        return None
    return fn


def call(name: str, *args: Any) -> Any:
    fn = lookup(name)
    if fn is None:
        raise KeyError(f"unknown apoc function {name}")
    return fn(*args)


def all_functions() -> list[str]:
    with _lock:
        return sorted(_REGISTRY)


def categories() -> dict[str, int]:
    with _lock:
        return {c: len(fns) for c, fns in sorted(_CATEGORIES.items())}
