"""APOC-compatible function/procedure library (ref: /root/reference/apoc/ —
850+ functions in ~45 categories; this build implements the core categories:
coll, text, map, math, number, convert, json, date, temporal, hashing, meta,
label, node, rel, any, util, bitwise, diff, stats, spatial, scoring, xml,
create, merge, refactor, neighbors, path, periodic, trigger, cypher, schema,
nodes, log)."""

from nornicdb_tpu.apoc import functions as _functions  # noqa: F401 — registers
from nornicdb_tpu.apoc import functions_ext as _functions_ext  # noqa: F401
from nornicdb_tpu.apoc import functions_graph as _functions_graph  # noqa: F401
from nornicdb_tpu.apoc import functions_graph2 as _functions_graph2  # noqa: F401
from nornicdb_tpu.apoc import functions_ops as _functions_ops  # noqa: F401
from nornicdb_tpu.apoc import functions_pure as _functions_pure  # noqa: F401
from nornicdb_tpu.apoc import functions_tail as _functions_tail  # noqa: F401
from nornicdb_tpu.apoc.registry import all_functions, call, categories, lookup

__all__ = ["all_functions", "call", "categories", "lookup"]


def register_procedures() -> None:
    """Import the storage-touching procedures into the Cypher registry."""
    from nornicdb_tpu.apoc import export_import as _export_import  # noqa: F401
    from nornicdb_tpu.apoc import procedures as _procedures  # noqa: F401
