"""APOC extended pure-function categories.

Behavioral reference: /root/reference/apoc/{bitwise,json,diff,stats,
spatial,scoring,xml}/ — each is a Go package of pure helpers
(bitwise/bitwise.go, json/json.go, diff/diff.go, stats/stats.go,
spatial/spatial.go, scoring/scoring.go, xml/xml.go). Reimplemented from
observed behavior; signatures follow the APOC dotted-name convention and
null-in/null-out semantics used throughout functions.py.
"""

from __future__ import annotations

import json as _json
import math as _math
import re
import statistics
import xml.etree.ElementTree as _ET
from typing import Any, Optional

from nornicdb_tpu.apoc.registry import register

# ---------------------------------------------------------------------------
# apoc.bitwise.* (ref: apoc/bitwise/bitwise.go — Op/And/Or/Xor/shifts/bits)
# ---------------------------------------------------------------------------


@register("apoc.bitwise.op")
def bitwise_op(a, op, b):
    if a is None or b is None:
        return None
    a, b = int(a), int(b)
    op = str(op).upper()
    if op in ("&", "AND"):
        return a & b
    if op in ("|", "OR"):
        return a | b
    if op in ("^", "XOR"):
        return a ^ b
    if op in ("<<", "LEFT_SHIFT", "LEFT SHIFT"):
        return a << b
    if op in (">>", "RIGHT_SHIFT", "RIGHT SHIFT"):
        return a >> b
    if op in ("~", "NOT"):
        return ~a
    return 0


@register("apoc.bitwise.and")
def bitwise_and(*values):
    vals = values[0] if len(values) == 1 and isinstance(values[0], list) else values
    if not vals:
        return 0
    out = int(vals[0])
    for v in vals[1:]:
        out &= int(v)
    return out


@register("apoc.bitwise.or")
def bitwise_or(*values):
    vals = values[0] if len(values) == 1 and isinstance(values[0], list) else values
    out = 0
    for v in vals:
        out |= int(v)
    return out


@register("apoc.bitwise.xor")
def bitwise_xor(*values):
    vals = values[0] if len(values) == 1 and isinstance(values[0], list) else values
    out = 0
    for v in vals:
        out ^= int(v)
    return out


@register("apoc.bitwise.not")
def bitwise_not(a):
    return None if a is None else ~int(a)


@register("apoc.bitwise.leftShift")
def bitwise_lshift(a, n):
    return None if a is None else int(a) << int(n)


@register("apoc.bitwise.rightShift")
def bitwise_rshift(a, n):
    return None if a is None else int(a) >> int(n)


@register("apoc.bitwise.setBit")
def bitwise_set_bit(a, pos):
    return None if a is None else int(a) | (1 << int(pos))


@register("apoc.bitwise.clearBit")
def bitwise_clear_bit(a, pos):
    return None if a is None else int(a) & ~(1 << int(pos))


@register("apoc.bitwise.toggleBit")
def bitwise_toggle_bit(a, pos):
    return None if a is None else int(a) ^ (1 << int(pos))


@register("apoc.bitwise.testBit")
def bitwise_test_bit(a, pos):
    return None if a is None else bool(int(a) & (1 << int(pos)))


@register("apoc.bitwise.countBits")
def bitwise_count_bits(a):
    if a is None:
        return None
    v = int(a)
    return bin(v & 0xFFFFFFFFFFFFFFFF).count("1") if v < 0 else bin(v).count("1")


# ---------------------------------------------------------------------------
# apoc.json.* (ref: apoc/json/json.go — Path/Validate/Parse/Stringify/…)
# ---------------------------------------------------------------------------


def _json_path(obj: Any, path: str) -> Any:
    """Dotted/bracket path: `a.b[0].c` (ref json.go Path). `$.` prefix ok."""
    if path.startswith("$"):
        path = path[1:].lstrip(".")
    cur = obj
    for part in re.findall(r"[^.\[\]]+|\[\d+\]", path):
        if cur is None:
            return None
        if part.startswith("["):
            idx = int(part[1:-1])
            if not isinstance(cur, list) or idx >= len(cur):
                return None
            cur = cur[idx]
        else:
            if isinstance(cur, dict):
                cur = cur.get(part)
            elif isinstance(cur, list) and part.isdigit():
                i = int(part)
                cur = cur[i] if i < len(cur) else None
            else:
                return None
    return cur


@register("apoc.json.path")
def json_path(value, path):
    if value is None:
        return None
    obj = _json.loads(value) if isinstance(value, str) else value
    return _json_path(obj, str(path or ""))


@register("apoc.json.validate")
def json_validate(s):
    if s is None:
        return False
    try:
        _json.loads(s)
        return True
    except (ValueError, TypeError):
        return False


@register("apoc.json.parse")
def json_parse(s):
    return None if s is None else _json.loads(s)


@register("apoc.json.stringify")
def json_stringify(v):
    return _json.dumps(v, default=str)


@register("apoc.json.pretty")
def json_pretty(v):
    obj = _json.loads(v) if isinstance(v, str) else v
    return _json.dumps(obj, indent=2, default=str)


@register("apoc.json.compact")
def json_compact(v):
    obj = _json.loads(v) if isinstance(v, str) else v
    return _json.dumps(obj, separators=(",", ":"), default=str)


@register("apoc.json.keys")
def json_keys(v):
    obj = _json.loads(v) if isinstance(v, str) else v
    return sorted(obj.keys()) if isinstance(obj, dict) else []


@register("apoc.json.size")
def json_size(v):
    obj = _json.loads(v) if isinstance(v, str) else v
    if isinstance(obj, (dict, list, str)):
        return len(obj)
    return 0


@register("apoc.json.merge")
def json_merge(a, b):
    da = _json.loads(a) if isinstance(a, str) else dict(a or {})
    db = _json.loads(b) if isinstance(b, str) else dict(b or {})
    return {**da, **db}


@register("apoc.json.flatten")
def json_flatten(v, delimiter="."):
    """{"a": {"b": 1}} -> {"a.b": 1} (ref json.go Flatten)."""
    obj = _json.loads(v) if isinstance(v, str) else v
    out: dict[str, Any] = {}

    def walk(prefix, node):
        if isinstance(node, dict) and node:
            for k, val in node.items():
                walk(f"{prefix}{delimiter}{k}" if prefix else str(k), val)
        elif isinstance(node, list) and node:
            for i, val in enumerate(node):
                walk(f"{prefix}[{i}]", val)
        else:
            out[prefix] = node

    walk("", obj)
    return out


@register("apoc.json.set")
def json_set(v, path, value):
    obj = _json.loads(v) if isinstance(v, str) else dict(v or {})
    parts = str(path).split(".")
    cur = obj
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[parts[-1]] = value
    return obj


@register("apoc.json.delete")
def json_delete(v, path):
    obj = _json.loads(v) if isinstance(v, str) else dict(v or {})
    parts = str(path).split(".")
    cur = obj
    for p in parts[:-1]:
        cur = cur.get(p) if isinstance(cur, dict) else None
        if cur is None:
            return obj
    if isinstance(cur, dict):
        cur.pop(parts[-1], None)
    return obj


# ---------------------------------------------------------------------------
# apoc.diff.* (ref: apoc/diff/diff.go — Nodes/Maps/Lists/Strings)
# ---------------------------------------------------------------------------


def _props_of(x) -> dict:
    return dict(getattr(x, "properties", x) or {})


@register("apoc.diff.nodes")
def diff_nodes(a, b):
    """{leftOnly, rightOnly, inCommon, different} (ref diff.go Nodes)."""
    return diff_maps(_props_of(a), _props_of(b))


@register("apoc.diff.relationships")
def diff_relationships(a, b):
    return diff_maps(_props_of(a), _props_of(b))


@register("apoc.diff.maps")
def diff_maps(a, b):
    a, b = dict(a or {}), dict(b or {})
    left_only = {k: v for k, v in a.items() if k not in b}
    right_only = {k: v for k, v in b.items() if k not in a}
    in_common = {k: v for k, v in a.items() if k in b and b[k] == v}
    different = {
        k: {"left": a[k], "right": b[k]}
        for k in a
        if k in b and b[k] != a[k]
    }
    return {
        "leftOnly": left_only,
        "rightOnly": right_only,
        "inCommon": in_common,
        "different": different,
    }


@register("apoc.diff.lists")
def diff_lists(a, b):
    a, b = list(a or []), list(b or [])
    return {
        "leftOnly": [x for x in a if x not in b],
        "rightOnly": [x for x in b if x not in a],
        "inCommon": [x for x in a if x in b],
    }


@register("apoc.diff.strings")
def diff_strings(a, b):
    if a is None or b is None:
        return None
    a, b = str(a), str(b)
    prefix = 0
    while prefix < min(len(a), len(b)) and a[prefix] == b[prefix]:
        prefix += 1
    suffix = 0
    while (
        suffix < min(len(a), len(b)) - prefix
        and a[len(a) - 1 - suffix] == b[len(b) - 1 - suffix]
    ):
        suffix += 1
    return {
        "equal": a == b,
        "commonPrefix": a[:prefix],
        "commonSuffix": a[len(a) - suffix :] if suffix else "",
        "leftDelta": a[prefix : len(a) - suffix],
        "rightDelta": b[prefix : len(b) - suffix],
    }


# ---------------------------------------------------------------------------
# apoc.stats.* (ref: apoc/stats/stats.go — Mean/Median/StdDev/…/Histogram)
# ---------------------------------------------------------------------------


def _nums(xs) -> list[float]:
    return [float(x) for x in (xs or []) if x is not None]


@register("apoc.stats.mean")
def stats_mean(xs):
    v = _nums(xs)
    return statistics.fmean(v) if v else None


@register("apoc.stats.median")
def stats_median(xs):
    v = _nums(xs)
    return statistics.median(v) if v else None


@register("apoc.stats.mode")
def stats_mode(xs):
    v = _nums(xs)
    return statistics.mode(v) if v else None


@register("apoc.stats.stdev")
def stats_stdev(xs, population=False):
    v = _nums(xs)
    if len(v) < 2:
        return 0.0 if v else None
    return statistics.pstdev(v) if population else statistics.stdev(v)


@register("apoc.stats.variance")
def stats_variance(xs, population=False):
    v = _nums(xs)
    if len(v) < 2:
        return 0.0 if v else None
    return statistics.pvariance(v) if population else statistics.variance(v)


@register("apoc.stats.percentile")
def stats_percentile(xs, p):
    """Linear-interpolation percentile, p in [0,1] or [0,100]."""
    v = sorted(_nums(xs))
    if not v:
        return None
    p = float(p)
    if p > 1.0:
        p /= 100.0
    idx = p * (len(v) - 1)
    lo, hi = int(_math.floor(idx)), int(_math.ceil(idx))
    if lo == hi:
        return v[lo]
    return v[lo] + (v[hi] - v[lo]) * (idx - lo)


@register("apoc.stats.quartiles")
def stats_quartiles(xs):
    v = _nums(xs)
    if not v:
        return None
    return {
        "q1": stats_percentile(v, 0.25),
        "q2": stats_percentile(v, 0.5),
        "q3": stats_percentile(v, 0.75),
    }


@register("apoc.stats.iqr")
def stats_iqr(xs):
    q = stats_quartiles(xs)
    return None if q is None else q["q3"] - q["q1"]


@register("apoc.stats.zscore")
def stats_zscore(xs):
    v = _nums(xs)
    if len(v) < 2:
        return [0.0] * len(v)
    mu, sd = statistics.fmean(v), statistics.pstdev(v)
    if sd == 0:
        return [0.0] * len(v)
    return [(x - mu) / sd for x in v]


@register("apoc.stats.normalize")
def stats_normalize(xs):
    """Min-max normalize into [0,1]."""
    v = _nums(xs)
    if not v:
        return []
    lo, hi = min(v), max(v)
    if hi == lo:
        return [0.0] * len(v)
    return [(x - lo) / (hi - lo) for x in v]


@register("apoc.stats.skewness")
def stats_skewness(xs):
    v = _nums(xs)
    if len(v) < 3:
        return None
    mu, sd = statistics.fmean(v), statistics.pstdev(v)
    if sd == 0:
        return 0.0
    return sum(((x - mu) / sd) ** 3 for x in v) / len(v)


@register("apoc.stats.kurtosis")
def stats_kurtosis(xs):
    """Excess kurtosis (normal -> 0)."""
    v = _nums(xs)
    if len(v) < 4:
        return None
    mu, sd = statistics.fmean(v), statistics.pstdev(v)
    if sd == 0:
        return 0.0
    return sum(((x - mu) / sd) ** 4 for x in v) / len(v) - 3.0


@register("apoc.stats.correlation")
def stats_correlation(xs, ys):
    a, b = _nums(xs), _nums(ys)
    if len(a) != len(b) or len(a) < 2:
        return None
    try:
        return statistics.correlation(a, b)
    except statistics.StatisticsError:
        return None


@register("apoc.stats.covariance")
def stats_covariance(xs, ys):
    a, b = _nums(xs), _nums(ys)
    if len(a) != len(b) or len(a) < 2:
        return None
    return statistics.covariance(a, b)


@register("apoc.stats.histogram")
def stats_histogram(xs, bins=10):
    v = _nums(xs)
    if not v:
        return []
    lo, hi = min(v), max(v)
    bins = max(1, int(bins))
    width = (hi - lo) / bins or 1.0
    counts = [0] * bins
    for x in v:
        idx = min(int((x - lo) / width), bins - 1)
        counts[idx] += 1
    return [
        {"min": lo + i * width, "max": lo + (i + 1) * width, "count": c}
        for i, c in enumerate(counts)
    ]


@register("apoc.stats.outliers")
def stats_outliers(xs):
    """IQR-fence outliers (ref stats.go Outliers)."""
    v = _nums(xs)
    q = stats_quartiles(v)
    if q is None:
        return []
    iqr = q["q3"] - q["q1"]
    lo, hi = q["q1"] - 1.5 * iqr, q["q3"] + 1.5 * iqr
    return [x for x in v if x < lo or x > hi]


@register("apoc.stats.summary")
def stats_summary(xs):
    v = _nums(xs)
    if not v:
        return None
    return {
        "count": len(v),
        "min": min(v),
        "max": max(v),
        "sum": sum(v),
        "mean": statistics.fmean(v),
        "median": statistics.median(v),
        "stdev": statistics.pstdev(v) if len(v) > 1 else 0.0,
    }


# ---------------------------------------------------------------------------
# apoc.spatial.* (ref: apoc/spatial/spatial.go — haversine/bearing/geohash)
# ---------------------------------------------------------------------------

_EARTH_R_M = 6371008.8  # mean earth radius, meters


def _latlon(p) -> tuple[float, float]:
    if isinstance(p, dict):
        return float(p.get("latitude", p.get("lat", 0.0))), float(
            p.get("longitude", p.get("lon", p.get("lng", 0.0)))
        )
    lat, lon = p
    return float(lat), float(lon)


@register("apoc.spatial.distance")
def spatial_distance(p1, p2):
    """Haversine great-circle distance in meters."""
    if p1 is None or p2 is None:
        return None
    lat1, lon1 = _latlon(p1)
    lat2, lon2 = _latlon(p2)
    phi1, phi2 = _math.radians(lat1), _math.radians(lat2)
    dphi = _math.radians(lat2 - lat1)
    dlam = _math.radians(lon2 - lon1)
    a = (
        _math.sin(dphi / 2) ** 2
        + _math.cos(phi1) * _math.cos(phi2) * _math.sin(dlam / 2) ** 2
    )
    return 2 * _EARTH_R_M * _math.asin(_math.sqrt(a))


@register("apoc.spatial.bearing")
def spatial_bearing(p1, p2):
    """Initial bearing in degrees [0, 360)."""
    if p1 is None or p2 is None:
        return None
    lat1, lon1 = _latlon(p1)
    lat2, lon2 = _latlon(p2)
    phi1, phi2 = _math.radians(lat1), _math.radians(lat2)
    dlam = _math.radians(lon2 - lon1)
    y = _math.sin(dlam) * _math.cos(phi2)
    x = _math.cos(phi1) * _math.sin(phi2) - _math.sin(phi1) * _math.cos(
        phi2
    ) * _math.cos(dlam)
    return (_math.degrees(_math.atan2(y, x)) + 360.0) % 360.0


@register("apoc.spatial.destination")
def spatial_destination(p, distance_m, bearing_deg):
    if p is None:
        return None
    lat, lon = _latlon(p)
    phi1, lam1 = _math.radians(lat), _math.radians(lon)
    delta = float(distance_m) / _EARTH_R_M
    theta = _math.radians(float(bearing_deg))
    phi2 = _math.asin(
        _math.sin(phi1) * _math.cos(delta)
        + _math.cos(phi1) * _math.sin(delta) * _math.cos(theta)
    )
    lam2 = lam1 + _math.atan2(
        _math.sin(theta) * _math.sin(delta) * _math.cos(phi1),
        _math.cos(delta) - _math.sin(phi1) * _math.sin(phi2),
    )
    return {
        "latitude": _math.degrees(phi2),
        "longitude": (_math.degrees(lam2) + 540.0) % 360.0 - 180.0,
    }


@register("apoc.spatial.midpoint")
def spatial_midpoint(p1, p2):
    if p1 is None or p2 is None:
        return None
    d = spatial_distance(p1, p2)
    b = spatial_bearing(p1, p2)
    return spatial_destination(p1, d / 2.0, b)


@register("apoc.spatial.boundingBox")
def spatial_bbox(points):
    pts = [_latlon(p) for p in (points or []) if p is not None]
    if not pts:
        return None
    lats = [p[0] for p in pts]
    lons = [p[1] for p in pts]
    return {
        "minLatitude": min(lats),
        "maxLatitude": max(lats),
        "minLongitude": min(lons),
        "maxLongitude": max(lons),
    }


@register("apoc.spatial.within")
def spatial_within(p, bbox):
    if p is None or bbox is None:
        return None
    lat, lon = _latlon(p)
    return (
        bbox["minLatitude"] <= lat <= bbox["maxLatitude"]
        and bbox["minLongitude"] <= lon <= bbox["maxLongitude"]
    )


@register("apoc.spatial.withinDistance")
def spatial_within_distance(p1, p2, max_m):
    d = spatial_distance(p1, p2)
    return None if d is None else d <= float(max_m)


@register("apoc.spatial.centroid")
def spatial_centroid(points):
    pts = [_latlon(p) for p in (points or []) if p is not None]
    if not pts:
        return None
    return {
        "latitude": sum(p[0] for p in pts) / len(pts),
        "longitude": sum(p[1] for p in pts) / len(pts),
    }


_GEOHASH32 = "0123456789bcdefghjkmnpqrstuvwxyz"


@register("apoc.spatial.encodeGeohash")
def spatial_encode_geohash(p, precision=9):
    if p is None:
        return None
    lat, lon = _latlon(p)
    lat_rng, lon_rng = [-90.0, 90.0], [-180.0, 180.0]
    out, bits, ch, even = [], 0, 0, True
    while len(out) < int(precision):
        rng, v = (lon_rng, lon) if even else (lat_rng, lat)
        mid = (rng[0] + rng[1]) / 2
        ch <<= 1
        if v >= mid:
            ch |= 1
            rng[0] = mid
        else:
            rng[1] = mid
        even = not even
        bits += 1
        if bits == 5:
            out.append(_GEOHASH32[ch])
            bits, ch = 0, 0
    return "".join(out)


@register("apoc.spatial.decodeGeohash")
def spatial_decode_geohash(gh):
    if not gh:
        return None
    lat_rng, lon_rng = [-90.0, 90.0], [-180.0, 180.0]
    even = True
    for c in str(gh).lower():
        idx = _GEOHASH32.find(c)
        if idx < 0:
            return None
        for bit in (16, 8, 4, 2, 1):
            rng = lon_rng if even else lat_rng
            mid = (rng[0] + rng[1]) / 2
            if idx & bit:
                rng[0] = mid
            else:
                rng[1] = mid
            even = not even
    return {
        "latitude": (lat_rng[0] + lat_rng[1]) / 2,
        "longitude": (lon_rng[0] + lon_rng[1]) / 2,
    }


# ---------------------------------------------------------------------------
# apoc.scoring.* (ref: apoc/scoring/scoring.go — similarity + rank metrics)
# ---------------------------------------------------------------------------


@register("apoc.scoring.existence")
def scoring_existence(score, exists):
    """(ref scoring.go Existence) score if exists else 0."""
    return float(score) if exists else 0.0


@register("apoc.scoring.pareto")
def scoring_pareto(minimum_threshold, eighty_percent_value, maximum_value, score):
    """(ref scoring.go Pareto) 80/20 cumulative-exponential scoring."""
    score = float(score)
    if score < float(minimum_threshold):
        return 0.0
    k = _math.log(5.0) / float(eighty_percent_value)
    return float(maximum_value) * (1.0 - _math.exp(-k * score))


@register("apoc.scoring.cosine")
def scoring_cosine(a, b):
    a, b = _nums(a), _nums(b)
    if len(a) != len(b) or not a:
        return None
    dot = sum(x * y for x, y in zip(a, b))
    na = _math.sqrt(sum(x * x for x in a))
    nb = _math.sqrt(sum(y * y for y in b))
    if na == 0 or nb == 0:
        return 0.0
    return dot / (na * nb)


@register("apoc.scoring.euclidean")
def scoring_euclidean(a, b):
    a, b = _nums(a), _nums(b)
    if len(a) != len(b) or not a:
        return None
    return _math.sqrt(sum((x - y) ** 2 for x, y in zip(a, b)))


@register("apoc.scoring.manhattan")
def scoring_manhattan(a, b):
    a, b = _nums(a), _nums(b)
    if len(a) != len(b) or not a:
        return None
    return sum(abs(x - y) for x, y in zip(a, b))


@register("apoc.scoring.jaccard")
def scoring_jaccard(a, b):
    sa, sb = set(a or []), set(b or [])
    if not sa and not sb:
        return 1.0
    return len(sa & sb) / len(sa | sb)


@register("apoc.scoring.overlap")
def scoring_overlap(a, b):
    sa, sb = set(a or []), set(b or [])
    denom = min(len(sa), len(sb))
    return len(sa & sb) / denom if denom else 0.0


@register("apoc.scoring.dice")
def scoring_dice(a, b):
    sa, sb = set(a or []), set(b or [])
    if not sa and not sb:
        return 1.0
    return 2 * len(sa & sb) / (len(sa) + len(sb))


@register("apoc.scoring.pearson")
def scoring_pearson(a, b):
    return stats_correlation(a, b)


@register("apoc.scoring.sigmoid")
def scoring_sigmoid(x):
    return None if x is None else 1.0 / (1.0 + _math.exp(-float(x)))


@register("apoc.scoring.softmax")
def scoring_softmax(xs):
    v = _nums(xs)
    if not v:
        return []
    m = max(v)
    exps = [_math.exp(x - m) for x in v]
    s = sum(exps)
    return [e / s for e in exps]


@register("apoc.scoring.minMax")
def scoring_minmax(xs):
    return stats_normalize(xs)


@register("apoc.scoring.rank")
def scoring_rank(xs, descending=True):
    """1-based ranks; ties share the lower rank."""
    v = _nums(xs)
    order = sorted(v, reverse=bool(descending))
    return [order.index(x) + 1 for x in v]


@register("apoc.scoring.topK")
def scoring_topk(xs, k):
    v = _nums(xs)
    return sorted(v, reverse=True)[: int(k)]


@register("apoc.scoring.tfidf")
def scoring_tfidf(term_count, doc_len, n_docs, docs_with_term):
    """tf * idf with smooth idf (ref scoring.go TFIDF)."""
    if not doc_len or not n_docs:
        return 0.0
    tf = float(term_count) / float(doc_len)
    idf = _math.log((1.0 + float(n_docs)) / (1.0 + float(docs_with_term))) + 1.0
    return tf * idf


# ---------------------------------------------------------------------------
# apoc.xml.* (ref: apoc/xml/xml.go — Parse/ToMap/ToJson/escape helpers)
# ---------------------------------------------------------------------------


def _xml_to_map(el: _ET.Element) -> dict:
    out: dict[str, Any] = {"_type": el.tag}
    if el.attrib:
        out.update(el.attrib)
    text = (el.text or "").strip()
    if text:
        out["_text"] = text
    children = [_xml_to_map(c) for c in el]
    if children:
        out["_children"] = children
    return out


@register("apoc.xml.parse")
def xml_parse(s):
    """XML string -> nested map {_type, attrs..., _text, _children}."""
    if s is None:
        return None
    try:
        return _xml_to_map(_ET.fromstring(s))
    except _ET.ParseError:
        return None


@register("apoc.xml.validate")
def xml_validate(s):
    if s is None:
        return False
    try:
        _ET.fromstring(s)
        return True
    except _ET.ParseError:
        return False


@register("apoc.xml.toJson")
def xml_to_json(s):
    m = xml_parse(s)
    return None if m is None else _json.dumps(m)


@register("apoc.xml.escape")
def xml_escape(s):
    if s is None:
        return None
    return (
        str(s)
        .replace("&", "&amp;")
        .replace("<", "&lt;")
        .replace(">", "&gt;")
        .replace('"', "&quot;")
        .replace("'", "&apos;")
    )


@register("apoc.xml.unescape")
def xml_unescape(s):
    if s is None:
        return None
    return (
        str(s)
        .replace("&apos;", "'")
        .replace("&quot;", '"')
        .replace("&gt;", ">")
        .replace("&lt;", "<")
        .replace("&amp;", "&")
    )


@register("apoc.xml.getAttribute")
def xml_get_attribute(s, tag, attr):
    if s is None:
        return None
    try:
        root = _ET.fromstring(s)
    except _ET.ParseError:
        return None
    if root.tag == tag and attr in root.attrib:
        return root.attrib[attr]
    el = root.find(f".//{tag}")
    return el.attrib.get(attr) if el is not None else None


@register("apoc.xml.getText")
def xml_get_text(s, tag):
    if s is None:
        return None
    try:
        root = _ET.fromstring(s)
    except _ET.ParseError:
        return None
    el = root if root.tag == tag else root.find(f".//{tag}")
    return (el.text or "").strip() if el is not None else None


# ---------------------------------------------------------------------------
# apoc.agg.* gaps (ref: apoc/agg — Nth/Slice/Mode/MinItems/MaxItems/
# Frequencies; the rest live in functions.py)
# ---------------------------------------------------------------------------


@register("apoc.agg.nth", category="agg")
def agg_nth(xs, offset):
    xs = list(xs or [])
    i = int(offset)
    return xs[i] if -len(xs) <= i < len(xs) else None


@register("apoc.agg.slice", category="agg")
def agg_slice(xs, start=0, length=None):
    xs = list(xs or [])
    start = int(start)
    if length is None:
        return xs[start:]
    return xs[start : start + int(length)]


def _agg_key(v: Any) -> Any:
    """Canonical hashable key for Cypher values. Type-tagged so a string
    never collides with a structurally-equal serialized list/map and
    booleans stay distinct from 0/1 (Cypher equality treats 1 = 1.0 as
    equal, so plain numbers share a key)."""
    if isinstance(v, bool):
        return ("bool", v)
    if isinstance(v, (list, dict)):
        return ("json", _json.dumps(v, sort_keys=True, default=str))
    if isinstance(v, str):
        return ("str", v)
    return ("val", v)


@register("apoc.agg.mode", category="agg")
def agg_mode(xs):
    xs = [x for x in (xs or []) if x is not None]
    if not xs:
        return None
    counts: dict[Any, int] = {}
    for x in xs:
        k = _agg_key(x)
        counts[k] = counts.get(k, 0) + 1
    best = max(counts.values())
    # deterministic: first value reaching the max count
    for x in xs:
        if counts[_agg_key(x)] == best:
            return x
    return None


@register("apoc.agg.minItems", category="agg")
def agg_min_items(items, values=None):
    """All items tied for the minimum value. One-arg form reduces the list
    itself; two-arg form pairs items with their sort values."""
    items = list(items or [])
    vals = list(values) if values is not None else items
    pairs = [(v, i) for i, v in zip(items, vals) if v is not None]
    if not pairs:
        return {"value": None, "items": []}
    lo = min(p[0] for p in pairs)
    return {"value": lo, "items": [i for v, i in pairs if v == lo]}


@register("apoc.agg.maxItems", category="agg")
def agg_max_items(items, values=None):
    items = list(items or [])
    vals = list(values) if values is not None else items
    pairs = [(v, i) for i, v in zip(items, vals) if v is not None]
    if not pairs:
        return {"value": None, "items": []}
    hi = max(p[0] for p in pairs)
    return {"value": hi, "items": [i for v, i in pairs if v == hi]}


@register("apoc.agg.frequencies", category="agg")
def agg_frequencies(xs):
    counts: dict[Any, int] = {}
    order: list[tuple[Any, Any]] = []  # (key, original value)
    for x in xs or []:
        k = _agg_key(x)
        if k not in counts:
            order.append((k, x))
        counts[k] = counts.get(k, 0) + 1
    return [{"item": x, "count": counts[k]} for k, x in order]


# ---------------------------------------------------------------------------
# apoc.util.* gaps (ref: apoc/util/util.go — sleep/validate/compress/
# base64/url/timestamps; md5/sha* live in functions.py)
# ---------------------------------------------------------------------------


@register("apoc.util.sleep")
def util_sleep(ms):
    """Capped at 10s: an unbounded sleep inside a query is a DoS lever
    (the reference sleeps uncapped; deliberate deviation)."""
    import time as _t

    _t.sleep(min(max(float(ms or 0), 0.0), 10_000.0) / 1000.0)
    return None


@register("apoc.util.validate")
def util_validate(predicate, message, params=None):
    """Raise with `message` when predicate is truthy (ref util.go Validate:
    used for inline assertions in write queries)."""
    if predicate:
        msg = str(message or "validation failed")
        for i, p in enumerate(params or []):
            msg = msg.replace("%s", str(p), 1).replace(f"{{{i}}}", str(p))
        raise ValueError(msg)
    return None


@register("apoc.util.compress")
def util_compress(s, config=None):
    import gzip as _gzip

    if s is None:
        return None
    return list(_gzip.compress(str(s).encode("utf-8")))


@register("apoc.util.decompress")
def util_decompress(data, config=None):
    import gzip as _gzip

    if data is None:
        return None
    return _gzip.decompress(bytes(bytearray(int(b) & 0xFF for b in data))).decode("utf-8")


# base64/url codecs already exist as apoc.text.*; register the util names
# as aliases of the SAME functions so a fix in one spelling reaches both
from nornicdb_tpu.apoc.functions import (  # noqa: E402
    text_b64,
    text_unb64,
    text_urldecode,
    text_urlencode,
)

register("apoc.util.encodeBase64")(text_b64)
register("apoc.util.decodeBase64")(text_unb64)
register("apoc.util.encodeUrl")(text_urlencode)
register("apoc.util.decodeUrl")(text_urldecode)


# ---------------------------------------------------------------------------
# apoc.convert.* gaps (ref: apoc/convert/convert.go — typed lists, sets,
# sorted json, json property helpers)
# ---------------------------------------------------------------------------


@register("apoc.convert.toSet")
def convert_to_set(xs):
    """Dedup preserving first-seen order (apoc returns a list)."""
    if xs is None:
        return None
    seen = set()
    out = []
    for x in xs if isinstance(xs, list) else [xs]:
        k = _agg_key(x)
        if k not in seen:
            seen.add(k)
            out.append(x)
    return out


@register("apoc.convert.toSortedJsonMap")
def convert_sorted_json(v):
    return None if v is None else _json.dumps(v, sort_keys=True, default=str)


def _to_typed_list(xs, cast):
    if xs is None:
        return None
    out = []
    for x in xs if isinstance(xs, list) else [xs]:
        try:
            out.append(None if x is None else cast(x))
        except (TypeError, ValueError):
            out.append(None)
    return out


@register("apoc.convert.toIntList")
def convert_int_list(xs):
    def cast(v):
        try:
            return int(v)  # exact for big ints; int(float()) would round 2^53+
        except (TypeError, ValueError):
            return int(float(v))  # decimal strings like "2.7"
    return _to_typed_list(xs, cast)


@register("apoc.convert.toFloatList")
def convert_float_list(xs):
    return _to_typed_list(xs, float)


@register("apoc.convert.toStringList")
def convert_string_list(xs):
    return _to_typed_list(xs, str)


@register("apoc.convert.toBooleanList")
def convert_bool_list(xs):
    def cast(v):
        if isinstance(v, str):
            return v.lower() in ("true", "yes", "1")
        return bool(v)
    return _to_typed_list(xs, cast)


@register("apoc.convert.getJsonProperty")
def convert_get_json_prop(entity, key, path=None):
    """Parse a JSON-string property and optionally descend a path. Accepts
    a node, a property map, or a raw JSON string (ref convert.go:237 takes
    the JSON string form)."""
    if isinstance(entity, str):
        # reference form: the FIRST arg is the JSON document; the value is
        # returned as-is (no double parse)
        try:
            doc = _json.loads(entity)
        except ValueError:
            return None
        if not isinstance(doc, dict):
            return None
        val = doc.get(key)
        return _json_path(val, str(path)) if path else val
    # node/map form: the property VALUE is a JSON string to parse
    props = getattr(entity, "properties", entity) or {}
    if not isinstance(props, dict):
        return None
    raw = props.get(key)
    if raw is None:
        return None
    try:
        obj = _json.loads(raw) if isinstance(raw, str) else raw
    except ValueError:
        return None
    return _json_path(obj, str(path)) if path else obj


@register("apoc.convert.setJsonProperty")
def convert_set_json_prop(entity, key, value):
    """Serialize value into a JSON-string property. For a node/map input
    the entity is returned; for a raw JSON-string input the updated JSON
    string is returned (ref convert.go SetJsonProperty)."""
    if isinstance(entity, str):
        try:
            obj = _json.loads(entity)
        except ValueError:
            return None
        if not isinstance(obj, dict):
            return None
        obj[key] = value
        return _json.dumps(obj, default=str)
    props = getattr(entity, "properties", entity)
    props[key] = _json.dumps(value, default=str)
    return entity


# ---------------------------------------------------------------------------
# apoc.date.* gaps (ref: apoc/date/date.go — ISO8601 + unix + fields)
# ---------------------------------------------------------------------------


@register("apoc.date.toISO8601")
def date_to_iso(epoch, unit="ms"):
    import datetime as _dt

    if epoch is None:
        return None
    secs = float(epoch) / (1000.0 if unit == "ms" else 1.0)
    return _dt.datetime.fromtimestamp(
        secs, tz=_dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"


@register("apoc.date.fromISO8601")
def date_from_iso(s):
    import datetime as _dt

    if s is None:
        return None
    s = str(s).replace("Z", "+00:00")
    dt = _dt.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


@register("apoc.date.toUnixTime")
def date_to_unix(epoch_ms):
    return None if epoch_ms is None else int(float(epoch_ms) / 1000.0)


@register("apoc.date.fromUnixTime")
def date_from_unix(secs):
    return None if secs is None else int(float(secs) * 1000.0)


@register("apoc.date.field")
def date_field(epoch_ms, unit="d"):
    """Extract a field from an epoch-ms timestamp (UTC)."""
    import datetime as _dt

    if epoch_ms is None:
        return None
    dt = _dt.datetime.fromtimestamp(float(epoch_ms) / 1000.0,
                                    tz=_dt.timezone.utc)
    unit = str(unit).lower()
    return {
        "years": dt.year, "year": dt.year, "y": dt.year,
        "months": dt.month, "month": dt.month,
        "days": dt.day, "day": dt.day, "d": dt.day,
        "hours": dt.hour, "hour": dt.hour, "h": dt.hour,
        # 'm' means MINUTES (ref date.go duration units), not month
        "minutes": dt.minute, "minute": dt.minute, "m": dt.minute,
        "seconds": dt.second, "second": dt.second, "s": dt.second,
    }.get(unit)


@register("apoc.date.fields")
def date_fields(epoch_ms):
    import datetime as _dt

    if epoch_ms is None:
        return None
    dt = _dt.datetime.fromtimestamp(float(epoch_ms) / 1000.0,
                                    tz=_dt.timezone.utc)
    # key names follow the reference's Fields map (date.go:80)
    return {"year": dt.year, "month": dt.month, "day": dt.day,
            "hour": dt.hour, "minute": dt.minute, "second": dt.second,
            "dayOfWeek": dt.isoweekday(),
            "dayOfYear": dt.timetuple().tm_yday,
            "weekOfYear": dt.isocalendar()[1]}


# ---------------------------------------------------------------------------
# apoc.temporal.* gaps (ref: apoc/temporal/temporal.go — epoch-ms calendar
# helpers: StartOf/EndOf/IsWeekend/Quarter/IsLeapYear/DaysInMonth/
# Difference/Age; apoc.temporal.format lives in functions.py)
# ---------------------------------------------------------------------------


def _dt_utc(epoch_ms):
    import datetime as _dt

    return _dt.datetime.fromtimestamp(float(epoch_ms) / 1000.0,
                                      tz=_dt.timezone.utc)


@register("apoc.temporal.startOf")
def temporal_start_of(epoch_ms, unit="day"):
    import datetime as _dt

    if epoch_ms is None:
        return None
    dt = _dt_utc(epoch_ms)
    unit = str(unit).lower()
    if unit in ("year", "years"):
        dt = dt.replace(month=1, day=1, hour=0, minute=0, second=0,
                        microsecond=0)
    elif unit in ("month", "months"):
        dt = dt.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    elif unit in ("week", "weeks"):
        dt = (dt - _dt.timedelta(days=dt.isoweekday() - 1)).replace(
            hour=0, minute=0, second=0, microsecond=0)
    elif unit in ("day", "days"):
        dt = dt.replace(hour=0, minute=0, second=0, microsecond=0)
    elif unit in ("hour", "hours"):
        dt = dt.replace(minute=0, second=0, microsecond=0)
    elif unit in ("minute", "minutes"):
        dt = dt.replace(second=0, microsecond=0)
    else:
        return None
    return int(dt.timestamp() * 1000)


def _next_period_start(dt, unit: str):
    """Start of the FOLLOWING unit period; shared by endOf so the unit
    dispatch can never drift from startOf's. Returns None for unknown
    units (same contract as startOf)."""
    import datetime as _dt

    if unit in ("year", "years"):
        return dt.replace(year=dt.year + 1)
    if unit in ("month", "months"):
        return (dt.replace(year=dt.year + 1, month=1) if dt.month == 12
                else dt.replace(month=dt.month + 1))
    if unit in ("week", "weeks"):
        return dt + _dt.timedelta(days=7)
    if unit in ("day", "days"):
        return dt + _dt.timedelta(days=1)
    if unit in ("hour", "hours"):
        return dt + _dt.timedelta(hours=1)
    if unit in ("minute", "minutes"):
        return dt + _dt.timedelta(minutes=1)
    return None


@register("apoc.temporal.endOf")
def temporal_end_of(epoch_ms, unit="day"):
    if epoch_ms is None:
        return None
    start = temporal_start_of(epoch_ms, unit)
    if start is None:
        return None
    nxt = _next_period_start(_dt_utc(start), str(unit).lower())
    if nxt is None:
        return None
    return int(nxt.timestamp() * 1000) - 1


@register("apoc.temporal.isWeekend")
def temporal_is_weekend(epoch_ms):
    return None if epoch_ms is None else _dt_utc(epoch_ms).isoweekday() >= 6


@register("apoc.temporal.isWeekday")
def temporal_is_weekday(epoch_ms):
    return None if epoch_ms is None else _dt_utc(epoch_ms).isoweekday() <= 5


@register("apoc.temporal.quarter")
def temporal_quarter(epoch_ms):
    if epoch_ms is None:
        return None
    return (_dt_utc(epoch_ms).month - 1) // 3 + 1


@register("apoc.temporal.isLeapYear")
def temporal_is_leap(year):
    import calendar

    return None if year is None else calendar.isleap(int(year))


@register("apoc.temporal.daysInMonth")
def temporal_days_in_month(year, month):
    import calendar

    if year is None or month is None:
        return None
    return calendar.monthrange(int(year), int(month))[1]


@register("apoc.temporal.difference")
def temporal_difference(a_ms, b_ms, unit="ms"):
    """Signed difference b - a, truncated toward zero (ref temporal.go
    Difference — the sign tells callers which side is later). months/years
    use the reference's fixed 30/365-day approximations."""
    if a_ms is None or b_ms is None:
        return None
    diff = float(b_ms) - float(a_ms)
    divisors = {
        "ms": 1.0,
        "s": 1e3, "second": 1e3, "seconds": 1e3,
        "m": 6e4, "minute": 6e4, "minutes": 6e4,
        "h": 3.6e6, "hour": 3.6e6, "hours": 3.6e6,
        "d": 8.64e7, "day": 8.64e7, "days": 8.64e7,
        "month": 30 * 8.64e7, "months": 30 * 8.64e7,
        "year": 365 * 8.64e7, "years": 365 * 8.64e7,
    }
    div = divisors.get(str(unit).lower())
    return None if div is None else int(diff / div)


@register("apoc.temporal.age")
def temporal_age(birth_ms, now_ms=None):
    """Whole years between birth and now (calendar-aware)."""
    if birth_ms is None:
        return None
    import time as _t

    b = _dt_utc(birth_ms)
    n = _dt_utc(now_ms if now_ms is not None else _t.time() * 1000.0)
    years = n.year - b.year
    if (n.month, n.day) < (b.month, b.day):
        years -= 1
    return years


# ---------------------------------------------------------------------------
# apoc.map.* gaps (ref: apoc/map/map.go — FromValues/SetEntry/SetPairs/
# SetLists/SetValues/MGet/Keys/Unflatten/UpdateTree/DropNullValues)
# ---------------------------------------------------------------------------


@register("apoc.map.fromValues")
def map_from_values(xs):
    """Alternating [k1, v1, k2, v2, ...] -> map."""
    xs = list(xs or [])
    return {str(xs[i]): xs[i + 1] for i in range(0, len(xs) - 1, 2)}


# setEntry is the reference's alias for SetKey — register the SAME function
from nornicdb_tpu.apoc.functions import map_set_key as _map_set_key  # noqa: E402

register("apoc.map.setEntry")(_map_set_key)


@register("apoc.map.setPairs")
def map_set_pairs(m, pairs):
    out = dict(m or {})
    for pair in pairs or []:
        if isinstance(pair, (list, tuple)) and len(pair) >= 2:
            out[str(pair[0])] = pair[1]
    return out


@register("apoc.map.setLists")
def map_set_lists(m, keys, values):
    out = dict(m or {})
    for k, v in zip(keys or [], values or []):
        out[str(k)] = v
    return out


@register("apoc.map.setValues")
def map_set_values(m, xs):
    """Alternating [k1, v1, ...] merged into m."""
    out = dict(m or {})
    xs = list(xs or [])
    for i in range(0, len(xs) - 1, 2):
        out[str(xs[i])] = xs[i + 1]
    return out


@register("apoc.map.mget")
def map_mget(m, keys, default=None):
    m = m or {}
    return [m.get(str(k), default) for k in keys or []]


@register("apoc.map.keys")
def map_keys(m):
    return sorted((m or {}).keys())  # ref map.go Keys sorts


@register("apoc.map.unflatten")
def map_unflatten(m, delimiter="."):
    """{"a.b": 1} -> {"a": {"b": 1}} (inverse of apoc.map.flatten)."""
    out: dict[str, Any] = {}
    for k, v in (m or {}).items():
        parts = str(k).split(delimiter)
        cur = out
        for p in parts[:-1]:
            nxt = cur.get(p)
            if not isinstance(nxt, dict):
                nxt = {}
                cur[p] = nxt
            cur = nxt
        cur[parts[-1]] = v
    return out


@register("apoc.map.updateTree")
def map_update_tree(m, path, value):
    """Set a value at a dot-delimited path, creating intermediate maps
    (ref map.go UpdateTree). Non-map intermediates are replaced rather
    than panicking like the reference's type assertion."""
    out = dict(m or {})
    parts = str(path).split(".")
    cur = out
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
        else:
            nxt = dict(nxt)  # copy-on-write down the path
        cur[p] = nxt
        cur = nxt
    cur[parts[-1]] = value
    return out


@register("apoc.map.dropNullValues")
def map_drop_nulls(m):
    return {k: v for k, v in (m or {}).items() if v is not None}


# ---------------------------------------------------------------------------
# apoc.coll.* gaps (ref: apoc/coll/coll.go — ContainsAny/Sorted, Different,
# Disjunction, DuplicatesWithCount, InsertAll, IsEmpty/IsNotEmpty,
# PairsMin, RemoveAll, Set, Slice, SortMaps, UnionAll, FrequenciesAsMap)
# ---------------------------------------------------------------------------


@register("apoc.coll.containsAny")
def coll_contains_any(xs, candidates):
    if xs is None or candidates is None:
        return None
    keys = {_agg_key(x) for x in xs}
    return any(_agg_key(c) in keys for c in candidates)


@register("apoc.coll.containsSorted")
def coll_contains_sorted(xs, value):
    """Binary search over an already-sorted list (ref coll.go
    ContainsSorted). A probe that isn't order-comparable with the
    elements is simply not contained."""
    import bisect

    if xs is None:
        return None
    try:
        i = bisect.bisect_left(xs, value)
    except TypeError:
        return False
    return i < len(xs) and xs[i] == value


@register("apoc.coll.different")
def coll_different(a, b):
    """Elements of the first list absent from the second (ref coll.go
    Different(list1, list2) — a list difference, not a predicate)."""
    if a is None:
        return None
    kb = {_agg_key(x) for x in (b or [])}
    return [x for x in a if _agg_key(x) not in kb]


@register("apoc.coll.disjunction")
def coll_disjunction(a, b):
    """Symmetric difference, first-seen order."""
    a, b = list(a or []), list(b or [])
    ka = {_agg_key(x) for x in a}
    kb = {_agg_key(x) for x in b}
    out = []
    emitted: set[Any] = set()  # result is a SET (ref applies ToSet)
    for x in a:
        k = _agg_key(x)
        if k not in kb and k not in emitted:
            emitted.add(k)
            out.append(x)
    for x in b:
        k = _agg_key(x)
        if k not in ka and k not in emitted:
            emitted.add(k)
            out.append(x)
    return out


@register("apoc.coll.duplicatesWithCount")
def coll_dupes_with_count(xs):
    counts: dict[Any, int] = {}
    order: list[tuple[Any, Any]] = []
    for x in xs or []:
        k = _agg_key(x)
        if k not in counts:
            order.append((k, x))
        counts[k] = counts.get(k, 0) + 1
    return [{"item": x, "count": counts[k]} for k, x in order if counts[k] > 1]


@register("apoc.coll.insertAll")
def coll_insert_all(xs, index, values):
    xs = list(xs or [])
    i = int(index)
    if not 0 <= i <= len(xs):
        return xs  # out-of-range is a no-op (ref + coll.set convention)
    return xs[:i] + list(values or []) + xs[i:]


@register("apoc.coll.isEmpty")
def coll_is_empty(xs):
    return None if xs is None else len(xs) == 0


@register("apoc.coll.isNotEmpty")
def coll_is_not_empty(xs):
    return None if xs is None else len(xs) > 0


@register("apoc.coll.pairsMin")
def coll_pairs_min(xs):
    """NON-overlapping pairs, stepping by two; a trailing odd element is
    dropped (ref coll.go PairsMin i += 2)."""
    xs = list(xs or [])
    return [[xs[i], xs[i + 1]] for i in range(0, len(xs) - 1, 2)]


@register("apoc.coll.removeAll")
def coll_remove_all(xs, to_remove):
    kill = {_agg_key(x) for x in (to_remove or [])}
    return [x for x in (xs or []) if _agg_key(x) not in kill]


@register("apoc.coll.set")
def coll_set(xs, index, value):
    xs = list(xs or [])
    i = int(index)
    if 0 <= i < len(xs):
        xs[i] = value
    return xs


@register("apoc.coll.slice")
def coll_slice(xs, offset, length=None):
    xs = list(xs or [])
    off = max(0, int(offset))
    if length is None:
        return xs[off:]
    return xs[off : off + max(0, int(length))]


@register("apoc.coll.sortMaps")
def coll_sort_maps(maps, key, descending=False):
    """Sort a list of maps by a key, ASCENDING like the reference
    (coll.go SortMaps has no direction param); null-valued entries sort
    last. The optional descending flag is a convenience superset."""
    maps = list(maps or [])
    with_val = [m for m in maps if isinstance(m, dict) and m.get(key) is not None]
    without = [m for m in maps if not (isinstance(m, dict) and m.get(key) is not None)]
    # heterogeneous property values are normal graph data: sort within
    # type groups (type-tagged key) instead of raising TypeError
    def sort_key(m):
        v = m[key]
        if isinstance(v, bool):
            return (0, v)
        if isinstance(v, (int, float)):
            return (1, v)
        if isinstance(v, str):
            return (2, v)
        return (3, str(v))
    with_val.sort(key=sort_key, reverse=bool(descending))
    return with_val + without


@register("apoc.coll.unionAll")
def coll_union_all(a, b):
    """Concatenation keeping duplicates (union() dedups)."""
    return list(a or []) + list(b or [])


@register("apoc.coll.frequenciesAsMap")
def coll_frequencies_as_map(xs):
    """List of {item, count} rows, exactly the reference's shape
    (coll.go FrequenciesAsMap returns []map, not a dict — the name is
    historical)."""
    from nornicdb_tpu.apoc.functions import coll_frequencies

    return coll_frequencies(xs)


# ---------------------------------------------------------------------------
# apoc.text.* gaps (ref: apoc/text/text.go — CapitalizeAll/DecapitalizeAll/
# Reverse/Trim family/IndexesOf/FromCodePoint/Bytes/Hamming/JaroWinkler/
# Phonetic/DoubleMetaphone)
# ---------------------------------------------------------------------------


@register("apoc.text.capitalizeAll")
def text_capitalize_all(s):
    # ref text.go CapitalizeAll is strings.ToUpper (not title-case)
    return None if s is None else str(s).upper()


@register("apoc.text.decapitalizeAll")
def text_decapitalize_all(s):
    return None if s is None else str(s).lower()


@register("apoc.text.reverse")
def text_reverse(s):
    return None if s is None else str(s)[::-1]


@register("apoc.text.trim")
def text_trim(s):
    return None if s is None else str(s).strip()


@register("apoc.text.ltrim")
def text_ltrim(s):
    return None if s is None else str(s).lstrip()


@register("apoc.text.rtrim")
def text_rtrim(s):
    return None if s is None else str(s).rstrip()


@register("apoc.text.indexesOf")
def text_indexes_of(s, lookup, from_=0, to=None):
    if s is None or lookup is None:
        return None
    s, lookup = str(s), str(lookup)
    end = len(s) if to is None else int(to)
    out = []
    i = int(from_)
    while True:
        i = s.find(lookup, i, end)
        if i == -1:
            break
        out.append(i)
        i += 1
    return out


@register("apoc.text.fromCodePoint")
def text_from_code_point(*codes):
    vals = codes[0] if len(codes) == 1 and isinstance(codes[0], list) else codes
    return "".join(chr(int(c)) for c in vals)


@register("apoc.text.bytes")
def text_bytes(s, charset="UTF-8"):
    return None if s is None else list(str(s).encode(charset))


@register("apoc.text.bytesToString")
def text_bytes_to_string(data, charset="UTF-8"):
    if data is None:
        return None
    return bytes(bytearray(int(b) & 0xFF for b in data)).decode(charset)


@register("apoc.text.hammingDistance")
def text_hamming(a, b):
    if a is None or b is None:
        return None
    a, b = str(a), str(b)
    if len(a) != len(b):
        return -1  # ref text.go: unequal lengths are invalid, sentinel -1
    return sum(x != y for x, y in zip(a, b))


@register("apoc.text.jaroWinklerDistance")
def text_jaro_winkler(a, b):
    """Jaro-Winkler SIMILARITY in [0,1] (apoc's name says distance but it
    returns similarity, matching the reference)."""
    if a is None or b is None:
        return None
    s1, s2 = str(a), str(b)
    if s1 == s2:
        return 1.0
    if not s1 or not s2:
        return 0.0
    window = max(max(len(s1), len(s2)) // 2 - 1, 1)  # ref clamps to >= 1
    m1, m2 = [False] * len(s1), [False] * len(s2)
    matches = 0
    for i, c in enumerate(s1):
        lo, hi = max(0, i - window), min(len(s2), i + window + 1)
        for j in range(lo, hi):
            if not m2[j] and s2[j] == c:
                m1[i] = m2[j] = True
                matches += 1
                break
    if matches == 0:
        return 0.0
    t = 0
    k = 0
    for i in range(len(s1)):
        if m1[i]:
            while not m2[k]:
                k += 1
            if s1[i] != s2[k]:
                t += 1
            k += 1
    jaro = (matches / len(s1) + matches / len(s2)
            + (matches - t / 2) / matches) / 3.0
    prefix = 0
    for x, y in zip(s1, s2):
        if x != y or prefix == 4:
            break
        prefix += 1
    return jaro + prefix * 0.1 * (1.0 - jaro)


def _soundex(s: str) -> str:
    """Classic Soundex (ref text.go Phonetic)."""
    codes = {
        **dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
        **dict.fromkeys("DT", "3"), "L": "4",
        **dict.fromkeys("MN", "5"), "R": "6",
    }
    s = "".join(c for c in s.upper() if c.isalpha())
    if not s:
        return ""
    out = s[0]
    prev = codes.get(s[0], "")
    for c in s[1:]:
        code = codes.get(c, "")
        if code and code != prev:
            out += code
            if len(out) == 4:
                break
        if c not in "HW":
            prev = code
    return (out + "000")[:4]


@register("apoc.text.phonetic")
def text_phonetic(s):
    if s is None:
        return None
    return "".join(_soundex(w) for w in str(s).split())


@register("apoc.text.phoneticDelta")
def text_phonetic_delta(a, b):
    """0 = identical soundex codes, 4 = different (ref text.go
    PhoneticDelta — a DELTA, so zero means phonetically the same)."""
    if a is None or b is None:
        return None
    return 0 if _soundex(str(a)) == _soundex(str(b)) else 4


# ---------------------------------------------------------------------------
# apoc.number.* gaps (ref: apoc/number/number.go — romanize/arabize, base
# conversions, clamp/lerp, primality, gcd/lcm, factorial, fibonacci)
# ---------------------------------------------------------------------------

_ROMAN = [(1000, "M"), (900, "CM"), (500, "D"), (400, "CD"), (100, "C"),
          (90, "XC"), (50, "L"), (40, "XL"), (10, "X"), (9, "IX"),
          (5, "V"), (4, "IV"), (1, "I")]


@register("apoc.number.romanize")
def number_romanize(n):
    if n is None:
        return None
    n = int(n)
    if not 0 < n < 4000:
        return None
    out = []
    for val, sym in _ROMAN:
        while n >= val:
            out.append(sym)
            n -= val
    return "".join(out)


@register("apoc.number.arabize")
def number_arabize(s):
    if not s:
        return None
    vals = {"I": 1, "V": 5, "X": 10, "L": 50, "C": 100, "D": 500, "M": 1000}
    s = str(s).upper()
    total = 0
    prev = 0  # value of the PREVIOUS char (right-to-left), not a running max
    for c in reversed(s):
        v = vals.get(c)
        if v is None:
            return None
        total += v if v >= prev else -v
        prev = v
    return total


_INT64_MAX = (1 << 63) - 1
_INT64_MIN = -(1 << 63)
_BASE_DIGITS = "0123456789abcdefghijklmnopqrstuvwxyz"


def _parse_int_strict(s, base: int):
    """strconv.ParseInt-shaped parsing: optional sign, strict per-base
    charset (no 0x/0b prefixes, no underscores, no whitespace), int64
    bounds. Returns None on any violation — shared by every from* codec
    so their leniency can never diverge."""
    if s is None:
        return None
    s = str(s)
    body = s[1:] if s[:1] in "+-" else s
    if not body:
        return None
    allowed = set(_BASE_DIGITS[:base])
    if any(c not in allowed for c in body.lower()):
        return None
    v = int(s, base)
    if not _INT64_MIN <= v <= _INT64_MAX:
        return None
    return v


@register("apoc.number.toHex")
def number_to_hex(n):
    # reference uppercases (number.go ToHex: strings.ToUpper)
    return None if n is None else format(int(n), "X")


@register("apoc.number.fromHex")
def number_from_hex(s):
    return _parse_int_strict(s, 16)


@register("apoc.number.toBinary")
def number_to_binary(n):
    return None if n is None else format(int(n), "b")


@register("apoc.number.fromBinary")
def number_from_binary(s):
    return _parse_int_strict(s, 2)


@register("apoc.number.toOctal")
def number_to_octal(n):
    return None if n is None else format(int(n), "o")


@register("apoc.number.fromOctal")
def number_from_octal(s):
    return _parse_int_strict(s, 8)


@register("apoc.number.toBase")
def number_to_base(n, base):
    if n is None or base is None:
        return None
    base = int(base)
    if not 2 <= base <= 36:
        return None
    n = int(n)
    if n == 0:
        return "0"
    neg = n < 0
    n = abs(n)
    out = []
    while n:
        out.append(_BASE_DIGITS[n % base])
        n //= base
    # reference uppercases base-converted output (number.go ToBase)
    return (("-" if neg else "") + "".join(reversed(out))).upper()


@register("apoc.number.fromBase")
def number_from_base(s, base):
    try:
        base = int(base)
    except (TypeError, ValueError):
        return None
    if not 2 <= base <= 36:
        return None
    return _parse_int_strict(s, base)


# ---------------------------------------------------------------------------
# apoc.math.* gaps (ref: apoc/math/math.go — clamp/lerp/gcd/lcm/factorial/
# fibonacci/isPrime/nextPrime/logit and the trig family)
# ---------------------------------------------------------------------------


@register("apoc.math.clamp")
def math_clamp(v, lo, hi):
    if v is None or lo is None or hi is None:
        return None
    return max(float(lo), min(float(hi), float(v)))


@register("apoc.math.lerp")
def math_lerp(a, b, t):
    if a is None or b is None or t is None:
        return None
    return float(a) + (float(b) - float(a)) * float(t)


@register("apoc.math.gcd")
def math_gcd(a, b):
    return None if a is None or b is None else _math.gcd(int(a), int(b))


@register("apoc.math.lcm")
def math_lcm(a, b):
    if a is None or b is None:
        return None
    a, b = int(a), int(b)
    return 0 if a == 0 or b == 0 else abs(a * b) // _math.gcd(a, b)


@register("apoc.math.factorial")
def math_factorial(n):
    if n is None:
        return None
    n = int(n)
    if n <= 1:
        return 1  # ref math.go Factorial: n <= 1 (incl. negatives) -> 1
    if n > 20:
        return None  # 21! overflows int64; the reference silently wraps
    return _math.factorial(n)


@register("apoc.math.fibonacci")
def math_fibonacci(n):
    if n is None or int(n) < 0:
        return None
    a, b = 0, 1
    for _ in range(int(n)):
        a, b = b, a + b
    return a


@register("apoc.math.isPrime")
def math_is_prime(n):
    if n is None:
        return None
    n = int(n)
    if n < 2:
        return False
    if n < 4:
        return True
    if n % 2 == 0:
        return False
    i = 3
    while i * i <= n:
        if n % i == 0:
            return False
        i += 2
    return True


@register("apoc.math.nextPrime")
def math_next_prime(n):
    if n is None:
        return None
    c = int(n) + 1
    while not math_is_prime(c):
        c += 1
    return c


@register("apoc.math.logit")
def math_logit(p):
    if p is None:
        return None
    p = float(p)
    if not 0.0 < p < 1.0:
        return None
    return _math.log(p / (1.0 - p))


# ---------------------------------------------------------------------------
# apoc.hashing.* gaps (ref: apoc/hashing/hashing.go — FNV1a 32/64,
# MurmurHash3 32, JumpHash, ConsistentHash, Fingerprint; md5/sha live in
# functions.py)
# ---------------------------------------------------------------------------

_U32 = 0xFFFFFFFF
_U64 = 0xFFFFFFFFFFFFFFFF


@register("apoc.hashing.fnv1a")
def hashing_fnv1a(s):
    if s is None:
        return None
    h = 0x811C9DC5
    for b in str(s).encode("utf-8"):
        h = ((h ^ b) * 0x01000193) & _U32
    return h


@register("apoc.hashing.fnv1a64")
def hashing_fnv1a64(s):
    if s is None:
        return None
    h = 0xCBF29CE484222325
    for b in str(s).encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & _U64
    return h


@register("apoc.hashing.murmur3")
def hashing_murmur3(s, seed=0):
    """MurmurHash3 x86 32-bit (ref hashing.go murmur3_32)."""
    if s is None:
        return None
    data = str(s).encode("utf-8")
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = int(seed) & _U32
    n_blocks = len(data) // 4
    for i in range(n_blocks):
        k = int.from_bytes(data[i * 4 : i * 4 + 4], "little")
        k = (k * c1) & _U32
        k = ((k << 15) | (k >> 17)) & _U32
        k = (k * c2) & _U32
        h ^= k
        h = ((h << 13) | (h >> 19)) & _U32
        h = (h * 5 + 0xE6546B64) & _U32
    tail = data[n_blocks * 4 :]
    k = 0
    for i, b in enumerate(tail):
        k |= b << (8 * i)
    if tail:
        k = (k * c1) & _U32
        k = ((k << 15) | (k >> 17)) & _U32
        k = (k * c2) & _U32
        h ^= k
    h ^= len(data)
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _U32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _U32
    h ^= h >> 16
    return h


@register("apoc.hashing.jumpHash")
def hashing_jump_hash(key, buckets):
    """Jump consistent hash (ref hashing.go JumpHash — Lamping-Veach).
    String keys are fnv1a64-hashed first."""
    if key is None or buckets is None:
        return None
    buckets = int(buckets)
    if buckets <= 0:
        return None
    k = hashing_fnv1a64(key) if isinstance(key, str) else int(key) & _U64
    b, j = -1, 0
    while j < buckets:
        b = j
        k = (k * 2862933555777941757 + 1) & _U64
        j = int(float(b + 1) * (float(1 << 31) / float((k >> 33) + 1)))
    return b


@register("apoc.hashing.consistentHash")
def hashing_consistent(key, buckets):
    """fnv1a64(key) % buckets -> bucket index (ref hashing.go
    ConsistentHash). For ring-with-named-nodes semantics use jumpHash
    over an index into your node list."""
    if key is None or buckets is None:
        return None
    try:
        buckets = int(buckets)
    except (TypeError, ValueError):
        return None
    if buckets <= 0:
        return None
    return hashing_fnv1a64(str(key)) % buckets


@register("apoc.hashing.fingerprint")
def hashing_fingerprint(entity, exclude=None):
    """Content fingerprint of a node/relationship/map: sha256 over the
    sorted properties (minus excluded keys) + labels/type (ref
    hashing.go Fingerprint)."""
    import hashlib as _hl

    if entity is None:
        return None
    exclude = set(exclude or [])
    props = getattr(entity, "properties", None)
    if props is None and isinstance(entity, dict):
        props = entity
    if props is None:
        # scalar/list input: hash the value itself (ref hashes %v), so
        # distinct scalars get distinct fingerprints
        blob = _json.dumps(entity, sort_keys=True, default=str)
        return _hl.sha256(blob.encode("utf-8")).hexdigest()
    payload = {k: v for k, v in dict(props).items() if k not in exclude}
    # unambiguous envelope: labels/type ride INSIDE the json, so
    # ['A|B'] vs ['A','B'] can never collide and type never clobbers labels
    envelope = {"properties": payload}
    labels = getattr(entity, "labels", None)
    if labels is not None:
        envelope["labels"] = sorted(labels)
    etype = getattr(entity, "type", None)
    if isinstance(etype, str):
        envelope["type"] = etype
    blob = _json.dumps(envelope, sort_keys=True, default=str)
    return _hl.sha256(blob.encode("utf-8")).hexdigest()


# ---------------------------------------------------------------------------
# apoc.node./rel./label./any. gaps (ref: apoc/node/node.go, rel/rel.go,
# label/label.go, any/any.go — pure accessors over bound entities)
# ---------------------------------------------------------------------------


@register("apoc.node.id")
def node_id(n):
    return None if n is None else getattr(n, "id", None)


@register("apoc.node.labels")
def node_labels(n):
    return None if n is None else list(getattr(n, "labels", []) or [])


@register("apoc.node.hasLabel")
def node_has_label(n, label):
    if n is None or label is None:
        return None
    return str(label) in (getattr(n, "labels", []) or [])


@register("apoc.node.hasLabels")
def node_has_labels(n, labels):
    if n is None or labels is None:
        return None
    if isinstance(labels, str):
        labels = [labels]  # a bare string is ONE label, not a char list
    have = set(getattr(n, "labels", []) or [])
    return all(str(l) in have for l in labels)


@register("apoc.rel.id")
def rel_id(e):
    return None if e is None else getattr(e, "id", None)


@register("apoc.rel.startNode")
def rel_start(ex, e):
    """Resolves the NODE (not its id), like the builtin startNode() and
    the reference's Storage.GetNode path (rel.go StartNode)."""
    if e is None:
        return None
    if ex is None:
        raise ValueError("apoc.rel.startNode requires executor context")
    return ex.get_node_or_none(getattr(e, "start_node", None))


rel_start.needs_executor = True


@register("apoc.rel.endNode")
def rel_end(ex, e):
    if e is None:
        return None
    if ex is None:
        raise ValueError("apoc.rel.endNode requires executor context")
    return ex.get_node_or_none(getattr(e, "end_node", None))


rel_end.needs_executor = True


@register("apoc.rel.isType")
def rel_is_type(e, rel_type):
    if e is None or rel_type is None:
        return None
    return getattr(e, "type", None) == str(rel_type)


@register("apoc.rel.isLoop")
def rel_is_loop(e):
    from nornicdb_tpu.storage.types import Edge as _Edge

    if e is None:
        return None
    if not isinstance(e, _Edge):
        return None  # not a relationship: no sentinel-equality surprises
    return e.start_node == e.end_node


from nornicdb_tpu.storage.types import Edge as _EdgeT  # noqa: E402
from nornicdb_tpu.storage.types import Node as _NodeT  # noqa: E402


# reference registers these under apoc.util.* (apoc.go:482-484); the
# any.* spellings stay as aliases for symmetry with any.properties
@register("apoc.util.isNode")
@register("apoc.any.isNode")
def any_is_node(v):
    return isinstance(v, _NodeT)


@register("apoc.util.isRelationship")
@register("apoc.any.isRelationship")
def any_is_rel(v):
    return isinstance(v, _EdgeT)


@register("apoc.util.isPath")
@register("apoc.any.isPath")
def any_is_path(v):
    return isinstance(v, dict) and bool(v.get("__path__"))
