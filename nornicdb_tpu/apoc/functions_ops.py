"""APOC operational function categories: load / log / lock / warmup /
trigger / periodic / import / export / refactor.

Behavioral reference: /root/reference/apoc/apoc.go registerAllFunctions +
apoc/{load,log,lock,warmup,trigger,periodic,import,export,refactor}/.
Notes on fidelity:

- load: local-file and data-string loaders are real. The reference's
  external connectors (jdbc/kafka/s3/gcs/azure/redis/elasticsearch/ldap/
  arrow/avro/parquet/rest/graphql/driver) are placeholders that return
  empty results (load.go:299 Jdbc, :405 S3, :435 Kafka, ...); this build
  mirrors that observable behavior exactly and says so per-function.
- lock: a real in-process lock registry (the reference's lock.go is also
  process-local bookkeeping over the embedded store).
- log: a real bounded in-memory log ring with levels + search/tail.
- refactor: function forms of the refactor procedures, executed through
  the live storage engine.
"""

from __future__ import annotations

import csv as _csvmod
import io
import json as _json
import os
import re
import threading
import time
import uuid as _uuid
from typing import Any, Optional

from nornicdb_tpu.apoc.functions_graph import _edge, _graph_fn, _node
from nornicdb_tpu.apoc.registry import register
from nornicdb_tpu.errors import NornicError, NotFoundError
from nornicdb_tpu.storage.types import Edge, Node

# =============================================================== apoc.load


def _gated_path(path) -> str:
    """Shared import gate for every apoc.load.* local read: explicit
    operator opt-in, confinable via NORNICDB_IMPORT_DIR (config.py)."""
    from nornicdb_tpu.config import resolve_import_url

    p = str(path)
    if p.startswith(("http://", "https://", "s3://", "gs://")):
        raise NornicError(
            "remote URLs are not loadable in this build (zero-egress); "
            "use a local path"
        )
    try:
        return resolve_import_url(p)
    except PermissionError as e:
        raise NornicError(str(e)) from None


def _read_local(path: str) -> str:
    with open(_gated_path(path), "r", encoding="utf-8") as f:
        return f.read()


def _csv_rows(text: str, sep=",") -> list[dict]:
    reader = _csvmod.DictReader(io.StringIO(text), delimiter=sep)
    return [dict(r) for r in reader]


@register("apoc.load.csv")
def load_csv(path, config=None):
    sep = (config or {}).get("sep", ",")
    return _csv_rows(_read_local(path), sep)


@register("apoc.load.csvStream")
def load_csv_stream(data, config=None):
    """CSV from a data string (stream form)."""
    sep = (config or {}).get("sep", ",")
    return _csv_rows(str(data), sep)


@register("apoc.load.jsonStream")
def load_json_stream(data):
    """One JSON document per line (NDJSON)."""
    out = []
    for line in str(data).splitlines():
        line = line.strip()
        if line:
            out.append(_json.loads(line))
    return out


@register("apoc.load.jsonParams")
def load_json_params(path_or_data, params=None):
    """Load JSON after ${param} substitution. Accepts a file path (import-
    gated) or inline JSON data; a gated path must surface the gate error,
    not fall through to 'parse the path as JSON'."""
    data = str(path_or_data)
    # inline sniff covers every JSON start token — objects/arrays/strings by
    # prefix, bare scalars (123, -4.5, true, null) by an actual parse so a
    # digit-leading *path* ("2024/data.json" fails json.loads) still routes
    # to the gated file read
    looks_inline = data.lstrip()[:1] in ("{", "[", '"')
    if not looks_inline:
        try:
            _json.loads(data)
            looks_inline = True
        except ValueError:
            pass
    if looks_inline:
        text = data
    else:
        try:
            text = _read_local(data)
        except OSError:
            text = data  # not a readable file: treat as inline data
    for k, v in (params or {}).items():
        text = text.replace("${" + str(k) + "}", str(v))
    return _json.loads(text)


@register("apoc.load.jsonSchema")
def load_json_schema(data):
    """Infer a {key: type} schema from a JSON document."""
    obj = _json.loads(data) if isinstance(data, str) else data

    def kind(v):
        if v is None:
            return "null"
        if isinstance(v, bool):
            return "boolean"
        if isinstance(v, int):
            return "integer"
        if isinstance(v, float):
            return "number"
        if isinstance(v, str):
            return "string"
        if isinstance(v, list):
            return "array"
        return "object"

    if isinstance(obj, dict):
        return {k: kind(v) for k, v in obj.items()}
    return kind(obj)


@register("apoc.load.xml")
def load_xml(path):
    from nornicdb_tpu.apoc.functions_ext import _xml_to_map
    import xml.etree.ElementTree as _ET

    return _xml_to_map(_ET.fromstring(_read_local(path)))


@register("apoc.load.xmlSimple")
def load_xml_simple(data):
    from nornicdb_tpu.apoc.functions_ext import _xml_to_map
    import xml.etree.ElementTree as _ET

    return _xml_to_map(_ET.fromstring(str(data)))


@register("apoc.load.html")
def load_html(data, selectors=None):
    """Extract title/meta/links/text from an HTML string (the reference's
    Html is likewise a lightweight extractor, load.go)."""
    html = str(data)
    title = re.search(r"<title[^>]*>(.*?)</title>", html, re.S | re.I)
    metas = {
        m.group(1): m.group(2)
        for m in re.finditer(
            r'<meta\s+name="([^"]+)"\s+content="([^"]*)"', html, re.I)
    }
    links = re.findall(r'href="([^"]+)"', html, re.I)
    text = re.sub(r"<[^>]+>", " ", re.sub(r"<(script|style).*?</\1>", " ",
                                          html, flags=re.S | re.I))
    return {
        "title": title.group(1).strip() if title else None,
        "meta": metas,
        "links": links,
        "text": " ".join(text.split()),
    }


@register("apoc.load.directory")
def load_directory(path, pattern="*"):
    import fnmatch

    return sorted(
        f for f in os.listdir(_gated_path(path))
        if fnmatch.fnmatch(f, str(pattern))
    )


@register("apoc.load.directoryTree")
def load_directory_tree(path):
    out = []
    for root, _dirs, files in os.walk(_gated_path(path)):
        for f in sorted(files):
            out.append(os.path.join(root, f))
    return sorted(out)


@register("apoc.load.binary")
def load_binary(path):
    """Local file bytes as base64 (import-gated)."""
    import base64

    with open(_gated_path(path), "rb") as f:
        return base64.b64encode(f.read()).decode()


@register("apoc.load.stream")
def load_stream(path):
    return _read_local(path).splitlines()


def _placeholder(name, value):
    """Mirror the reference's placeholder connectors exactly (load.go:299
    Jdbc -> [], :405 S3 -> empty, :435 Kafka -> [] ...)."""

    def fn(*args, **kwargs):
        return value() if callable(value) else value

    fn.__doc__ = (
        f"{name}: external connector; returns the same empty result as the "
        "reference's placeholder implementation (apoc/load/load.go)."
    )
    return fn


register("apoc.load.jdbc")(_placeholder("apoc.load.jdbc", list))
register("apoc.load.jdbcUpdate")(_placeholder("apoc.load.jdbcUpdate", 0))
register("apoc.load.kafka")(_placeholder("apoc.load.kafka", list))
register("apoc.load.redis")(_placeholder("apoc.load.redis", None))
register("apoc.load.s3")(_placeholder("apoc.load.s3", ""))
register("apoc.load.gcs")(_placeholder("apoc.load.gcs", ""))
register("apoc.load.azure")(_placeholder("apoc.load.azure", ""))
register("apoc.load.elasticsearch")(
    _placeholder("apoc.load.elasticsearch", list))
register("apoc.load.ldap")(_placeholder("apoc.load.ldap", list))
register("apoc.load.arrow")(_placeholder("apoc.load.arrow", list))
register("apoc.load.avro")(_placeholder("apoc.load.avro", list))
register("apoc.load.parquet")(_placeholder("apoc.load.parquet", list))
register("apoc.load.rest")(_placeholder("apoc.load.rest", dict))
register("apoc.load.graphql")(_placeholder("apoc.load.graphql", dict))


@register("apoc.load.driver")
def load_driver(driver_name, url=None, query=None):
    raise NornicError(f"driver not implemented: {driver_name}")


# ================================================================ apoc.log
_LOG_LOCK = threading.Lock()
_LOG_RING: list[dict] = []
_LOG_MAX = 10_000
_LOG_LEVELS = ("TRACE", "DEBUG", "INFO", "WARN", "ERROR")
_log_state = {"level": "INFO"}
_log_timers: dict[str, float] = {}


def _log_emit(level: str, message, category="general") -> dict:
    entry = {
        "ts": int(time.time() * 1000),
        "level": level,
        "message": str(message),
        "category": category,
    }
    with _LOG_LOCK:
        if _LOG_LEVELS.index(level) >= _LOG_LEVELS.index(_log_state["level"]):
            _LOG_RING.append(entry)
            del _LOG_RING[:-_LOG_MAX]
    return entry


for _lvl in ("trace", "debug", "info", "warn", "error"):
    register(f"apoc.log.{_lvl}")(
        (lambda lvl: lambda message: _log_emit(lvl, message))(_lvl.upper())
    )


@register("apoc.log.custom")
def log_custom(level, message, category="custom"):
    lvl = str(level).upper()
    if lvl not in _LOG_LEVELS:
        raise NornicError(f"unknown log level {level!r}")
    return _log_emit(lvl, message, category)


@register("apoc.log.audit")
def log_audit(message):
    return _log_emit("INFO", message, "audit")


@register("apoc.log.security")
def log_security(message):
    return _log_emit("WARN", message, "security")


@register("apoc.log.query")
def log_query(query, duration_ms=0):
    return _log_emit("INFO", f"query={query} duration={duration_ms}ms",
                     "query")


@register("apoc.log.result")
def log_result(result):
    return _log_emit("INFO", _json.dumps(result, default=str)[:500], "result")


@register("apoc.log.progress")
def log_progress(done, total, label=""):
    pct = (100.0 * float(done) / float(total)) if total else 0.0
    return _log_emit("INFO", f"{label} {done}/{total} ({pct:.1f}%)",
                     "progress")


@register("apoc.log.setLevel")
def log_set_level(level):
    lvl = str(level).upper()
    if lvl not in _LOG_LEVELS:
        raise NornicError(f"unknown log level {level!r}")
    with _LOG_LOCK:
        _log_state["level"] = lvl
    return lvl


@register("apoc.log.getLevel")
def log_get_level():
    return _log_state["level"]


@register("apoc.log.clear")
def log_clear():
    with _LOG_LOCK:
        n = len(_LOG_RING)
        _LOG_RING.clear()
    return n


@register("apoc.log.rotate")
def log_rotate(keep=1000):
    with _LOG_LOCK:
        n = len(_LOG_RING)
        del _LOG_RING[:-int(keep)]
        return n - len(_LOG_RING)


@register("apoc.log.tail")
def log_tail(n=10):
    with _LOG_LOCK:
        return list(_LOG_RING[-int(n):])


@register("apoc.log.stream")
def log_stream(since_ts=0):
    with _LOG_LOCK:
        return [e for e in _LOG_RING if e["ts"] >= int(since_ts)]


@register("apoc.log.search")
def log_search(pattern):
    pat = re.compile(str(pattern), re.IGNORECASE)
    with _LOG_LOCK:
        return [e for e in _LOG_RING if pat.search(e["message"])]


@register("apoc.log.stats")
def log_stats():
    with _LOG_LOCK:
        counts: dict[str, int] = {}
        for e in _LOG_RING:
            counts[e["level"]] = counts.get(e["level"], 0) + 1
        return {"total": len(_LOG_RING), "byLevel": counts,
                "level": _log_state["level"]}


@register("apoc.log.format")
def log_format(entry):
    e = entry or {}
    return f"[{e.get('ts')}] {e.get('level')} {e.get('category')}: " \
           f"{e.get('message')}"


@register("apoc.log.timer")
def log_timer(name, stop=False):
    """Start (or stop and report) a named timer; returns elapsed ms."""
    now = time.perf_counter()
    if not stop:
        with _LOG_LOCK:
            _log_timers[str(name)] = now
        return 0.0
    with _LOG_LOCK:
        t0 = _log_timers.pop(str(name), now)
    ms = (now - t0) * 1000.0
    _log_emit("INFO", f"timer {name}: {ms:.2f}ms", "timer")
    return ms


@register("apoc.log.memory")
def log_memory():
    import resource

    usage = resource.getrusage(resource.RUSAGE_SELF)
    return {"maxRssKb": usage.ru_maxrss}


@register("apoc.log.metrics")
def log_metrics():
    return {**log_stats(), "timers": sorted(_log_timers)}


@register("apoc.log.performance")
def log_performance(label, ms):
    return _log_emit("INFO", f"{label}: {float(ms):.2f}ms", "performance")


@register("apoc.log.toFile")
def log_to_file(path):
    with _LOG_LOCK:
        lines = [log_format(e) for e in _LOG_RING]
    with open(str(path), "w", encoding="utf-8") as f:
        f.write("\n".join(lines))
    return len(lines)


# =============================================================== apoc.lock
# Real in-process registry (the reference's lock.go is the same idea over
# the embedded store: write/read lock bookkeeping per entity id).
_locks_lock = threading.Lock()
_locks: dict[str, dict] = {}  # id -> {mode, count, priority}


def _ent_id(v) -> str:
    if isinstance(v, (Node, Edge)):
        return v.id
    return str(v)


def _acquire(ids, mode) -> int:
    n = 0
    with _locks_lock:
        for i in ids:
            cur = _locks.get(i)
            if cur is None:
                _locks[i] = {"mode": mode, "count": 1, "priority": 0}
                n += 1
            elif cur["mode"] == "read" and mode == "read":
                cur["count"] += 1
                n += 1
            elif cur["mode"] == mode == "write":
                cur["count"] += 1  # re-entrant
                n += 1
    return n


def _release(ids) -> int:
    n = 0
    with _locks_lock:
        for i in ids:
            cur = _locks.get(i)
            if cur is not None:
                cur["count"] -= 1
                if cur["count"] <= 0:
                    _locks.pop(i, None)
                n += 1
    return n


@register("apoc.lock.nodes")
@register("apoc.lock.batch")
def lock_nodes(nodes):
    return _acquire([_ent_id(v) for v in (nodes or [])], "write")


@register("apoc.lock.readNodes")
def lock_read_nodes(nodes):
    return _acquire([_ent_id(v) for v in (nodes or [])], "read")


@register("apoc.lock.unlockNodes")
@register("apoc.lock.unlockBatch")
def unlock_nodes(nodes):
    return _release([_ent_id(v) for v in (nodes or [])])


@register("apoc.lock.relationships")
def lock_relationships(rels):
    return _acquire([_ent_id(v) for v in (rels or [])], "write")


@register("apoc.lock.readRelationships")
def lock_read_relationships(rels):
    return _acquire([_ent_id(v) for v in (rels or [])], "read")


@register("apoc.lock.unlockRelationships")
def unlock_relationships(rels):
    return _release([_ent_id(v) for v in (rels or [])])


@register("apoc.lock.all")
def lock_all(nodes, rels):
    return lock_nodes(nodes) + lock_relationships(rels)


@register("apoc.lock.unlockAll")
def unlock_all():
    with _locks_lock:
        n = len(_locks)
        _locks.clear()
    return n


@register("apoc.lock.tryLock")
def try_lock(entity):
    i = _ent_id(entity)
    with _locks_lock:
        if i in _locks:
            return False
        _locks[i] = {"mode": "write", "count": 1, "priority": 0}
        return True


@register("apoc.lock.isLocked")
def is_locked(entity):
    with _locks_lock:
        return _ent_id(entity) in _locks


@register("apoc.lock.waitFor")
def wait_for(entity, timeout_ms=1000):
    deadline = time.time() + float(timeout_ms) / 1000.0
    i = _ent_id(entity)
    while time.time() < deadline:
        if try_lock(i):
            return True
        time.sleep(0.005)
    return False


@register("apoc.lock.withLock")
def with_lock(entity, value):
    """Acquire, return value, release (value-form of the reference's
    callback shape, which Cypher cannot pass)."""
    i = _ent_id(entity)
    _acquire([i], "write")
    try:
        return value
    finally:
        _release([i])


@register("apoc.lock.withReadLock")
def with_read_lock(entity, value):
    i = _ent_id(entity)
    _acquire([i], "read")
    try:
        return value
    finally:
        _release([i])


@register("apoc.lock.priority")
def lock_priority(entity, priority):
    with _locks_lock:
        cur = _locks.get(_ent_id(entity))
        if cur is None:
            return False
        cur["priority"] = int(priority)
        return True


@register("apoc.lock.stats")
def lock_stats():
    with _locks_lock:
        reads = sum(1 for v in _locks.values() if v["mode"] == "read")
        return {"held": len(_locks), "read": reads,
                "write": len(_locks) - reads}


@register("apoc.lock.clear")
def lock_clear():
    return unlock_all()


@register("apoc.lock.detectDeadlock")
def detect_deadlock():
    """Single-process registry: no wait-for graph, so never a deadlock
    (same invariant as the reference's embedded-store locks)."""
    return False


# ============================================================ apoc.warmup
_warmup_lock = threading.Lock()
_warmup_state = {"last": None}


@_graph_fn("apoc.warmup.nodes")
def warmup_nodes(ex):
    n = sum(1 for _ in ex.storage.all_nodes())
    return {"nodesLoaded": n}


@_graph_fn("apoc.warmup.relationships")
def warmup_relationships(ex):
    n = sum(1 for _ in ex.storage.all_edges())
    return {"relsLoaded": n}


@_graph_fn("apoc.warmup.properties")
def warmup_properties(ex):
    n = sum(len(x.properties) for x in ex.storage.all_nodes())
    n += sum(len(x.properties) for x in ex.storage.all_edges())
    return {"propertiesLoaded": n}


@_graph_fn("apoc.warmup.indexes")
def warmup_indexes(ex):
    count = 0
    for node in ex.storage.all_nodes():
        ex.schema.index_node(node)
        count += 1
    return {"indexed": count, "indexes": len(ex.schema.list_indexes())}


@_graph_fn("apoc.warmup.cache")
def warmup_cache(ex):
    """Prime the columnar scan index for every label."""
    idx = ex._scan_index()
    labels = set()
    for n in ex.storage.all_nodes():
        labels.update(n.labels)
    warmed = 0
    if idx is not None:
        for label in labels:
            if idx._get(label) is not None:
                warmed += 1
    return {"labelsWarmed": warmed}


@_graph_fn("apoc.warmup.run")
def warmup_run(ex):
    out = {**warmup_nodes(ex), **warmup_relationships(ex),
           **warmup_properties(ex), **warmup_cache(ex)}
    with _warmup_lock:
        _warmup_state["last"] = {"ts": int(time.time() * 1000), **out}
    return out


@_graph_fn("apoc.warmup.runWithParams")
def warmup_run_with_params(ex, config=None):
    cfg = config or {}
    out = {}
    if cfg.get("nodes", True):
        out.update(warmup_nodes(ex))
    if cfg.get("relationships", True):
        out.update(warmup_relationships(ex))
    if cfg.get("properties", False):
        out.update(warmup_properties(ex))
    if cfg.get("cache", False):
        out.update(warmup_cache(ex))
    with _warmup_lock:
        _warmup_state["last"] = {"ts": int(time.time() * 1000), **out}
    return out


@_graph_fn("apoc.warmup.subgraph")
def warmup_subgraph(ex, labels):
    n = 0
    for label in labels or []:
        n += len(ex.storage.get_nodes_by_label(label))
    return {"nodesLoaded": n}


@_graph_fn("apoc.warmup.path")
def warmup_path(ex, start, max_hops=3):
    from nornicdb_tpu.apoc.functions_graph import neighbors_to_hop

    return {"nodesLoaded": len(neighbors_to_hop(ex, start, None, max_hops))}


@register("apoc.warmup.status")
def warmup_status():
    return {"lastRun": _warmup_state["last"]}


@register("apoc.warmup.progress")
def warmup_progress():
    return {"running": False, "lastRun": _warmup_state["last"]}


@register("apoc.warmup.stats")
def warmup_stats():
    return {"lastRun": _warmup_state["last"]}


@register("apoc.warmup.clear")
def warmup_clear():
    with _warmup_lock:
        _warmup_state["last"] = None
    return True


@_graph_fn("apoc.warmup.optimize")
def warmup_optimize(ex):
    return warmup_run(ex)


@register("apoc.warmup.schedule")
def warmup_schedule(interval_seconds):
    """Scheduling belongs to apoc.periodic procedures; records intent."""
    return {"scheduled": False,
            "hint": "use apoc.periodic.repeat with apoc.warmup.run"}


# =========================================================== apoc.trigger
def _trigger_mgr(ex):
    from nornicdb_tpu.apoc.triggers import manager_for

    return manager_for(ex)


@_graph_fn("apoc.trigger.add")
@_graph_fn("apoc.trigger.install")
def trigger_add(ex, name, statement, config=None):
    t = _trigger_mgr(ex).add(str(name), str(statement), dict(config or {}))
    return {"name": t.name, "paused": t.paused}


@_graph_fn("apoc.trigger.remove")
@_graph_fn("apoc.trigger.drop")
def trigger_remove(ex, name):
    return _trigger_mgr(ex).remove(str(name))


@_graph_fn("apoc.trigger.removeAll")
def trigger_remove_all(ex):
    return _trigger_mgr(ex).remove_all()


@_graph_fn("apoc.trigger.list")
def trigger_list(ex):
    return [{"name": t.name, "statement": t.statement, "paused": t.paused}
            for t in _trigger_mgr(ex).list()]


@_graph_fn("apoc.trigger.show")
def trigger_show(ex, name):
    t = _trigger_mgr(ex).get(str(name))
    if t is None:
        return None
    return {"name": t.name, "statement": t.statement, "paused": t.paused,
            "config": dict(t.selector)}


@_graph_fn("apoc.trigger.pause")
@_graph_fn("apoc.trigger.disable")
def trigger_pause(ex, name):
    t = _trigger_mgr(ex).pause(str(name), True)
    return t is not None


@_graph_fn("apoc.trigger.resume")
@_graph_fn("apoc.trigger.enable")
def trigger_resume(ex, name):
    t = _trigger_mgr(ex).pause(str(name), False)
    return t is not None


@_graph_fn("apoc.trigger.isEnabled")
def trigger_is_enabled(ex, name):
    t = _trigger_mgr(ex).get(str(name))
    return t is not None and not t.paused


@_graph_fn("apoc.trigger.count")
def trigger_count(ex):
    return len(_trigger_mgr(ex).list())


@_graph_fn("apoc.trigger.stats")
def trigger_stats(ex):
    ts = _trigger_mgr(ex).list()
    return {"total": len(ts), "paused": sum(1 for t in ts if t.paused)}


@_graph_fn("apoc.trigger.export")
def trigger_export(ex):
    return [{"name": t.name, "statement": t.statement,
             "config": dict(t.selector), "paused": t.paused}
            for t in _trigger_mgr(ex).list()]


@_graph_fn("apoc.trigger.import")
def trigger_import(ex, triggers):
    mgr = _trigger_mgr(ex)
    n = 0
    for spec in triggers or []:
        t = mgr.add(str(spec["name"]), str(spec["statement"]),
                    dict(spec.get("config") or {}))
        if spec.get("paused"):
            mgr.pause(t.name, True)
        n += 1
    return n


def _selector_trigger(ex, name, statement, selector):
    t = _trigger_mgr(ex).add(str(name), str(statement), selector)
    return {"name": t.name, "config": selector}


@_graph_fn("apoc.trigger.nodeByLabel")
def trigger_node_by_label(ex, label, statement):
    return _selector_trigger(ex, f"label-{label}", statement,
                             {"selector": {"label": str(label)}})


@_graph_fn("apoc.trigger.relationshipByType")
def trigger_rel_by_type(ex, rel_type, statement):
    return _selector_trigger(ex, f"type-{rel_type}", statement,
                             {"selector": {"type": str(rel_type)}})


@_graph_fn("apoc.trigger.onCreate")
def trigger_on_create(ex, name, statement):
    return _selector_trigger(ex, name, statement, {"event": "create"})


@_graph_fn("apoc.trigger.onUpdate")
def trigger_on_update(ex, name, statement):
    return _selector_trigger(ex, name, statement, {"event": "update"})


@_graph_fn("apoc.trigger.onDelete")
def trigger_on_delete(ex, name, statement):
    return _selector_trigger(ex, name, statement, {"event": "delete"})


@_graph_fn("apoc.trigger.before")
def trigger_before(ex, name, statement):
    return _selector_trigger(ex, name, statement, {"phase": "before"})


@_graph_fn("apoc.trigger.after")
def trigger_after(ex, name, statement):
    return _selector_trigger(ex, name, statement, {"phase": "after"})


@_graph_fn("apoc.trigger.afterAsync")
def trigger_after_async(ex, name, statement):
    return _selector_trigger(ex, name, statement, {"phase": "afterAsync"})


# ========================================================== apoc.periodic
@_graph_fn("apoc.periodic.iterate")
def periodic_iterate_fn(ex, outer, inner, config=None):
    """Function form of the periodic.iterate procedure: batches the outer
    query's rows through the inner statement; returns {batches, total}."""
    cfg = config or {}
    batch_size = int(cfg.get("batchSize", 1000))
    res = ex.execute(str(outer))
    rows = res.rows_as_dicts()
    total = 0
    batches = 0
    for i in range(0, len(rows), batch_size):
        for row in rows[i:i + batch_size]:
            ex.execute(str(inner), row)
            total += 1
        batches += 1
    return {"batches": batches, "total": total}


@_graph_fn("apoc.periodic.commit")
def periodic_commit_fn(ex, statement, params=None):
    """Re-run until the statement reports no more updates (LIMIT loops)."""
    total = 0
    for _ in range(10_000):
        res = ex.execute(str(statement), params or {})
        n = 0
        if res.rows and isinstance(res.rows[0][0], (int, float)):
            n = int(res.rows[0][0])
        else:
            st = res.stats
            n = (st.nodes_created + st.nodes_deleted + st.properties_set
                 if st else 0)
        total += n
        if n == 0:
            break
    return {"updates": total}


@_graph_fn("apoc.periodic.submit")
def periodic_submit(ex, name, statement):
    """Run once, record as a completed job (the reference's Submit also
    executes immediately in the background)."""
    ex.execute(str(statement))
    with _jobs_lock:
        jobs = _jobs_state.setdefault(id(ex), {})
        jobs[str(name)] = {"name": str(name), "statement": str(statement),
                           "done": True, "cancelled": False}
        return jobs[str(name)]


_jobs_lock = threading.Lock()
_jobs_state: dict[int, dict] = {}


@_graph_fn("apoc.periodic.repeat")
@_graph_fn("apoc.periodic.schedule")
def periodic_repeat(ex, name, statement, interval_s=60):
    """Records the schedule; execution rides the DB's decay/maintenance
    timer rather than an unmanaged thread."""
    with _jobs_lock:
        jobs = _jobs_state.setdefault(id(ex), {})
        jobs[str(name)] = {"name": str(name), "statement": str(statement),
                           "intervalSeconds": int(interval_s), "done": False,
                           "cancelled": False}
        return jobs[str(name)]


@_graph_fn("apoc.periodic.cancel")
def periodic_cancel(ex, name):
    jobs = _jobs_state.get(id(ex), {})
    job = jobs.get(str(name))
    if job is None:
        return False
    job["cancelled"] = True
    return True


@_graph_fn("apoc.periodic.list")
def periodic_list(ex):
    return [j for j in _jobs_state.get(id(ex), {}).values()
            if not j.get("cancelled")]


@_graph_fn("apoc.periodic.countdown")
def periodic_countdown(ex, name, statement, count):
    """Run `statement` `count` times now (bounded synchronous form)."""
    n = 0
    for _ in range(int(count)):
        ex.execute(str(statement))
        n += 1
    return {"name": str(name), "executions": n}


@_graph_fn("apoc.periodic.truncate")
def periodic_truncate(ex, config=None):
    """Delete everything in batches (ref periodic.go Truncate)."""
    deleted = 0
    for n in list(ex.storage.all_nodes()):
        ex.storage.delete_node(n.id)
        deleted += 1
    return {"nodesDeleted": deleted}


@_graph_fn("apoc.periodic.rock")
def periodic_rock(ex, name, config=None):
    """Rock'n'roll alias of iterate (the reference keeps the joke name)."""
    cfg = config or {}
    return periodic_iterate_fn(
        ex, cfg.get("outer", "MATCH (n) RETURN n LIMIT 0"),
        cfg.get("inner", "RETURN 1"), cfg)


# ============================================================ apoc.import
@register("apoc.import.parseCsvLine")
def import_parse_csv_line(line, sep=","):
    reader = _csvmod.reader(io.StringIO(str(line)), delimiter=str(sep))
    for row in reader:
        return row
    return []


@register("apoc.import.parseJsonLine")
def import_parse_json_line(line):
    return _json.loads(str(line))


@register("apoc.import.csvData")
def import_csv_data(data, config=None):
    return _csv_rows(str(data), (config or {}).get("sep", ","))


@register("apoc.import.jsonData")
def import_json_data(data):
    return load_json_stream(data) if "\n" in str(data).strip() \
        else _json.loads(str(data))


@register("apoc.import.convertType")
def import_convert_type(value, type_name):
    t = str(type_name).lower()
    if value is None:
        return None
    if t in ("int", "integer", "long"):
        return int(float(value))
    if t in ("float", "double"):
        return float(value)
    if t in ("bool", "boolean"):
        return str(value).lower() in ("1", "true", "yes")
    if t == "string":
        return str(value)
    if t == "list":
        return list(value) if isinstance(value, (list, tuple)) \
            else [v.strip() for v in str(value).split(";")]
    raise NornicError(f"unknown type {type_name!r}")


@register("apoc.import.validateSchema")
def import_validate_schema(data, schema):
    """Rows must carry every schema key with the right JSON type."""
    rows = data if isinstance(data, list) else [data]
    schema = schema or {}

    def ok(v, t):
        return {
            "string": isinstance(v, str),
            "integer": isinstance(v, int) and not isinstance(v, bool),
            "number": isinstance(v, (int, float)) and not isinstance(v, bool),
            "boolean": isinstance(v, bool),
            "array": isinstance(v, list),
            "object": isinstance(v, dict),
        }.get(str(t).lower(), True)

    bad = []
    for i, row in enumerate(rows):
        for k, t in schema.items():
            if k not in row or not ok(row[k], t):
                bad.append({"row": i, "key": k})
    return {"valid": not bad, "violations": bad}


@_graph_fn("apoc.import.transform")
def import_transform(ex, data, expr):
    """Map rows through a Cypher expression over `row`."""
    from nornicdb_tpu.apoc.functions_graph import _eval_pred

    return [_eval_pred(ex, str(expr), {"row": row}) for row in (data or [])]


@_graph_fn("apoc.import.filter")
def import_filter(ex, data, predicate):
    from nornicdb_tpu.apoc.functions_graph import _eval_pred

    return [row for row in (data or [])
            if _eval_pred(ex, str(predicate), {"row": row}) is True]


@register("apoc.import.merge")
def import_merge(d1, d2):
    return list(d1 or []) + list(d2 or [])


@register("apoc.import.batch")
def import_batch(items, batch_size):
    size = max(int(batch_size), 1)
    items = list(items or [])
    return [items[i:i + size] for i in range(0, len(items), size)]


@register("apoc.import.file")
def import_file(path):
    return _read_local(path)


@register("apoc.import.stream")
def import_stream(data):
    return str(data).splitlines()


@register("apoc.import.url")
def import_url(url):
    raise NornicError(
        "remote URLs are not loadable in this build (zero-egress); "
        "use apoc.import.file with a local path"
    )


@_graph_fn("apoc.import.cypher")
def import_cypher(ex, path):
    from nornicdb_tpu.apoc.functions_graph2 import cypher_run_file

    return cypher_run_file(ex, path)


@_graph_fn("apoc.import.cypherData")
def import_cypher_data(ex, queries):
    out = []
    items = queries if isinstance(queries, list) else str(queries).split(";")
    for q in items:
        q = str(q).strip()
        if q:
            out.append(ex.execute(q).rows_as_dicts())
    return out


@_graph_fn("apoc.import.graphMLData")
def import_graphml_data(ex, xml_string):
    """Create nodes/edges from a GraphML string (data form of the
    apoc.import.graphml procedure)."""
    import tempfile

    with tempfile.NamedTemporaryFile(
        "w", suffix=".graphml", delete=False, encoding="utf-8"
    ) as f:
        f.write(str(xml_string))
        tmp = f.name
    try:
        from nornicdb_tpu.apoc.export_import import import_graphml

        return import_graphml(ex, [tmp], {})
    finally:
        os.unlink(tmp)


# ========================================================== apoc.export
def _graph_data(ex, nodes=None, rels=None):
    if nodes is None:
        nodes = list(ex.storage.all_nodes())
    if rels is None:
        rels = list(ex.storage.all_edges())
    return nodes, rels


@_graph_fn("apoc.export.jsonData")
def export_json_data_fn(ex, nodes=None, rels=None):
    from nornicdb_tpu.apoc.export_import import _json_payload

    return _json_payload(*_graph_data(ex, nodes, rels))


@_graph_fn("apoc.export.csvData")
def export_csv_data_fn(ex, nodes=None, rels=None):
    from nornicdb_tpu.apoc.export_import import _csv_payload

    return _csv_payload(*_graph_data(ex, nodes, rels))


@_graph_fn("apoc.export.cypherData")
def export_cypher_data_fn(ex, nodes=None, rels=None):
    from nornicdb_tpu.apoc.export_import import _cypher_payload

    return _cypher_payload(*_graph_data(ex, nodes, rels))


@_graph_fn("apoc.export.graphMLData")
def export_graphml_data_fn(ex, nodes=None, rels=None):
    from nornicdb_tpu.apoc.export_import import _graphml_payload

    return _graphml_payload(*_graph_data(ex, nodes, rels))


def _export_file(ex, path, payload_fn):
    from nornicdb_tpu.apoc.export_import import _export_allowed

    if not _export_allowed():
        raise NornicError("export is disabled (NORNICDB_APOC_EXPORT_ENABLED)")
    payload = payload_fn()
    with open(str(path), "w", encoding="utf-8") as f:
        f.write(payload)
    return {"file": str(path), "bytes": len(payload)}


@_graph_fn("apoc.export.json")
@_graph_fn("apoc.export.jsonAll")
def export_json_file(ex, path):
    return _export_file(ex, path, lambda: export_json_data_fn(ex))


@_graph_fn("apoc.export.csv")
@_graph_fn("apoc.export.csvAll")
def export_csv_file(ex, path):
    return _export_file(ex, path, lambda: export_csv_data_fn(ex))


@_graph_fn("apoc.export.cypher")
@_graph_fn("apoc.export.cypherAll")
def export_cypher_file(ex, path):
    return _export_file(ex, path, lambda: export_cypher_data_fn(ex))


@_graph_fn("apoc.export.graphML")
@_graph_fn("apoc.export.graphMLAll")
def export_graphml_file(ex, path):
    return _export_file(ex, path, lambda: export_graphml_data_fn(ex))


@register("apoc.export.toString")
def export_to_string(data):
    if isinstance(data, str):
        return data
    return _json.dumps(data, default=str, sort_keys=True)


@register("apoc.export.toFile")
def export_to_file(data, path):
    from nornicdb_tpu.apoc.export_import import _export_allowed

    if not _export_allowed():
        raise NornicError("export is disabled (NORNICDB_APOC_EXPORT_ENABLED)")
    payload = export_to_string(data)
    with open(str(path), "w", encoding="utf-8") as f:
        f.write(payload)
    return {"file": str(path), "bytes": len(payload)}


# ========================================================= apoc.refactor
@_graph_fn("apoc.refactor.renameLabel")
def refactor_rename_label(ex, old, new):
    n = 0
    for node in ex.storage.get_nodes_by_label(str(old)):
        node.labels = [str(new) if l == str(old) else l for l in node.labels]
        ex.storage.update_node(node)
        n += 1
    return n


@_graph_fn("apoc.refactor.renameType")
@_graph_fn("apoc.refactor.changeType")
def refactor_rename_type(ex, old, new):
    n = 0
    for e in list(ex.storage.get_edges_by_type(str(old))):
        ex.storage.delete_edge(e.id)
        ex.storage.create_edge(Edge(
            id=e.id, start_node=e.start_node, end_node=e.end_node,
            type=str(new), properties=dict(e.properties)))
        n += 1
    return n


@_graph_fn("apoc.refactor.renameProperty")
def refactor_rename_property(ex, old, new):
    n = 0
    for node in ex.storage.all_nodes():
        if str(old) in node.properties:
            node.properties[str(new)] = node.properties.pop(str(old))
            ex.storage.update_node(node)
            n += 1
    return n


@_graph_fn("apoc.refactor.setType")
def refactor_set_type(ex, rel, new_type):
    r = _edge(ex, rel)
    ex.storage.delete_edge(r.id)
    return ex.storage.create_edge(Edge(
        id=r.id, start_node=r.start_node, end_node=r.end_node,
        type=str(new_type), properties=dict(r.properties)))


@_graph_fn("apoc.refactor.invertRelationship")
def refactor_invert(ex, rel):
    from nornicdb_tpu.apoc.functions_graph import rel_reverse

    return rel_reverse(ex, rel)


@_graph_fn("apoc.refactor.redirectRelationship")
def refactor_redirect(ex, rel, new_target):
    r = _edge(ex, rel)
    t = _node(ex, new_target)
    ex.storage.delete_edge(r.id)
    return ex.storage.create_edge(Edge(
        id=r.id, start_node=r.start_node, end_node=t.id,
        type=r.type, properties=dict(r.properties)))


@_graph_fn("apoc.refactor.mergeNodes")
def refactor_merge_nodes(ex, nodes):
    from nornicdb_tpu.apoc.functions_graph import nodes_collapse

    return nodes_collapse(ex, nodes)


@_graph_fn("apoc.refactor.mergeRelationships")
def refactor_merge_rels(ex, rels):
    """Merge parallel rels into the first (properties combine, first
    wins)."""
    seq = [_edge(ex, v) for v in (rels or [])]
    if not seq:
        return None
    target = seq[0]
    for other in seq[1:]:
        for k, v in other.properties.items():
            target.properties.setdefault(k, v)
        ex.storage.delete_edge(other.id)
    return ex.storage.update_edge(target)


@_graph_fn("apoc.refactor.cloneNodes")
def refactor_clone_nodes(ex, nodes, with_rels=False):
    from nornicdb_tpu.apoc.functions_graph import node_clone

    clones = []
    mapping = {}
    for v in nodes or []:
        n = _node(ex, v)
        c = node_clone(ex, n)
        mapping[n.id] = c
        clones.append(c)
    if with_rels:
        for v in nodes or []:
            n = _node(ex, v)
            for r in ex.storage.get_outgoing_edges(n.id):
                if r.end_node in mapping:
                    ex.storage.create_edge(Edge(
                        id=f"apoc-{_uuid.uuid4()}",
                        start_node=mapping[n.id].id,
                        end_node=mapping[r.end_node].id,
                        type=r.type, properties=dict(r.properties)))
    return clones


@_graph_fn("apoc.refactor.cloneSubgraph")
def refactor_clone_subgraph(ex, nodes, rels=None):
    from nornicdb_tpu.apoc.functions_graph2 import create_clone_subgraph

    if rels is None:
        ids = {(_node(ex, v)).id for v in (nodes or [])}
        rels = [r for nid in ids for r in ex.storage.get_outgoing_edges(nid)
                if r.end_node in ids]
    return create_clone_subgraph(ex, nodes, rels)


@_graph_fn("apoc.refactor.cloneSubgraphFromPaths")
def refactor_clone_subgraph_from_paths(ex, paths):
    nodes: dict[str, str] = {}
    for p in paths or []:
        for nid in (p if isinstance(p, list) else p.get("nodes", [])):
            nid = nid.id if isinstance(nid, Node) else str(nid)
            nodes[nid] = nid
    return refactor_clone_subgraph(ex, list(nodes))


@_graph_fn("apoc.refactor.extractNode")
def refactor_extract_node(ex, rel, labels=None):
    """Turn a relationship into a node with IN/OUT rels (ref
    refactor.go ExtractNode)."""
    r = _edge(ex, rel)
    mid = ex.storage.create_node(Node(
        id=f"apoc-{_uuid.uuid4()}", labels=list(labels or [r.type]),
        properties=dict(r.properties)))
    ex.storage.delete_edge(r.id)
    ex.storage.create_edge(Edge(
        id=f"apoc-{_uuid.uuid4()}", start_node=r.start_node,
        end_node=mid.id, type="IN", properties={}))
    ex.storage.create_edge(Edge(
        id=f"apoc-{_uuid.uuid4()}", start_node=mid.id,
        end_node=r.end_node, type="OUT", properties={}))
    return mid


@_graph_fn("apoc.refactor.collapseNode")
def refactor_collapse_node(ex, node, rel_type=None):
    """Inverse of extractNode: replace a node with a direct rel between its
    single in- and out-neighbor."""
    n = _node(ex, node)
    ins = ex.storage.get_incoming_edges(n.id)
    outs = ex.storage.get_outgoing_edges(n.id)
    if len(ins) != 1 or len(outs) != 1:
        raise NornicError(
            "collapseNode requires exactly one incoming and one outgoing "
            "relationship")
    new_type = str(rel_type or f"{ins[0].type}_{outs[0].type}")
    props = {**ins[0].properties, **outs[0].properties, **n.properties}
    start, end = ins[0].start_node, outs[0].end_node
    ex.storage.delete_node(n.id)  # cascades the two rels
    return ex.storage.create_edge(Edge(
        id=f"apoc-{_uuid.uuid4()}", start_node=start, end_node=end,
        type=new_type, properties=props))


@_graph_fn("apoc.refactor.deleteAndReconnect")
def refactor_delete_and_reconnect(ex, node):
    """Delete a node, reconnecting each in-neighbor to each out-neighbor."""
    n = _node(ex, node)
    ins = ex.storage.get_incoming_edges(n.id)
    outs = ex.storage.get_outgoing_edges(n.id)
    created = []
    for i in ins:
        for o in outs:
            if i.start_node == n.id or o.end_node == n.id:
                continue
            created.append(ex.storage.create_edge(Edge(
                id=f"apoc-{_uuid.uuid4()}", start_node=i.start_node,
                end_node=o.end_node, type=o.type,
                properties=dict(o.properties))))
    ex.storage.delete_node(n.id)
    return created


@_graph_fn("apoc.refactor.normalize")
def refactor_normalize(ex, node, prop, mapping):
    """Map a property's raw values through a {raw: normalized} table."""
    n = _node(ex, node)
    v = n.properties.get(str(prop))
    if v in (mapping or {}):
        n.properties[str(prop)] = mapping[v]
        ex.storage.update_node(n)
    return n


@_graph_fn("apoc.refactor.normalizeAsBoolean")
def refactor_normalize_bool(ex, node, prop, true_values, false_values):
    n = _node(ex, node)
    v = n.properties.get(str(prop))
    if v in (true_values or []):
        n.properties[str(prop)] = True
        ex.storage.update_node(n)
    elif v in (false_values or []):
        n.properties[str(prop)] = False
        ex.storage.update_node(n)
    return n


@_graph_fn("apoc.refactor.categorizeProperty")
def refactor_categorize(ex, prop, rel_type, label):
    """Extract a property into category nodes linked by rel_type (ref
    refactor.go Categorize)."""
    cats: dict[str, Node] = {}
    n_linked = 0
    for node in list(ex.storage.all_nodes()):
        v = node.properties.get(str(prop))
        if v is None or str(label) in node.labels:
            continue
        key = str(v)
        cat = cats.get(key)
        if cat is None:
            for existing in ex.storage.get_nodes_by_label(str(label)):
                if existing.properties.get("name") == v:
                    cat = existing
                    break
            if cat is None:
                cat = ex.storage.create_node(Node(
                    id=f"apoc-{_uuid.uuid4()}", labels=[str(label)],
                    properties={"name": v}))
            cats[key] = cat
        ex.storage.create_edge(Edge(
            id=f"apoc-{_uuid.uuid4()}", start_node=node.id,
            end_node=cat.id, type=str(rel_type), properties={}))
        node.properties.pop(str(prop), None)
        ex.storage.update_node(node)
        n_linked += 1
    return {"categories": len(cats), "linked": n_linked}


@_graph_fn("apoc.refactor.denormalize")
def refactor_denormalize(ex, node, rel_type, prop):
    """Copy a neighbor's property back onto the node (inverse of
    categorizeProperty)."""
    n = _node(ex, node)
    for r in ex.storage.get_outgoing_edges(n.id):
        if r.type == str(rel_type):
            cat = ex.get_node_or_none(r.end_node)
            if cat is not None and "name" in cat.properties:
                n.properties[str(prop)] = cat.properties["name"]
                ex.storage.update_node(n)
                break
    return n


@_graph_fn("apoc.refactor.from")
def refactor_from(ex, rel, new_start):
    r = _edge(ex, rel)
    s = _node(ex, new_start)
    ex.storage.delete_edge(r.id)
    return ex.storage.create_edge(Edge(
        id=r.id, start_node=s.id, end_node=r.end_node,
        type=r.type, properties=dict(r.properties)))
